"""TCP gateway: the cluster's client-facing endpoints on real sockets.

Reference: in the reference every role endpoint is served directly by
FlowTransport on the process's listen address, and out-of-process
clients (the C binding linking NativeAPI) reach it by token
(fdbrpc/FlowTransport.actor.cpp:517 deliver; bindings/c/fdb_c.cpp is a
thin ABI over that client). Here the cluster's role endpoints live on
the in-process flow scheduler, so the gateway plays the listen-address
seam: each client-visible endpoint (proxy GRV/commit, storage
get/range/get_key) is assigned a real TCP token whose frames are
forwarded into the role's RequestStream and whose replies travel back
over the same wire format the simulator round-trips.

The describe endpoint (fixed token 1) plays MonitorLeader +
openDatabase: it serves a token-translated ServerDBInfo (proxy and
shard maps), long-polling the ClusterController through the attached
Database when the client's picture went stale — exactly the client
recovery path (fdbclient/MonitorLeader.actor.cpp, NativeAPI
getClientInfo), so an out-of-process client rides epoch recoveries the
same way in-process ones do.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .. import flow
from ..flow import error
from .tcp import TcpRequestStream, TcpTransport

DESCRIBE_TOKEN = 1

# request payload selecting the PEER describe (see _translate_peers):
# role endpoints — master version authority, resolver resolve/handoff,
# tlog commit, proxy raw-committed — for an out-of-process PEER
# (a proxy worker in tools/clusterbench.py), not a client
PEER_DESCRIBE = "peers"


async def forward_stream(stream: TcpRequestStream, ref, src) -> None:
    """Forward every frame arriving on a TCP endpoint into a sim
    NetworkRef and relay the reply — the role-endpoint serving seam
    shared by the gateway and clusterbench's worker processes."""

    async def one(req, reply):
        try:
            reply.send(await ref.get_reply(req, src))
        except flow.FdbError as e:
            reply.send_error(e)
        except Exception:  # noqa: BLE001 — a bad frame fails only itself
            reply.send_error(error("internal_error"))

    while True:
        req, reply = await stream.pop()
        flow.spawn(one(req, reply))


class TcpGateway:
    """Serve a cluster (via its client `Database` handle) over TCP.

    Two endpoint classes share the transport: CLIENT endpoints (proxy
    GRV/commit, storage reads — the original describe document) and,
    when a cluster object is attached, PEER endpoints (ISSUE 15):
    master version authority, per-resolver resolve + handoff streams,
    per-tlog commit streams and per-proxy raw-committed probes, so
    out-of-process PEER ROLES — clusterbench's proxy workers — can join
    the commit pipeline over the real wire, not just clients."""

    def __init__(self, db, host: str = "127.0.0.1", port: int = 0,
                 tls=None, protocol: bytes = None, cluster=None):
        self.db = db
        self.cluster = cluster
        self.transport = TcpTransport(host, port, tls=tls,
                                      protocol=protocol)
        self._describe = TcpRequestStream(self.transport)
        assert self._describe.token == DESCRIBE_TOKEN, \
            "describe must be the transport's first registered endpoint"
        #: (process name, sim token) -> tcp token
        self._exposed: Dict[Tuple[str, int], int] = {}
        self._actors: List[object] = []

    @property
    def port(self) -> int:
        return self.transport.port

    def start(self) -> None:
        self.transport.start()
        self._actors.append(flow.spawn(
            self._describe_loop(), name=f"gateway:{self.port}.describe"))

    def close(self) -> None:
        self.transport.close()
        for a in self._actors:
            a.cancel()
        self._actors.clear()

    # -- endpoint exposure ----------------------------------------------
    def _expose(self, ref) -> int:
        """TCP token for a sim NetworkRef, forwarding frames to it.

        Tokens are cached by (process, sim-token) identity: after a
        recovery the same describe tokens keep working for surviving
        roles, while new-epoch roles get fresh tokens in the next
        describe — dead tokens answer broken_promise, which the client
        treats as a stale-picture refresh signal.
        """
        ep = ref.endpoint
        key = (ep.process.name, ep.token)
        token = self._exposed.get(key)
        if token is None:
            stream = TcpRequestStream(self.transport)
            token = stream.token
            self._exposed[key] = token
            self._actors.append(flow.spawn(
                self._forward_loop(stream, ref),
                name=f"gateway:{self.port}.fwd.{ep.process.name}"))
        return token

    async def _forward_loop(self, stream: TcpRequestStream, ref) -> None:
        await forward_stream(stream, ref, self.db.process)

    # -- describe --------------------------------------------------------
    async def _describe_loop(self) -> None:
        while True:
            req, reply = await self._describe.pop()
            flow.spawn(self._describe_one(req, reply))

    async def _describe_one(self, min_seq, reply) -> None:
        """Request payload: the newest dbinfo seq the client has seen
        (-1 for "whatever is current"). A non-negative seq long-polls
        the CC until the broadcast picture moves past it (the client's
        post-failure refresh), mirroring Database.refresh_past. The
        string payload "peers" selects the peer-role document instead
        (requires the gateway to be attached to its cluster)."""
        try:
            if min_seq == PEER_DESCRIBE:
                reply.send(self._translate_peers())
                return
            if isinstance(min_seq, int) and min_seq >= 0:
                await self.db.refresh_past(min_seq)
            info = await self.db.info()
            reply.send(self._translate(info))
        except flow.FdbError as e:
            reply.send_error(e)
        except Exception:  # noqa: BLE001
            reply.send_error(error("internal_error"))

    def _translate_peers(self) -> dict:
        """The transaction subsystem's ROLE endpoints as TCP tokens
        (ISSUE 15): everything an out-of-process proxy needs to join
        the commit pipeline — the master's version authority, every
        current-epoch resolver's resolve + handoff streams, every
        tlog's commit stream, every in-cluster proxy's raw-committed
        probe (GRV causal confirmation), and the routing config
        (initial resolver splits — the master's version replies replay
        the whole move log onto them, so a late joiner reconstructs
        the exact current keyResolvers map — plus storage splits/tags
        and the recovery version)."""
        if self.cluster is None:
            raise error("client_invalid_operation")
        from ..server.cluster_controller import epoch_roles
        from ..server.master import initial_resolver_splits
        from ..server.proxy import Proxy
        from ..server.resolver_role import Resolver
        cc = self.cluster.cc
        info = cc.dbinfo.get()
        rec = cc._recovery
        if rec is None or rec.master is None or not info.proxies:
            # mid-recovery: peers retry exactly like stale clients
            raise error("broken_promise")

        def by_index(pairs):
            return sorted(pairs, key=lambda p: int(p[0].rsplit("-", 1)[1]))

        proxies = by_index(list(
            epoch_roles(cc.workers, info.epoch, Proxy)))
        first_proxy = proxies[0][1]
        # role-per-process deployment (tools/rolehost.py): recruitment
        # stashed addr-carrying descriptors — a worker proxy connects
        # DIRECTLY to each role process instead of through this
        # gateway's forwarders. In-process roles keep the original
        # gateway-token shape; an entry is a dict with an "addr" iff
        # the role is external (tlog entries: bare int = gateway token,
        # dict = external commit endpoint).
        ext_resolvers = getattr(rec, "peer_resolvers", None)
        if ext_resolvers is not None:
            resolvers_doc = [dict(e) for e in ext_resolvers]
        else:
            resolvers = by_index(list(
                epoch_roles(cc.workers, info.epoch, Resolver)))
            resolvers_doc = [
                {"name": rn,
                 "resolves": self._expose(r.resolves.ref()),
                 "handoffs": self._expose(r.handoffs.ref())}
                for rn, r in resolvers]
        ext_tlogs = getattr(rec, "peer_tlogs", None)
        if ext_tlogs is not None:
            tlogs_doc = [dict(e) for e in ext_tlogs]
        else:
            tlogs_doc = [self._expose(lr.commits)
                         for lr in info.logs.logs]
        n_res = len(resolvers_doc)
        return {
            "epoch": info.epoch,
            "recovery_version": info.recovery_version,
            "master": self._expose(rec.master.version_requests.ref()),
            "resolvers": resolvers_doc,
            "tlogs": tlogs_doc,
            "proxy_raw_committed": [
                self._expose(p.raw_committed.ref())
                for _rn, p in proxies],
            # recruitment-time resolver splits (THE shared formula —
            # server/master.py); the move-log replay reconstructs the
            # live map from them
            "resolver_splits": list(initial_resolver_splits(n_res)),
            "storage_splits": list(first_proxy._sbounds[1:-1]),
            "storage_tags": list(first_proxy._stags),
        }

    def _translate(self, info) -> dict:
        """ServerDBInfo with every NetworkRef replaced by a TCP token
        (refs themselves cannot cross this wire: their encoding names a
        sim process, meaningless to an out-of-process peer)."""
        return {
            "seq": info.seq,
            "epoch": info.epoch,
            "recovery_state": info.recovery_state,
            "failed": list(info.failed),
            # control plane (ref: StatusClient / ManagementAPI reach the
            # CC the same way data ops reach the roles)
            "status": (self._expose(self.db.status_ref)
                       if self.db.status_ref is not None else 0),
            "management": (self._expose(self.db.management_ref)
                           if self.db.management_ref is not None else 0),
            "proxies": [
                {"name": p.name,
                 "grvs": self._expose(p.grvs),
                 "commits": self._expose(p.commits)}
                for p in info.proxies],
            "shards": [
                {"begin": s.begin,
                 "end": s.end if s.end is not None else b"",
                 "has_end": s.end is not None,
                 "replicas": [
                     {"name": r.name,
                      "gets": self._expose(r.gets),
                      "ranges": self._expose(r.ranges),
                      "get_keys": self._expose(r.get_keys),
                      "watches": self._expose(r.watches)}
                     for r in s.replicas]}
                for s in info.storages],
        }
