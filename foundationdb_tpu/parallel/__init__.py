"""Multi-device parallelism: key-range sharded resolution over a Mesh.

The reference scales conflict resolution by partitioning the keyspace
across resolver processes (fdbserver/MasterProxyServer.actor.cpp
keyResolvers map, ResolutionRequestBuilder :265-341; rebalanced by
masterserver.actor.cpp resolutionBalancing :1008). Here the partition is
a jax.sharding.Mesh axis, and cross-shard combines ride ICI collectives
instead of RPC.
"""

from .sharded_resolver import ShardedTpuConflictSet, default_split_keys

__all__ = ["ShardedTpuConflictSet", "default_split_keys"]
