"""Key-range sharded conflict resolution over a device mesh.

The TPU analogue of FDB's multi-resolver deployment: each device owns a
contiguous keyspace shard (ref: keyResolvers KeyRangeMap,
fdbserver/MasterProxyServer.actor.cpp:204; range splits moved by
resolutionBalancing, fdbserver/masterserver.actor.cpp:1008). The batch
is replicated to every shard; each shard clips conflict ranges to its
own interval and runs the same resolve kernel on its local history
partition (shard_map over a `resolvers` mesh axis).

Where the reference combines per-resolver verdicts with min() at the
proxy (MasterProxyServer.actor.cpp:585-592) and each resolver's
intra-batch check runs on local knowledge only — recording writes of
transactions another resolver aborted — here every external verdict and
every intra-batch fixpoint round is psum-combined over ICI (see
make_resolve_core's axis_name). The sharded resolver is therefore
bit-identical to the single-shard one: strictly fewer false conflicts
than the reference design, at the cost of one tiny collective per
fixpoint round (a few per batch, latency-hidden inside the step).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..models.tpu_resolver import TpuConflictSet, _MIN_CAP


def default_split_keys(n_shards: int) -> list[bytes]:
    """Evenly spaced single-byte split points over the keyspace."""
    return [bytes([(i * 256) // n_shards]) for i in range(1, n_shards)]


def _clip_and_resolve_packed(core, attribute: bool, unpack):
    """Per-shard wrapper for the packed single-buffer feed: unpack the
    replicated feed buffer locally (free — fused slices/bitcasts), clip
    the ranges to the shard, run the psum-combined core. The verdicts
    and attribution flags come back psum-COMBINED, hence identical on
    every shard — they are returned as REPLICATED outputs, so draining
    a ticket reads one device's buffer directly instead of slicing a
    distributed array (the per-device drain half of the async feed
    discipline)."""
    import jax.numpy as jnp

    from ..ops.keys import lt_rows

    def rows_max(a, b):
        bb = jnp.broadcast_to(b, a.shape)
        return jnp.where(lt_rows(a, bb)[:, None], bb, a)

    def rows_min(a, b):
        bb = jnp.broadcast_to(b, a.shape)
        return jnp.where(lt_rows(bb, a)[:, None], bb, a)

    def fn(shard_lo, shard_hi, hk, hv, buf):
        shard_lo, shard_hi = shard_lo[0], shard_hi[0]
        hk, hv = hk[0], hv[0]
        (snap, too_old, rb, re, rtxn, rvalid,
         wb, we, wtxn, wvalid, commit, oldest) = unpack(buf)
        rb2, re2 = rows_max(rb, shard_lo), rows_min(re, shard_hi)
        wb2, we2 = rows_max(wb, shard_lo), rows_min(we, shard_hi)
        rvalid2 = rvalid & lt_rows(rb2, re2)
        wvalid2 = wvalid & lt_rows(wb2, we2)
        out = core(hk, hv, snap, too_old, rb2, re2, rtxn, rvalid2,
                   wb2, we2, wtxn, wvalid2, commit, oldest)
        if not attribute:
            hk2, hv2, count, conflict = out
            return hk2[None], hv2[None], count[None], conflict
        hk2, hv2, count, conflict, read_hit = out
        return hk2[None], hv2[None], count[None], conflict, read_hit

    return fn


def _clip_and_resolve(core, attribute: bool):
    """Wrap the resolve core with per-shard range clipping."""
    import jax.numpy as jnp

    from ..ops.keys import lt_rows

    def rows_max(a, b):  # lexicographic per-row max of [n,width] vs [width]
        bb = jnp.broadcast_to(b, a.shape)
        return jnp.where(lt_rows(a, bb)[:, None], bb, a)

    def rows_min(a, b):
        bb = jnp.broadcast_to(b, a.shape)
        return jnp.where(lt_rows(bb, a)[:, None], bb, a)

    def fn(shard_lo, shard_hi, hk, hv, snap, too_old,
           rb, re, rtxn, rvalid, wb, we, wtxn, wvalid, commit, oldest):
        shard_lo, shard_hi = shard_lo[0], shard_hi[0]
        hk, hv = hk[0], hv[0]
        rb2, re2 = rows_max(rb, shard_lo), rows_min(re, shard_hi)
        wb2, we2 = rows_max(wb, shard_lo), rows_min(we, shard_hi)
        rvalid2 = rvalid & lt_rows(rb2, re2)
        wvalid2 = wvalid & lt_rows(wb2, we2)
        out = core(hk, hv, snap, too_old, rb2, re2, rtxn, rvalid2,
                   wb2, we2, wtxn, wvalid2, commit, oldest)
        if not attribute:
            hk2, hv2, count, conflict = out
            return (hk2[None], hv2[None], count[None], conflict[None])
        # read_hit comes back psum-combined across shards (the core
        # unions each shard's clipped-local attribution), so any
        # shard's copy is the global per-slot answer
        hk2, hv2, count, conflict, read_hit = out
        return (hk2[None], hv2[None], count[None], conflict[None],
                read_hit[None])

    return fn


class ShardedTpuConflictSet(TpuConflictSet):
    """Drop-in ConflictSet whose history is key-range sharded over a Mesh.

    Verdicts are bit-identical to `TpuConflictSet` (and therefore to the
    CPU baselines) — the acceptance criterion for the multi-resolver
    path, mirroring how the simulator replays verdicts across backends.
    """

    AXIS = "resolvers"
    BACKEND = "sharded-tpu"

    def __init__(self, init_version: int = 0, key_bytes: int = 32,
                 capacity: int = _MIN_CAP, mesh=None,
                 n_shards: Optional[int] = None,
                 split_keys: Optional[Sequence[bytes]] = None):
        import jax
        from jax.sharding import Mesh

        if mesh is None:
            devs = jax.devices()
            n = n_shards or len(devs)
            mesh = Mesh(np.asarray(devs[:n]), (self.AXIS,))
        self._mesh = mesh
        self._n_shards = mesh.devices.size
        if split_keys is None:
            split_keys = default_split_keys(self._n_shards)
        if len(split_keys) != self._n_shards - 1:
            raise ValueError("need n_shards-1 split keys")
        if list(split_keys) != sorted(split_keys):
            raise ValueError("split keys must be sorted")
        self._split_keys = [b""] + list(split_keys)
        self._shard_fns: dict = {}
        super().__init__(init_version=init_version, key_bytes=key_bytes,
                         capacity=capacity)

    # -- sharded state --------------------------------------------------
    def _to_device(self, hk: np.ndarray, hv: np.ndarray):
        """Expand single-shard init/grow arrays to [n_shards, ...]; shard 0
        keeps slot 0 at b"", every other shard's slot 0 is its own lower
        bound (the first boundary must be <= any clipped query begin)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.keys import encode_keys

        s = self._n_shards
        cap = hk.shape[0]
        shk = np.broadcast_to(hk, (s, cap, hk.shape[1])).copy()
        shv = np.broadcast_to(hv, (s, cap)).copy()
        lows = encode_keys(self._split_keys, self._key_bytes)
        base_version = hv[0]
        for i in range(1, s):
            shk[i, 0] = lows[i]
            shv[i, 0] = base_version
        self._shard_bounds = self._make_bounds(lows)
        dev = jax.device_put(
            (shk, shv),
            NamedSharding(self._mesh, P(self.AXIS)))
        return dev

    def _make_bounds(self, lows: np.ndarray):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        highs = np.full_like(lows, 0xFFFFFFFF)
        highs[:-1] = lows[1:]
        return jax.device_put((lows.copy(), highs),
                              NamedSharding(self._mesh, P(self.AXIS)))

    def _grow(self, needed: int) -> None:
        from ..ops.keys import next_pow2
        new_cap = max(self._cap * 2, next_pow2(needed + 2))
        s = self._n_shards
        shk = np.full((s, new_cap, self._n_words + 1), 0xFFFFFFFF, np.uint32)
        shv = np.full((s, new_cap), -(1 << 30), np.int32)
        shk[:, :self._cap] = np.asarray(self._hk)
        shv[:, :self._cap] = np.asarray(self._hv)
        self._cap = new_cap
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        self._hk, self._hv = jax.device_put(
            (shk, shv), NamedSharding(self._mesh, P(self.AXIS)))
        self._shard_fns.clear()

    # -- checkpoint / restore -------------------------------------------
    def _checkpoint_state(self):
        """Stitch the per-shard states back into ONE global step
        function: each shard's rows are clipped to its key range (slot 0
        is the shard's lower bound), so concatenating them in shard
        order is the global history. A boundary a shard recorded AT its
        upper bound covers keys it never answers for — the next shard's
        first row is authoritative there and replaces it."""
        from ..models.conflict_set import checkpoint_from_step
        from ..ops.fault_injection import convert_device_errors
        with convert_device_errors("drain", f"{self.BACKEND}.checkpoint"):
            shk = np.asarray(self._hk)
            shv = np.asarray(self._hv)
        keys: list = []
        vals: list = []
        for i in range(self._n_shards):
            k_i, v_i = self._decode_step(shk[i], shv[i])
            lo = self._split_keys[i]
            while keys and keys[-1] >= lo:
                keys.pop()
                vals.pop()
            keys.extend(k_i)
            vals.extend(v_i)
        return checkpoint_from_step(keys, vals, self._oldest,
                                    self._last_commit)

    def _install_step(self, keys, vals) -> None:
        """Re-shard a restored global step function: each shard gets
        the clip to its own [lo, hi) with an explicit boundary at lo
        (the same invariant _to_device establishes at init)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..models.conflict_set import clip_step
        from ..ops.keys import next_pow2
        s = self._n_shards
        clips = []
        for i in range(s):
            lo = self._split_keys[i]
            hi = self._split_keys[i + 1] if i + 1 < s else None
            clips.append(clip_step(keys, vals, lo, hi))
        rows = max(len(k) for k, _v in clips)
        self._cap = max(_MIN_CAP, self._cap, next_pow2(rows + 2))
        shk = np.empty((s, self._cap, self._n_words + 1), np.uint32)
        shv = np.empty((s, self._cap), np.int32)
        for i, (k_i, v_i) in enumerate(clips):
            shk[i], shv[i] = self._encode_step(k_i, v_i, self._cap)
        self._hk, self._hv = jax.device_put(
            (shk, shv), NamedSharding(self._mesh, P(self.AXIS)))
        # _shard_fns stays: entries are keyed by capacity, so a same-cap
        # restore reuses the compiled kernels and a grown cap compiles new
        self._count_hint = rows

    # -- sharded kernel dispatch ---------------------------------------
    def _get_shard_fn(self, npad, nrp, nwp, attribute: bool):
        key = (self._cap, npad, nrp, nwp, attribute)
        fn = self._shard_fns.get(key)
        if fn is not None:
            return fn
        import jax
        from jax.sharding import PartitionSpec as P

        from ..ops.conflict_kernel import make_resolve_core, profile_kernel

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        core = make_resolve_core(self._cap, npad, nrp, nwp, self._n_words,
                                 axis_name=self.AXIS, attribute=attribute)
        wrapped = _clip_and_resolve(core, attribute)
        sharded = P(self.AXIS)
        repl = P()
        n_out = 5 if attribute else 4
        specs = dict(
            mesh=self._mesh,
            in_specs=(sharded, sharded, sharded, sharded,
                      repl, repl, repl, repl, repl, repl,
                      repl, repl, repl, repl, repl, repl),
            out_specs=tuple([sharded] * n_out))
        # the replication-check kwarg was renamed check_rep -> check_vma
        # across jax releases; disable it under whichever name this
        # jax accepts (the psum'd fixpoint is deliberately mixed
        # replicated/sharded). The history carry (args 2,3 — after the
        # shard bounds, which ARE reused every call) is donated so the
        # in-flight pipeline window shares one sharded state allocation.
        try:
            fn = jax.jit(shard_map(wrapped, check_vma=False, **specs),
                         donate_argnums=(2, 3))
        except TypeError:
            fn = jax.jit(shard_map(wrapped, check_rep=False, **specs),
                         donate_argnums=(2, 3))
        # same compile/execute accounting as the single-shard families:
        # the sharded kernels have the most expensive compiles, so
        # bucket churn must be visible in the process-wide profile too
        tag = "" if attribute else "/noattr"
        fn = profile_kernel(
            fn, f"sharded[{self._cap}c/{npad}t/{nrp}r/{nwp}w{tag}]")
        from ..ops.conflict_kernel import _fault_seamed
        fn = _fault_seamed(fn, f"sharded[{self._cap}c]")
        self._shard_fns[key] = fn
        return fn

    def _call_kernel(self, npad, nrp, nwp, args, attribute: bool):
        fn = self._get_shard_fn(npad, nrp, nwp, attribute)
        lows, highs = self._shard_bounds
        read_hit = None
        if attribute:
            self._hk, self._hv, count, conflict, read_hit = fn(
                lows, highs, self._hk, self._hv, *args)
            read_hit = read_hit[0]
        else:
            self._hk, self._hv, count, conflict = fn(
                lows, highs, self._hk, self._hv, *args)
        return count, conflict[0], read_hit

    # -- packed single-buffer feed: per-device async transfers ----------
    def _feed(self, buf):
        """Per-device async feed: each shard's copy of the packed batch
        buffer is transferred with its own NON-BLOCKING device_put (the
        puts overlap each other and the previous batch's compute), then
        stitched into one replicated global array — the jit dispatch
        never gates on a single global host->device transfer, so
        aggregate feed throughput scales with chip count rather than
        link round-trips. h2d counters count every per-device put."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        devs = list(self._mesh.devices.flat)
        p = self.profile
        p.counter("h2d_transfers").add(len(devs))
        p.counter("h2d_bytes").add(int(buf.nbytes) * len(devs))
        parts = [jax.device_put(buf, d) for d in devs]
        return jax.make_array_from_single_device_arrays(
            buf.shape, NamedSharding(self._mesh, P()), parts)

    def _get_shard_packed_fn(self, npad, nrp, nwp, attribute: bool):
        key = ("packed", self._cap, npad, nrp, nwp, attribute)
        fn = self._shard_fns.get(key)
        if fn is not None:
            return fn
        import jax
        from jax.sharding import PartitionSpec as P

        from ..ops.conflict_kernel import (make_interval_unpack,
                                           make_resolve_core,
                                           profile_kernel)

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        core = make_resolve_core(self._cap, npad, nrp, nwp, self._n_words,
                                 axis_name=self.AXIS, attribute=attribute)
        unpack = make_interval_unpack(npad, nrp, nwp, self._n_words)
        wrapped = _clip_and_resolve_packed(core, attribute, unpack)
        sharded = P(self.AXIS)
        repl = P()
        # conflict (and read_hit) are psum-combined inside the core —
        # identical on every shard — so they come back REPLICATED and a
        # drain reads one device's buffer, not a distributed slice
        out = [sharded, sharded, sharded, repl] + ([repl] if attribute
                                                  else [])
        specs = dict(
            mesh=self._mesh,
            in_specs=(sharded, sharded, sharded, sharded, repl),
            out_specs=tuple(out))
        # history carry (args 2,3) donated exactly like the unpacked
        # sharded entry; the replication-check kwarg rename is handled
        # the same way as _get_shard_fn
        try:
            fn = jax.jit(shard_map(wrapped, check_vma=False, **specs),
                         donate_argnums=(2, 3))
        except TypeError:
            fn = jax.jit(shard_map(wrapped, check_rep=False, **specs),
                         donate_argnums=(2, 3))
        tag = "" if attribute else "/noattr"
        fn = profile_kernel(
            fn, f"sharded_packed[{self._cap}c/{npad}t/{nrp}r/{nwp}w{tag}]")
        from ..ops.conflict_kernel import _fault_seamed
        fn = _fault_seamed(fn, f"sharded_packed[{self._cap}c]")
        self._shard_fns[key] = fn
        return fn

    def _call_kernel_packed(self, npad, nrp, nwp, dev_buf, attribute: bool):
        fn = self._get_shard_packed_fn(npad, nrp, nwp, attribute)
        lows, highs = self._shard_bounds
        read_hit = None
        if attribute:
            self._hk, self._hv, count, conflict, read_hit = fn(
                lows, highs, self._hk, self._hv, dev_buf)
            read_hit = read_hit.addressable_shards[0].data
        else:
            self._hk, self._hv, count, conflict = fn(
                lows, highs, self._hk, self._hv, dev_buf)
        # per-device drain: the replicated verdicts are read off ONE
        # device's buffer (no distributed-array slice, no cross-device
        # gather on the readback path)
        return count, conflict.addressable_shards[0].data, read_hit
