"""Out-of-process Python client: the full client stack over TCP.

Reference: an external fdbcli/client process reaches a cluster through
FlowTransport + MonitorLeader (fdbclient/MonitorLeader.actor.cpp,
NativeAPI) — no shared memory, only the wire. Here `RemoteCluster`
hosts a wall-clock flow scheduler on a background thread, connects a
TcpTransport to a cluster's TcpGateway, translates the gateway's
describe document into a ServerDBInfo whose endpoints are TcpRefs, and
reuses the ENTIRE in-process client (`client/transaction.py` — RYW,
shard routing, replica load balance, OCC retry loop) unchanged on top:
the transaction logic cannot diverge between local and remote use.

Blocking surface: `call(coro)` runs any client coroutine on the loop
thread and returns its result, so synchronous tools (the CLI's
``--connect`` mode) drive transactions without owning a scheduler.

Watches work over the seam too: the gateway forwards the storage watch
long-polls like any other endpoint.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from .. import flow
from ..rpc.gateway import DESCRIBE_TOKEN
from ..rpc.tcp import TcpTransport
from ..server.dbinfo import (LogSetInfo, ProxyRefs, ServerDBInfo,
                             StorageRefs, StorageShard)
from .transaction import Database


def _build_info(d: dict, transport: TcpTransport, host: str,
                port: int) -> ServerDBInfo:
    def mk(token: int):
        return transport.ref(host, port, token)

    proxies = tuple(
        ProxyRefs(p.get("name", f"proxy-{i}"), mk(p["grvs"]),
                  mk(p["commits"]))
        for i, p in enumerate(d["proxies"]))
    shards = []
    for s in d["shards"]:
        end = s["end"] if s["has_end"] else None
        replicas = tuple(
            StorageRefs(r.get("name", f"rep-{r['gets']}"), 0, s["begin"],
                        end, mk(r["gets"]), mk(r["ranges"]),
                        mk(r["get_keys"]),
                        mk(r["watches"]) if r.get("watches") else None)
            for r in s["replicas"])
        shards.append(StorageShard(0, s["begin"], end, replicas))
    return ServerDBInfo(
        epoch=d.get("epoch", 0),
        recovery_state=d.get("recovery_state", "fully_recovered"),
        recovery_version=0, proxies=proxies,
        logs=LogSetInfo(0, 0, -1, ()), old_logs=(),
        storages=tuple(shards), seq=d["seq"],
        failed=tuple(d.get("failed", ())))


class RemoteDatabase(Database):
    """Database whose cluster picture comes from a TcpGateway describe
    instead of the in-sim ClusterController broadcast."""

    def __init__(self, transport: TcpTransport, host: str, port: int):
        super().__init__(process=None, cluster_ref=None)
        self._transport = transport
        self._host = host
        self._port = port
        self._status_token = 0
        self._management_token = 0

    async def _describe(self, min_seq: int) -> None:
        ref = self._transport.ref(self._host, self._port, DESCRIBE_TOKEN)
        d = await flow.timeout_error(
            ref.get_reply(int(min_seq)),
            flow.SERVER_KNOBS.remote_client_request_timeout)
        self._status_token = d.get("status", 0)
        self._management_token = d.get("management", 0)
        self._info = _build_info(d, self._transport, self._host, self._port)

    async def info(self):
        if self._info is None:
            await self._describe(-1)
        return self._info

    async def refresh_past(self, used_seq: int) -> None:
        if self._info is not None and self._info.seq > used_seq:
            return
        await self._describe(max(used_seq, 0))

    async def get_status(self) -> dict:
        if not self._status_token:
            raise flow.error("client_invalid_operation")
        ref = self._transport.ref(self._host, self._port,
                                  self._status_token)
        from ..server.types import STATUS_REQUEST
        return await flow.timeout_error(
            ref.get_reply(STATUS_REQUEST),
            flow.SERVER_KNOBS.remote_client_request_timeout)

    # configure/exclude ride the inherited Database implementations —
    # ordinary \xff/conf//\xff/excluded transactions over the same
    # remote refs as any other write (ref: ManagementAPI building
    # system-key transactions client-side) — but keep the gateway's
    # management-token authorization gate for the convenience API

    def _check_management(self) -> None:
        if not self._management_token:
            raise flow.error("client_invalid_operation")

    async def configure(self, **kwargs) -> None:
        self._check_management()
        await super().configure(**kwargs)

    async def exclude(self, worker: str, exclude: bool = True) -> None:
        self._check_management()
        await super().exclude(worker, exclude)


class RemoteCluster:
    """Blocking handle: a background wall-clock loop thread owns the
    transport and scheduler; `call(coro)` executes client coroutines
    there and returns the result to the calling thread."""

    def __init__(self, host: str, port: int,
                 connect_timeout: float = None,
                 tls=None):
        self.host = host
        self.port = port
        self._tls = tls
        self._submissions: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._started: queue.Queue = queue.Queue()
        if connect_timeout is None:
            from ..flow import SERVER_KNOBS
            connect_timeout = SERVER_KNOBS.remote_connect_timeout
        self._connect_timeout = connect_timeout
        self._thread = threading.Thread(target=self._main, daemon=True)
        self._thread.start()
        # the loop thread's own boot timeout governs; the queue wait is
        # slightly longer so the REAL boot error arrives here instead
        # of a generic queue.Empty
        item = self._started.get(timeout=connect_timeout + 10)
        if isinstance(item, BaseException):
            raise item
        self.db: RemoteDatabase = item

    def _main(self) -> None:
        s = flow.Scheduler(virtual=False)
        flow.set_scheduler(s)
        transport = TcpTransport(tls=self._tls)
        try:
            transport.start()
            db = RemoteDatabase(transport, self.host, self.port)

            async def boot():
                await db.info()
                return True

            async def pump():
                # drain cross-thread submissions; each is
                # (coroutine, result_box, done_event)
                while not self._stop.is_set():
                    try:
                        coro, box, done = self._submissions.get_nowait()
                    except queue.Empty:
                        await flow.delay(
                            flow.SERVER_KNOBS.remote_client_poll_delay)
                        continue
                    flow.spawn(self._run_one(coro, box, done))

            t = s.spawn(boot())
            s.run(until=t, timeout_time=self._connect_timeout)
            self._started.put(db)
            s.run(until=s.spawn(pump()))
        except BaseException as e:  # noqa: BLE001 — surface to creator
            self._started.put(e)
        finally:
            self._stop.set()   # later call()s fail fast, never hang
            transport.close()
            flow.set_scheduler(None)

    @staticmethod
    async def _run_one(coro, box, done) -> None:
        try:
            box.append(("ok", await coro))
        except BaseException as e:  # noqa: BLE001 — marshalled to caller
            box.append(("err", e))
        finally:
            done.set()

    def call(self, coro, timeout: float = None):
        """Run a client coroutine on the loop thread; blocking."""
        if self._stop.is_set() or not self._thread.is_alive():
            raise flow.error("broken_promise")   # loop gone: fail fast
        if timeout is None:
            from ..flow import SERVER_KNOBS
            timeout = SERVER_KNOBS.remote_call_timeout
        box: list = []
        done = threading.Event()
        self._submissions.put((coro, box, done))
        if not done.wait(timeout):
            raise flow.error("timed_out")
        kind, value = box[0]
        if kind == "err":
            raise value
        return value

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
