"""The cluster file: `description:id@host:port[,host:port]...`

Ref: fdbclient/MonitorLeader.actor.cpp:185 (connection-string parsing
tests) and the fdb.cluster conventions (documentation/): a cluster is
named by `description:id` (description is operator-chosen, id changes
when the coordinator set changes) followed by the coordinator
addresses. Here the addresses name the cluster's TCP gateway(s) — the
seam an out-of-process client actually dials — and tools accept
`--cluster-file` (or the FDB_TPU_CLUSTER_FILE environment variable)
anywhere they accept `--connect host:port`.
"""

from __future__ import annotations

import os
import re
from typing import List, NamedTuple, Tuple

_KEY_RE = re.compile(r"^[A-Za-z0-9_]+$")


class ClusterConnectionString(NamedTuple):
    description: str
    cluster_id: str
    addresses: Tuple[Tuple[str, int], ...]

    def __str__(self) -> str:
        hosts = ",".join(f"{h}:{p}" for h, p in self.addresses)
        return f"{self.description}:{self.cluster_id}@{hosts}"


def parse_connection_string(s: str) -> ClusterConnectionString:
    """Parse `description:id@host:port,...` (whitespace/comment
    tolerant the way the reference's parser is)."""
    # strip comments and whitespace: the reference accepts a file with
    # leading '#' comment lines and surrounding blanks
    lines = [ln.strip() for ln in s.splitlines()]
    lines = [ln for ln in lines if ln and not ln.startswith("#")]
    if len(lines) != 1:
        raise ValueError(
            f"cluster file must hold exactly one connection string, "
            f"got {len(lines)} lines")
    body = lines[0]
    if "@" not in body:
        raise ValueError("missing '@' in connection string")
    name, hosts = body.split("@", 1)
    if ":" not in name:
        raise ValueError("missing ':' between description and id")
    desc, cid = name.split(":", 1)
    if not _KEY_RE.match(desc) or not _KEY_RE.match(cid):
        raise ValueError(
            f"description/id must be alphanumeric: {name!r}")
    addrs: List[Tuple[str, int]] = []
    for part in hosts.split(","):
        addrs.append(parse_address(part.strip()))
    return ClusterConnectionString(desc, cid, tuple(addrs))


def parse_address(part: str) -> Tuple[str, int]:
    """`host:port` with the port validated to the TCP range."""
    host, _, port = part.rpartition(":")
    if not host or not port.isdigit() or not 0 < int(port) < 65536:
        raise ValueError(f"bad address {part!r} (expected host:port)")
    return host, int(port)


def read_cluster_file(path: str) -> ClusterConnectionString:
    with open(path, "r") as f:
        return parse_connection_string(f.read())


def write_cluster_file(path: str, conn: ClusterConnectionString) -> None:
    """Atomic replace, like the reference rewriting fdb.cluster after a
    coordinators change."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(conn) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def resolve_connect(connect: str | None,
                    cluster_file: str | None) -> Tuple[str, int] | None:
    """The address tools dial: an explicit --connect host:port wins;
    otherwise the first address of --cluster-file or
    $FDB_TPU_CLUSTER_FILE; None means local/in-sim mode."""
    if connect is not None:
        return parse_address(connect)
    path = cluster_file or os.environ.get("FDB_TPU_CLUSTER_FILE")
    if path:
        conn = read_cluster_file(path)
        return conn.addresses[0]
    return None
