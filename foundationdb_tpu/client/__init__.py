"""Client API (ref: fdbclient/ — NativeAPI + ReadYourWrites)."""

from .transaction import Database, Transaction, run_transaction

__all__ = ["Database", "Transaction", "run_transaction"]
