"""Client API (ref: fdbclient/ — NativeAPI + ReadYourWrites)."""

from .transaction import (RETRYABLE, Database, Transaction,
                          run_transaction)

__all__ = ["RETRYABLE", "Database", "Transaction", "run_transaction"]
