"""Client transactions: snapshot reads, read-your-writes, OCC commit.

Reference: fdbclient/NativeAPI.actor.cpp — GRV (:2854 readVersionBatcher,
lazily fetched on first read), reads through the location cache to
storage (:1273 getValue, :1712 getRange), commit (:2498 tryCommit: ship
read/write conflict ranges + mutations to a proxy), and the retry loop
(:2956 onError: backoff then reset). Read-your-writes semantics come
from overlaying the transaction's uncommitted writes on every read
(fdbclient/ReadYourWrites.actor.cpp WriteMap merge), and reads record
read-conflict ranges so the resolver can detect conflicts exactly.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..flow import Future, TaskPriority, error
from ..rpc import NetworkRef, SimProcess
from ..server import atomic as _atomic
from ..server.types import (ADD_VALUE, AND, APPEND_IF_FITS, ATOMIC_OPS,
                            BYTE_MAX, BYTE_MIN, CLEAR_RANGE,
                            COMPARE_AND_CLEAR, CommitRequest, KeySelector,
                            MAX, MIN, MutationRef, OR, SET_VALUE,
                            SET_VERSIONSTAMPED_KEY, SET_VERSIONSTAMPED_VALUE,
                            StorageGetKeyRequest, StorageGetRangeRequest,
                            StorageGetRequest, StorageWatchRequest, XOR)

_ATOMIC_APPLY = {
    ADD_VALUE: _atomic.add, AND: _atomic.bit_and, OR: _atomic.bit_or,
    XOR: _atomic.bit_xor, APPEND_IF_FITS: _atomic.append_if_fits,
    MAX: _atomic.vmax, MIN: _atomic.vmin, BYTE_MIN: _atomic.byte_min,
    BYTE_MAX: _atomic.byte_max, COMPARE_AND_CLEAR: _atomic.compare_and_clear,
}

RETRYABLE = {"not_committed", "transaction_too_old", "future_version",
             "broken_promise", "commit_unknown_result", "timed_out"}


def _next_key(k: bytes) -> bytes:
    return k + b"\x00"


class Database:
    """Handle to the cluster (ref: Database/Cluster in NativeAPI)."""

    def __init__(self, process: SimProcess, grv_ref: NetworkRef,
                 commit_ref: NetworkRef, storage_get: NetworkRef,
                 storage_range: NetworkRef, storage_key: NetworkRef = None,
                 storage_watch: NetworkRef = None):
        self.process = process
        self.grv_ref = grv_ref
        self.commit_ref = commit_ref
        self.storage_get = storage_get
        self.storage_range = storage_range
        self.storage_key = storage_key
        self.storage_watch = storage_watch

    def create_transaction(self) -> "Transaction":
        return Transaction(self)


class Transaction:
    def __init__(self, db: Database):
        self.db = db
        self.reset()

    def reset(self) -> None:
        self._read_version: Optional[int] = None
        self._writes: Dict[bytes, Optional[bytes]] = {}  # RYW write map
        self._write_order: List[bytes] = []              # sorted keys
        self._cleared: List[Tuple[bytes, bytes]] = []    # ordered clears
        self._ops: Dict[bytes, List[Tuple[int, bytes]]] = {}  # pending atomics
        self._mutations: List[MutationRef] = []
        self._read_conflicts: List[Tuple[bytes, bytes]] = []
        self._write_conflicts: List[Tuple[bytes, bytes]] = []
        self._watches: List[Tuple[bytes, Future]] = []
        self.committed_version: Optional[int] = None
        self.committed_batch_index: Optional[int] = None

    # -- read version ---------------------------------------------------
    async def get_read_version(self) -> int:
        if self._read_version is None:
            reply = await self.db.grv_ref.get_reply(None, self.db.process)
            self._read_version = reply.version
        return self._read_version

    # -- RYW overlay ----------------------------------------------------
    def _overlay_get(self, key: bytes):
        """(found, value) against uncommitted writes, newest-first."""
        if key in self._writes:
            return True, self._writes[key]
        for b, e in reversed(self._cleared):
            if b <= key < e:
                return True, None
        return False, None

    # -- reads ----------------------------------------------------------
    async def _base_get(self, key: bytes) -> Optional[bytes]:
        found, val = self._overlay_get(key)
        if found:
            return val
        version = await self.get_read_version()
        return await self.db.storage_get.get_reply(
            StorageGetRequest(key, version), self.db.process)

    async def get(self, key: bytes, snapshot: bool = False) -> Optional[bytes]:
        if not snapshot:
            self._read_conflicts.append((key, _next_key(key)))
        val = await self._base_get(key)
        # pending atomic ops computed over the base (ref: RYW reads of
        # atomically-modified keys, ReadYourWrites.actor.cpp)
        for op, param in self._ops.get(key, ()):
            val = _ATOMIC_APPLY[op](val, param)
        return val

    async def get_key(self, selector: KeySelector,
                      snapshot: bool = False) -> bytes:
        """Resolve a key selector (ref: Transaction::getKey)."""
        version = await self.get_read_version()
        resolved = await self.db.storage_key.get_reply(
            StorageGetKeyRequest(selector, version), self.db.process)
        if not snapshot:
            lo = min(resolved, selector.key)
            hi = max(resolved, selector.key)
            self._read_conflicts.append((lo, _next_key(hi)))
        return resolved

    async def get_range(self, begin, end, limit: int = 1 << 20,
                        snapshot: bool = False,
                        reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        if isinstance(begin, KeySelector):
            begin = await self.get_key(begin, snapshot=snapshot)
        if isinstance(end, KeySelector):
            end = await self.get_key(end, snapshot=snapshot)
        if begin >= end:
            return []
        version = await self.get_read_version()
        # With no RYW overlay in the range the storage server honors the
        # caller's limit/reverse directly; an overlay (clears/writes/
        # atomics) can remove or add rows, so fetch the full range and
        # merge (ref: RYWIterator reads through the WriteMap instead).
        has_overlay = bool(self._cleared or self._write_order or self._ops)
        base = await self.db.storage_range.get_reply(
            StorageGetRangeRequest(begin, end, version,
                                   (1 << 20) if has_overlay else limit,
                                   False if has_overlay else reverse),
            self.db.process)
        # overlay uncommitted writes (ref: RYWIterator merge)
        merged: Dict[bytes, bytes] = {k: v for k, v in base}
        for b, e in self._cleared:
            for k in [k for k in merged if b <= k < e]:
                del merged[k]
        lo = bisect_left(self._write_order, begin)
        hi = bisect_left(self._write_order, end)
        for k in self._write_order[lo:hi]:
            v = self._writes[k]
            if v is None:
                merged.pop(k, None)
            else:
                merged[k] = v
        # keys with pending atomic ops materialize from their base value
        for k, ops in self._ops.items():
            if begin <= k < end:
                val = merged.get(k)
                if val is None and k not in self._writes and \
                        not any(b <= k < e for b, e in self._cleared):
                    val = await self.db.storage_get.get_reply(
                        StorageGetRequest(k, version), self.db.process)
                for op, param in ops:
                    val = _ATOMIC_APPLY[op](val, param)
                if val is None:
                    merged.pop(k, None)
                else:
                    merged[k] = val
        out = sorted(merged.items(), reverse=reverse)[:limit]
        if not snapshot:
            # record only the observed portion: when the limit truncates,
            # keys past the last returned row were never promised to the
            # caller (ref: record-what-was-read conflict semantics,
            # NativeAPI getRange → tr.addReadConflictRange of the
            # readThrough bound)
            if len(out) == limit and out:
                if reverse:
                    self._read_conflicts.append((out[-1][0], end))
                else:
                    self._read_conflicts.append((begin, _next_key(out[-1][0])))
            else:
                self._read_conflicts.append((begin, end))
        return out

    # -- writes ---------------------------------------------------------
    def _record_write(self, key: bytes, value: Optional[bytes]) -> None:
        if key not in self._writes:
            insort(self._write_order, key)
        self._writes[key] = value

    def set(self, key: bytes, value: bytes) -> None:
        self._record_write(key, value)
        self._ops.pop(key, None)  # a set supersedes pending atomics
        self._mutations.append(MutationRef(SET_VALUE, key, value))
        self._write_conflicts.append((key, _next_key(key)))

    def clear(self, key: bytes) -> None:
        self.clear_range(key, _next_key(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        if begin >= end:
            return
        self._cleared.append((begin, end))
        lo = bisect_left(self._write_order, begin)
        hi = bisect_left(self._write_order, end)
        for k in self._write_order[lo:hi]:
            self._writes[k] = None
        for k in [k for k in self._ops if begin <= k < end]:
            del self._ops[k]
        self._mutations.append(MutationRef(CLEAR_RANGE, begin, end))
        self._write_conflicts.append((begin, end))

    def atomic_op(self, key: bytes, param: bytes, op_type: int) -> None:
        """(ref: Transaction::atomicOp / fdbclient/Atomic.h op table)"""
        if op_type in (SET_VERSIONSTAMPED_KEY, SET_VERSIONSTAMPED_VALUE):
            # transformed at the proxy with the commit version; the
            # operand's trailing 4 bytes are the placeholder offset
            self._mutations.append(MutationRef(op_type, key, param))
            wkey = key[:-4] if op_type == SET_VERSIONSTAMPED_KEY else key
            self._write_conflicts.append((wkey, _next_key(wkey)))
            return
        if op_type not in ATOMIC_OPS:
            raise error("client_invalid_operation")
        # a set/clear'd key has a known value: fold the op in directly
        found, cur = self._overlay_get(key)
        if found and key not in self._ops:
            result = _ATOMIC_APPLY[op_type](cur, param)
            if result is None:
                self._record_write(key, None)
                self._mutations.append(
                    MutationRef(CLEAR_RANGE, key, _next_key(key)))
            else:
                self._record_write(key, result)
                self._mutations.append(MutationRef(SET_VALUE, key, result))
        else:
            self._ops.setdefault(key, []).append((op_type, param))
            self._mutations.append(MutationRef(op_type, key, param))
        self._write_conflicts.append((key, _next_key(key)))

    def watch(self, key: bytes) -> Future:
        """Future that fires when the key's value changes after this
        transaction commits (ref: Transaction::watch / storage watches).
        Errors with transaction_cancelled if the commit fails."""
        f = Future()
        self._watches.append((key, f))
        return f

    # -- commit ---------------------------------------------------------
    async def commit(self) -> int:
        """(ref: Transaction::commit :2710 / tryCommit :2498)"""
        if not self._mutations:
            # read-only: succeeds at the read version without a round trip
            self.committed_version = self._read_version or 0
            self._arm_watches(self.committed_version)
            return self.committed_version
        snapshot = await self.get_read_version()
        req = CommitRequest(snapshot, tuple(self._read_conflicts),
                            tuple(self._write_conflicts),
                            tuple(self._mutations))
        try:
            reply = await self.db.commit_ref.get_reply(req, self.db.process)
        except flow.FdbError as e:
            for _k, f in self._watches:
                if not f.is_ready:
                    f.send_error(error("transaction_cancelled"))
            raise e
        self.committed_version = reply.version
        self.committed_batch_index = reply.batch_index
        self._arm_watches(reply.version)
        return reply.version

    def get_versionstamp(self) -> bytes:
        """The committed transaction's 10-byte versionstamp."""
        if self.committed_version is None:
            raise error("client_invalid_operation")
        from ..server.proxy import make_versionstamp
        return make_versionstamp(self.committed_version,
                                 self.committed_batch_index or 0)

    def _arm_watches(self, version: int) -> None:
        """Wire pending watches to storage at the commit version."""
        for key, f in self._watches:
            if f.is_ready:
                continue
            storage_fut = self.db.storage_watch.get_reply(
                StorageWatchRequest(key, version), self.db.process)
            storage_fut.on_ready(
                lambda sf, f=f: (f.send(sf.get()) if not sf.is_error
                                 else f.send_error(sf.exception()))
                if not f.is_ready else None)
        self._watches = []

    # -- retry loop -----------------------------------------------------
    async def on_error(self, e: BaseException) -> None:
        """(ref: Transaction::onError :2956 — backoff and reset)"""
        if not (isinstance(e, flow.FdbError) and e.name in RETRYABLE):
            raise e
        await flow.delay(0.001 + flow.g_random.random01() * 0.01,
                         TaskPriority.DEFAULT_ENDPOINT)
        self.reset()


async def run_transaction(db: Database, body, max_retries: int = 100):
    """The standard retry loop (ref: the `doTransaction` idiom / python
    binding @fdb.transactional)."""
    tr = db.create_transaction()
    for _ in range(max_retries):
        try:
            result = await body(tr)
            await tr.commit()
            return result
        except flow.FdbError as e:
            await tr.on_error(e)
    raise error("transaction_timed_out")
