"""Client transactions: snapshot reads, read-your-writes, OCC commit.

Reference: fdbclient/NativeAPI.actor.cpp — GRV (:2854 readVersionBatcher,
lazily fetched on first read), reads through the location cache to
storage (:1273 getValue, :1712 getRange), commit (:2498 tryCommit: ship
read/write conflict ranges + mutations to a proxy), and the retry loop
(:2956 onError: backoff then reset). Read-your-writes semantics come
from overlaying the transaction's uncommitted writes on every read
(fdbclient/ReadYourWrites.actor.cpp WriteMap merge), and reads record
read-conflict ranges so the resolver can detect conflicts exactly.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..flow import TaskPriority, error
from ..rpc import NetworkRef, SimProcess
from ..server.types import (CLEAR_RANGE, SET_VALUE, CommitRequest, MutationRef,
                            StorageGetRangeRequest, StorageGetRequest)

RETRYABLE = {"not_committed", "transaction_too_old", "future_version",
             "broken_promise", "commit_unknown_result", "timed_out"}


def _next_key(k: bytes) -> bytes:
    return k + b"\x00"


class Database:
    """Handle to the cluster (ref: Database/Cluster in NativeAPI)."""

    def __init__(self, process: SimProcess, grv_ref: NetworkRef,
                 commit_ref: NetworkRef, storage_get: NetworkRef,
                 storage_range: NetworkRef):
        self.process = process
        self.grv_ref = grv_ref
        self.commit_ref = commit_ref
        self.storage_get = storage_get
        self.storage_range = storage_range

    def create_transaction(self) -> "Transaction":
        return Transaction(self)


class Transaction:
    def __init__(self, db: Database):
        self.db = db
        self.reset()

    def reset(self) -> None:
        self._read_version: Optional[int] = None
        self._writes: Dict[bytes, Optional[bytes]] = {}  # RYW write map
        self._write_order: List[bytes] = []              # sorted keys
        self._cleared: List[Tuple[bytes, bytes]] = []    # ordered clears
        self._mutations: List[MutationRef] = []
        self._read_conflicts: List[Tuple[bytes, bytes]] = []
        self._write_conflicts: List[Tuple[bytes, bytes]] = []
        self.committed_version: Optional[int] = None

    # -- read version ---------------------------------------------------
    async def get_read_version(self) -> int:
        if self._read_version is None:
            reply = await self.db.grv_ref.get_reply(None, self.db.process)
            self._read_version = reply.version
        return self._read_version

    # -- RYW overlay ----------------------------------------------------
    def _overlay_get(self, key: bytes):
        """(found, value) against uncommitted writes, newest-first."""
        if key in self._writes:
            return True, self._writes[key]
        for b, e in reversed(self._cleared):
            if b <= key < e:
                return True, None
        return False, None

    # -- reads ----------------------------------------------------------
    async def get(self, key: bytes, snapshot: bool = False) -> Optional[bytes]:
        if not snapshot:
            self._read_conflicts.append((key, _next_key(key)))
        found, val = self._overlay_get(key)
        if found:
            return val
        version = await self.get_read_version()
        return await self.db.storage_get.get_reply(
            StorageGetRequest(key, version), self.db.process)

    async def get_range(self, begin: bytes, end: bytes, limit: int = 1 << 20,
                        snapshot: bool = False) -> List[Tuple[bytes, bytes]]:
        if begin >= end:
            return []
        if not snapshot:
            self._read_conflicts.append((begin, end))
        version = await self.get_read_version()
        base = await self.db.storage_range.get_reply(
            StorageGetRangeRequest(begin, end, version, limit),
            self.db.process)
        # overlay uncommitted writes (ref: RYWIterator merge)
        merged: Dict[bytes, bytes] = {k: v for k, v in base}
        for b, e in self._cleared:
            for k in [k for k in merged if b <= k < e]:
                del merged[k]
        lo = bisect_left(self._write_order, begin)
        hi = bisect_left(self._write_order, end)
        for k in self._write_order[lo:hi]:
            v = self._writes[k]
            if v is None:
                merged.pop(k, None)
            else:
                merged[k] = v
        return sorted(merged.items())[:limit]

    # -- writes ---------------------------------------------------------
    def _record_write(self, key: bytes, value: Optional[bytes]) -> None:
        if key not in self._writes:
            insort(self._write_order, key)
        self._writes[key] = value

    def set(self, key: bytes, value: bytes) -> None:
        self._record_write(key, value)
        self._mutations.append(MutationRef(SET_VALUE, key, value))
        self._write_conflicts.append((key, _next_key(key)))

    def clear(self, key: bytes) -> None:
        self.clear_range(key, _next_key(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        if begin >= end:
            return
        self._cleared.append((begin, end))
        lo = bisect_left(self._write_order, begin)
        hi = bisect_left(self._write_order, end)
        for k in self._write_order[lo:hi]:
            self._writes[k] = None
        self._mutations.append(MutationRef(CLEAR_RANGE, begin, end))
        self._write_conflicts.append((begin, end))

    # -- commit ---------------------------------------------------------
    async def commit(self) -> int:
        """(ref: Transaction::commit :2710 / tryCommit :2498)"""
        if not self._mutations:
            # read-only: succeeds at the read version without a round trip
            self.committed_version = self._read_version or 0
            return self.committed_version
        snapshot = await self.get_read_version()
        req = CommitRequest(snapshot, tuple(self._read_conflicts),
                            tuple(self._write_conflicts),
                            tuple(self._mutations))
        reply = await self.db.commit_ref.get_reply(req, self.db.process)
        self.committed_version = reply.version
        return reply.version

    # -- retry loop -----------------------------------------------------
    async def on_error(self, e: BaseException) -> None:
        """(ref: Transaction::onError :2956 — backoff and reset)"""
        if not (isinstance(e, flow.FdbError) and e.name in RETRYABLE):
            raise e
        await flow.delay(0.001 + flow.g_random.random01() * 0.01,
                         TaskPriority.DEFAULT_ENDPOINT)
        self.reset()


async def run_transaction(db: Database, body, max_retries: int = 100):
    """The standard retry loop (ref: the `doTransaction` idiom / python
    binding @fdb.transactional)."""
    tr = db.create_transaction()
    for _ in range(max_retries):
        try:
            result = await body(tr)
            await tr.commit()
            return result
        except flow.FdbError as e:
            await tr.on_error(e)
    raise error("transaction_timed_out")
