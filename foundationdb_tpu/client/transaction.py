"""Client transactions: snapshot reads, read-your-writes, OCC commit.

Reference: fdbclient/NativeAPI.actor.cpp — GRV (:2854 readVersionBatcher,
lazily fetched on first read), reads through the location cache to
storage (:1273 getValue, :1712 getRange), commit (:2498 tryCommit: ship
read/write conflict ranges + mutations to a proxy), and the retry loop
(:2956 onError: backoff then reset). Read-your-writes semantics come
from overlaying the transaction's uncommitted writes on every read
(fdbclient/ReadYourWrites.actor.cpp WriteMap merge), and reads record
read-conflict ranges so the resolver can detect conflicts exactly.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..flow import SERVER_KNOBS, Future, TaskPriority, error
from ..rpc import NetworkRef, SimProcess
from ..server import atomic as _atomic
from ..server.cluster_controller import \
    OpenDatabaseRequest as _OpenDatabaseRequest
from ..server.types import (ADD_VALUE, AND, AND_V2, APPEND_IF_FITS,
                            ATOMIC_OPS, BYTE_MAX, BYTE_MIN, CLEAR_RANGE,
                            COMPARE_AND_CLEAR, CommitRequest, KeySelector,
                            MAX, MIN, MIN_V2, MutationRef, OR, SET_VALUE,
                            SET_VERSIONSTAMPED_KEY, SET_VERSIONSTAMPED_VALUE,
                            StorageGetRangeRequest,
                            StorageGetRequest, StorageWatchRequest, XOR)

_ATOMIC_APPLY = {
    ADD_VALUE: _atomic.add, AND: _atomic.bit_and, OR: _atomic.bit_or,
    XOR: _atomic.bit_xor, APPEND_IF_FITS: _atomic.append_if_fits,
    MAX: _atomic.vmax, MIN: _atomic.vmin, BYTE_MIN: _atomic.byte_min,
    BYTE_MAX: _atomic.byte_max, COMPARE_AND_CLEAR: _atomic.compare_and_clear,
    MIN_V2: _atomic.vmin, AND_V2: _atomic.bit_and,
}

RETRYABLE = {"not_committed", "transaction_too_old", "future_version",
             "broken_promise", "commit_unknown_result", "timed_out",
             "tlog_stopped", "coordinators_changed", "wrong_shard_server",
             # the enforced-admission plane's designed overload
             # responses: a rejected GRV retries through the ordinary
             # backoff loop (ref: proxy_memory_limit_exceeded /
             # tag_throttled both retryable in NativeAPI onError)
             "proxy_memory_limit_exceeded", "tag_throttled"}

# errors that mean our picture of the cluster may be stale: re-fetch the
# ServerDBInfo before retrying (ref: the client reconnecting through
# MonitorLeader / refreshing the location cache on wrong_shard_server)
REFRESH_ERRORS = {"broken_promise", "commit_unknown_result", "tlog_stopped",
                  "coordinators_changed", "wrong_shard_server"}


# seconds before a hung role surfaces as retryable timed_out
# (ref: failure-monitored getReply); see CLIENT_REQUEST_TIMEOUT knob


def _request_timeout() -> float:
    return flow.SERVER_KNOBS.client_request_timeout

# "no limit" sentinel for range reads: the default get_range cap, the
# overlay full-fetch, and the parallel-fan-out threshold must agree
UNBOUNDED_ROW_LIMIT = 1 << 20

# The \xff system keyspace schema lives in server/systemkeys.py (one
# source of truth for client, proxy, CC, and tools): everything in
# [\xff\x02, \xff\xff) is REAL stored data committed through the
# ordinary pipeline except the materialized \xff/keyServers/ view, so
# `configure`/`exclude` are transactions the proxies interpret (ref:
# fdbclient/SystemData.cpp; ApplyMetadataMutation.h).
from ..server.systemkeys import (CONF_PREFIX, CONF_ROW_BY_FIELD,
                                 ENGINE_PREFIX, EXCLUDED_PREFIX,
                                 KEY_SERVERS_END, KEY_SERVERS_PREFIX,
                                 STORED_SYSTEM_PREFIX, SYSTEM_PREFIX,
                                 is_stored_system as _is_stored_system)


def _rpc(fut: Future) -> Future:
    return flow.timeout_error(fut, _request_timeout())


def _pick_live_proxy(info):
    """A random proxy, preferring ones the failure monitor has not
    pushed as down (all-failed falls back to any — they may be wrong)."""
    live = [p for p in info.proxies if p.name not in info.failed]
    cands = live or list(info.proxies)
    return cands[flow.g_random.random_int(0, len(cands))]


def _next_key(k: bytes) -> bytes:
    return k + b"\x00"


class Database:
    """Handle to the cluster (ref: Database/Cluster in NativeAPI). Holds
    a cached ServerDBInfo fetched from the ClusterController's
    openDatabase endpoint (ref: MonitorLeader + openDatabase handshake);
    reads route through the shard map, commits through the proxies."""

    def __init__(self, process: SimProcess, cluster_ref: NetworkRef,
                 status_ref: NetworkRef = None,
                 management_ref: NetworkRef = None,
                 coordinators=None):
        self.process = process
        self.cluster_ref = cluster_ref
        self.status_ref = status_ref
        self.management_ref = management_ref
        # coordinator ref 4-tuples: with these the client survives the
        # death of the controller it was handed — it re-finds the
        # current leader through the coordinators, exactly how the
        # reference's clients outlive any one CC (ref: MonitorLeader,
        # fdbclient/MonitorLeader.actor.cpp — the cluster file names
        # coordinators, never the CC)
        self.coordinators = coordinators
        self._leader_gen = 0       # bumped on every rediscovered leader
        self._info = None
        #: (priority, tags) -> extra logical-transaction weight beyond
        #: the waiter count (client multiplexing; see batched_grv)
        self._grv_extra: Dict = {}
        #: priority class -> waiting futures (batched per class so a
        #: BATCH rider can never borrow DEFAULT's admission)
        self._grv_waiters: Dict[int, List[Future]] = {}
        self._grv_timer_armed = False
        #: replica name -> latency EMA seconds (ref: LoadBalance's
        #: per-alternative latency model, fdbrpc/LoadBalance.actor.h)
        self._latency_ema: Dict[str, float] = {}
        self._watch_task = None   # standing dbinfo long-poll
        # sampled transaction profiling (client/profiling.py): the
        # per-database transaction ordinal the deterministic sampling
        # decision hashes, and its lazily-derived salt
        self._txn_seq = 0
        self._profile_salt: Optional[int] = None
        # hot-key conflict windows ridden in on GRV replies
        # (server/scheduler.py ConflictWindowCache): database-scoped so
        # every transaction — including every RETRY attempt — consults
        # the same picture; lazily created on the first window-carrying
        # reply, so the feature-off path allocates nothing
        self._conflict_cache = None
        # server-advertised tag throttles ridden in on GRV replies
        # (server/tag_throttler.py ClientTagThrottleCache): same
        # plumbing — database-scoped so retries honor the backoff too,
        # lazily created on the first throttle-carrying reply
        self._tag_throttle_cache = None

    def note_latency(self, replica: str, seconds: float) -> None:
        prev = self._latency_ema.get(replica)
        self._latency_ema[replica] = seconds if prev is None else \
            0.9 * prev + 0.1 * seconds

    async def get_status(self) -> dict:
        """The cluster status document (ref: StatusClient fetching the
        CC-assembled JSON, fdbclient/StatusClient.actor.cpp)."""
        if self.status_ref is None:
            raise error("client_invalid_operation")
        from ..server.types import STATUS_REQUEST
        return await _rpc(
            self.status_ref.get_reply(STATUS_REQUEST, self.process))

    async def _live_workers(self, without: str = "") -> int:
        """Alive, non-excluded workers per status — the client-side
        recruitability check (ref: ManagementAPI changeConfig /
        excludeServers sanity checks run CLIENT side; the committed
        system keys are authoritative afterwards)."""
        st = await self.get_status()
        cl = st.get("cluster", {})
        excluded = set(cl.get("configuration", {}).get("excluded", ()))
        return sum(1 for name, w in cl.get("workers", {}).items()
                   if w.get("alive") and name not in excluded
                   and name != without)

    async def configure(self, **kwargs) -> None:
        """Change the transaction-subsystem shape (n_proxies,
        n_resolvers, n_logs, conflict_backend) by COMMITTING the new
        values into \\xff/conf/ — the proxies interpret the metadata
        mutations and the CC reacts with an epoch recovery (ref:
        ManagementAPI changeConfig building a \\xff/conf/ transaction;
        ApplyMetadataMutation.h). Validation (recruitable shape, known
        backend) runs client-side, like the reference's changeConfig."""
        updates = {k: v for k, v in kwargs.items() if v is not None}
        names = {"n_proxies", "n_resolvers", "n_logs",
                 "conflict_backend", "usable_regions"}
        if not set(updates) <= names:
            raise error("invalid_option_value")
        ints = {k: v for k, v in updates.items() if k != "conflict_backend"}
        if any(not isinstance(v, int) or v < 1 for v in ints.values()):
            raise error("invalid_option_value")
        if updates.get("conflict_backend") is not None:
            from ..models.native_backend import CONFLICT_BACKENDS
            if updates["conflict_backend"] not in CONFLICT_BACKENDS:
                raise error("invalid_option_value")
        if updates.get("usable_regions") not in (None, 1, 2):
            raise error("invalid_option_value")
        role_counts = {k: v for k, v in ints.items()
                       if k != "usable_regions"}
        if role_counts:
            live = await self._live_workers()
            if any(v > live for v in role_counts.values()):
                raise error("invalid_option_value")
        if not updates:
            return

        async def body(tr):
            tr.set_option("access_system_keys")
            for k, v in updates.items():
                key = CONF_PREFIX + CONF_ROW_BY_FIELD[k].encode()
                tr.set(key, str(v).encode())
        await run_transaction(self, body, max_retries=200)

    async def exclude(self, worker: str, exclude: bool = True) -> None:
        """Bar a worker from hosting roles by committing
        \\xff/excluded/<worker> (ref: ManagementAPI excludeServers
        writing \\xff/conf/excluded/ keys; include again clears the
        row). The leaves-recruitable safety check runs client-side,
        as the reference's does."""
        if exclude:
            st = await self.get_status()
            cfg = st.get("cluster", {}).get("configuration", {})
            need = max(cfg.get("logs", 1), cfg.get("proxies", 1),
                       cfg.get("resolvers", 1), 1)
            if await self._live_workers(without=worker) < need:
                raise error("invalid_option_value")

        async def body(tr):
            tr.set_option("access_system_keys")
            key = EXCLUDED_PREFIX + worker.encode()
            if exclude:
                tr.set(key, b"")
            else:
                tr.clear(key)
        await run_transaction(self, body, max_retries=200)

    async def change_coordinators(self, coordinators) -> None:
        """Move the coordinated state to a new coordinator set; the
        old coordinators forward until decommissioned (ref:
        ManagementAPI changeQuorum / `coordinators` in fdbcli). The
        change is durable once this returns — the move has a longer
        quorum path than other management ops, hence the wider bound."""
        from ..server.cluster_controller import ChangeCoordinatorsRequest
        if self.management_ref is None:
            raise error("client_invalid_operation")
        await flow.timeout_error(self.management_ref.get_reply(
            ChangeCoordinatorsRequest(tuple(coordinators)), self.process),
            30.0)

    @staticmethod
    def _ref_endpoint(r) -> tuple:
        ep = getattr(r, "endpoint", None)
        if ep is None:
            return (id(r),)
        return (ep.process.name, ep.token)

    async def _try_rediscover(self) -> bool:
        """Re-find the cluster controller through the coordinators
        after the one we knew stopped answering (ref: MonitorLeader's
        standing coordinator poll). Returns True when the leader moved
        and the endpoints were swapped."""
        if not self.coordinators:
            return False
        from ..server.coordination import get_leader
        li = await get_leader(self.coordinators, b"\xff/clusterLeader",
                              self.process)
        if li is None or getattr(li, "open_db", None) is None:
            return False
        if self._ref_endpoint(li.open_db) == \
                self._ref_endpoint(self.cluster_ref):
            return False
        flow.cover("client.leader_rediscovered")
        self.cluster_ref = li.open_db
        self.status_ref = li.status or self.status_ref
        self.management_ref = li.management or self.management_ref
        # broadcast sequences are per-controller: start over (the gen
        # bump tells in-flight transactions their captured seq is from
        # the dead leader)
        self._leader_gen += 1
        self._info = None
        return True

    async def info(self):
        if self._info is None:
            while True:
                try:
                    self._info = await flow.timeout_error(
                        self.cluster_ref.get_reply(
                            _OpenDatabaseRequest(-1), self.process),
                        _request_timeout())
                    break
                except flow.FdbError as e:
                    if e.name == "operation_cancelled":
                        raise
                    if await self._try_rediscover():
                        continue
                    if e.name == "timed_out" or self.coordinators:
                        await flow.delay(
                            flow.SERVER_KNOBS.client_retry_backoff_min,
                            TaskPriority.DEFAULT_ENDPOINT)
                        continue
                    raise
            # keep the picture fresh from here on: long-poll the CC's
            # broadcast so PUSHED state (failure monitor, recoveries)
            # reaches a long-lived client before — not after — it burns
            # a timeout on a known-dead endpoint (ref: MonitorLeader's
            # standing connection + FailureMonitorClient)
            if self._watch_task is None:
                self._watch_task = flow.spawn(
                    self._watch_info(), TaskPriority.DEFAULT_ENDPOINT,
                    name="client.infoWatch")
        return self._info

    async def _watch_info(self) -> None:
        while True:
            try:
                seq = self._info.seq if self._info is not None else -1
                info = await self.cluster_ref.get_reply(
                    _OpenDatabaseRequest(seq), self.process)
                if self._info is None or info.seq > self._info.seq:
                    self._info = info
            except flow.FdbError as e:
                if e.name == "operation_cancelled":
                    raise  # teardown must actually tear this down
                try:
                    await self._try_rediscover()
                except flow.FdbError as e2:
                    if e2.name == "operation_cancelled":
                        raise
                await flow.delay(flow.SERVER_KNOBS.client_rediscover_delay,
                                 TaskPriority.DEFAULT_ENDPOINT)

    def close(self) -> None:
        """Stop the standing dbinfo watcher (sim Databases are
        otherwise scheduler-lifetime objects)."""
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None

    async def refresh_past(self, used_seq: int) -> None:
        """Ensure the cached picture is newer than `used_seq` — the
        broadcast sequence the FAILED attempt actually used. Long-polls
        the CC only when the cache hasn't already moved past it: another
        transaction's retry may have refreshed first, and waiting for
        something newer than an already-current picture would deadlock
        a healthy cluster (round-3 fix)."""
        if self._info is not None and self._info.seq > used_seq:
            return
        while True:
            try:
                self._info = await flow.timeout_error(
                    self.cluster_ref.get_reply(
                        _OpenDatabaseRequest(used_seq), self.process),
                    _request_timeout())
                return
            except flow.FdbError as e:
                if e.name == "operation_cancelled":
                    raise
                if await self._try_rediscover():
                    # a NEW controller numbers its broadcasts from 1:
                    # any picture of it is newer than the dead one's
                    used_seq = -1
                    continue
                if e.name == "timed_out" or self.coordinators:
                    # CC alive but mid-recovery (keep long-polling), or
                    # dead with a successor still being elected (keep
                    # polling the coordinators) — both transient
                    await flow.delay(
                        flow.SERVER_KNOBS.client_retry_backoff_min,
                        TaskPriority.DEFAULT_ENDPOINT)
                    continue
                raise

    async def proxy(self):
        return _pick_live_proxy(await self.info())

    async def shard_for(self, key: bytes):
        info = await self.info()
        return info.storages[_shard_index(info.storages, key)]

    def batched_grv(self, priority: Optional[int] = None,
                    tags: Tuple[bytes, ...] = (),
                    weight: int = 1) -> Future:
        """Batch concurrent GRV REQUESTS into one proxy round trip PER
        PRIORITY CLASS (ref: readVersionBatcher,
        NativeAPI.actor.cpp:2854) — and per tag set, once tag
        throttling arms, so the proxy's per-tag admission gate sees the
        tags it must charge. Requests are collected for one batch
        interval and THEN fetched — a request must never join a fetch
        already in flight, or a client could receive a version
        predating its own acknowledged commit."""
        from ..server.types import PRIORITY_DEFAULT
        if priority is None:
            priority = PRIORITY_DEFAULT
        f = Future()
        key = (priority, tuple(tags))
        self._grv_waiters.setdefault(key, []).append(f)
        if weight > 1:
            # client-multiplexing (ISSUE 12): one wire GRV may stand in
            # for `weight` logical client transactions — the request's
            # transaction_count carries the full weight, so the proxy's
            # token buckets and the ratekeeper see the true offered
            # load even when a storm drives 10^6 simulated clients
            # through a handful of handles (ref: the batched
            # transaction_count in GetReadVersionRequest)
            self._grv_extra[key] = self._grv_extra.get(key, 0) \
                + (weight - 1)
        if not self._grv_timer_armed:
            self._grv_timer_armed = True
            flow.spawn(self._grv_batch_fire(),
                       TaskPriority.DEFAULT_ENDPOINT,
                       name="client.grvBatch")
        return f

    async def _grv_batch_fire(self) -> None:
        await flow.delay(SERVER_KNOBS.grv_batch_interval,
                         TaskPriority.DEFAULT_ENDPOINT)
        by_prio, self._grv_waiters = self._grv_waiters, {}
        extra, self._grv_extra = self._grv_extra, {}
        self._grv_timer_armed = False
        # classes fetch CONCURRENTLY: a throttled or dead-proxy fetch in
        # one class must not head-of-line block (or, on cancellation,
        # strand) another class's independent round trip
        for (priority, tags), waiters in by_prio.items():
            flow.spawn(self._grv_fetch_one(priority, tags, waiters,
                                           extra.get((priority, tags), 0)),
                       TaskPriority.DEFAULT_ENDPOINT,
                       name=f"client.grvFetch.p{priority}")

    async def _grv_fetch_one(self, priority: int, tags, waiters,
                             extra: int = 0) -> None:
        from ..server.types import GetReadVersionRequest
        info = None
        try:
            info = await self.info()
            proxy = await self.proxy()
            reply = await _rpc(proxy.grvs.get_reply(
                GetReadVersionRequest(len(waiters) + extra, priority, tags),
                self.process))
            windows = getattr(reply, "conflict_windows", ())
            if windows:
                if self._conflict_cache is None:
                    from ..server.scheduler import ConflictWindowCache
                    self._conflict_cache = ConflictWindowCache()
                self._conflict_cache.update(windows, flow.now())
            throttles = getattr(reply, "tag_throttles", ())
            if throttles:
                if self._tag_throttle_cache is None:
                    from ..server.tag_throttler import \
                        ClientTagThrottleCache
                    self._tag_throttle_cache = ClientTagThrottleCache()
                self._tag_throttle_cache.update(throttles, flow.now())
            for f in waiters:
                if not f.is_ready:
                    f.send((reply.version, info.seq))
        except flow.FdbError as e:
            # the batcher owns the seq its fetch used, so IT refreshes
            # the shared picture before failing the waiters — their
            # retries then run against the healed cluster (individual
            # transactions no longer see the seq on this path)
            if info is not None and e.name in REFRESH_ERRORS:
                try:
                    await self.refresh_past(info.seq)
                except flow.FdbError:
                    pass
            for f in waiters:
                if not f.is_ready:
                    f.send_error(e)
        except BaseException:
            # anything else (cancellation, internal error) must not
            # strand the swapped-out waiters in a silent deadlock
            for f in waiters:
                if not f.is_ready:
                    f.send_error(error("operation_failed"))
            raise

    async def honor_tag_throttles(self, tags,
                                  max_delay: Optional[float] = None) -> None:
        """Client-honored backoff (ref: the client-side tag-throttle
        delay in NativeAPI's readVersionBatcher): a tag the server
        advertised as throttled paces itself locally BEFORE the next
        GRV, so the shed work never reaches the proxy's queue at all.
        `max_delay` clips one wait (a transaction's TIMEOUT deadline
        must not be slept through before its own machinery can fire).
        Zero-cost until a throttle-carrying reply created the cache."""
        cache = self._tag_throttle_cache
        if cache is None:
            return
        d = cache.delay(tags, flow.now())
        if max_delay is not None:
            d = min(d, max(0.0, max_delay))
        if d > 0:
            from ..server.tag_throttler import note_backoff
            flow.cover("client.tag_backoff")
            note_backoff(d)
            await flow.delay(d, TaskPriority.DEFAULT_ENDPOINT)

    def create_transaction(self) -> "Transaction":
        return Transaction(self)

    def _maybe_sample(self):
        """The PROFILE_SAMPLE_RATE sampling decision for one fresh
        transaction (ref: NativeAPI's CSI sampling). Deterministic:
        hashes this database's transaction ordinal with a salt derived
        from the seeded RNG and the client's process name — no RNG
        state is consumed, so sampling never perturbs the simulation's
        event order. Only called when the rate knob is nonzero."""
        from . import profiling
        self._txn_seq += 1
        rate = float(flow.SERVER_KNOBS.profile_sample_rate)
        if self._profile_salt is None:
            import zlib
            # remote (TCP) clients have no sim process: a fixed name
            # keeps the decision well-defined there too
            name = (self.process.name if self.process is not None
                    else "remote-client")
            self._profile_salt = profiling._mix64(
                flow.g_random.seed ^ zlib.crc32(name.encode()))
        if not profiling.sample_decision(self._profile_salt,
                                         self._txn_seq, rate):
            return None
        profiling.note_sampled()
        rec_id = "%08x%016x" % (self._profile_salt & 0xFFFFFFFF,
                                self._txn_seq)
        return profiling.TransactionProfile(rec_id, flow.now())


def _shard_index(storages, key: bytes) -> int:
    """Last shard whose begin <= key (storages sorted by begin)."""
    for i in range(len(storages) - 1, -1, -1):
        if key >= storages[i].begin:
            return i
    return 0


def _overlapping_shards(storages, begin: bytes, end: bytes):
    out = []
    for s in storages:
        s_end = s.end
        if (s_end is None or begin < s_end) and s.begin < end:
            out.append(s)
    return out


class Transaction:
    def __init__(self, db: Database, sampled: bool = True):
        self.db = db
        # sampled=False marks internal transactions (the profile flush
        # writer) that must never themselves be profiled
        self.reset()
        # the sampling decision runs ONCE per logical transaction, at
        # creation: when the rate knob is 0 (the default) this is one
        # attribute read and a falsy test — the provably-zero-overhead
        # gate the bench relies on
        if sampled and flow.SERVER_KNOBS.profile_sample_rate:
            self._profile = db._maybe_sample()

    def set_option(self, option: str, value=None) -> None:
        """(ref: fdb_transaction_set_option — the subset with behavior
        here: ACCESS_SYSTEM_KEYS admits \\xff\\x02 writes; TIMEOUT
        bounds the transaction INCLUDING retries in seconds;
        RETRY_LIMIT caps on_error resets. Timeout/retry state survives
        reset() the way the reference's options do.)"""
        from ..server.types import PRIORITY_BATCH, PRIORITY_IMMEDIATE
        if option == "access_system_keys":
            self._access_system = True
            self._read_system = True
        elif option == "read_system_keys":
            # read-only admission to \xff (ref: READ_SYSTEM_KEYS)
            self._read_system = True
        elif option in ("timeout", "retry_limit"):
            try:
                value = float(value) if option == "timeout" else int(value)
            except (TypeError, ValueError):
                raise error("invalid_option_value") from None
            # fdb sentinels: 0 disables the timeout; a negative retry
            # limit means unlimited
            if option == "timeout":
                self._timeout_seconds = value if value > 0 else None
                self._timeout_deadline = (flow.now() + value
                                          if value > 0 else None)
            else:
                self._retry_limit = value if value >= 0 else None
        elif option == "debug_transaction_identifier":
            # sampled-transaction stitching (ref: the TransactionDebug
            # attach + per-station events through the commit path)
            self._debug_id = value
        elif option == "transaction_logging_enable":
            # force-sample THIS transaction regardless of the
            # database-level rate (ref: TRANSACTION_LOGGING_ENABLE with
            # an optional identifier). The identifier becomes the
            # record id in \xff\x02/fdbClientInfo/client_latency/, so
            # it may not contain the key schema's field separator.
            if self._profile is None:
                from . import profiling
                self.db._txn_seq += 1
                # the ordinal suffix keeps two transactions armed with
                # the SAME identifier in the same sim tick from
                # colliding on record keys (same start_ts + rec_id
                # would silently overwrite)
                ident = "%s-%08x" % (
                    str(value).replace("/", "_") if value else "opt",
                    self.db._txn_seq)
                profiling.note_sampled()
                self._profile = profiling.TransactionProfile(
                    ident, flow.now())
        elif option == "automatic_repair":
            # the transaction-repair contract (server/repair.py): the
            # client declares its read-set is fully recorded as read
            # conflicts and its writes do not depend on read VALUES
            # (atomic ops, blind sets/clears), so a conflicted commit
            # may be repaired server-side — invalidated reads
            # re-executed at the conflict version and the commit
            # revalidated — instead of aborting. The server verifies
            # what it can (mutation types) and falls back to the
            # ordinary abort otherwise; with TXN_REPAIR off the flag
            # rides the wire inert.
            self._repairable = True
        elif option == "report_conflicting_keys":
            # a conflicted commit surfaces WHICH read ranges aborted it
            # (ref: the REPORT_CONFLICTING_KEYS option + the
            # \xff\xff/transaction/conflicting_keys/ special keyspace);
            # read back via get_conflicting_ranges() after not_committed
            self._report_conflicting = True
        elif option == "priority_batch":
            self._grv_priority = PRIORITY_BATCH
        elif option == "priority_system_immediate":
            self._grv_priority = PRIORITY_IMMEDIATE
        elif option == "grv_batch_weight":
            # this transaction's GRV stands in for `value` logical
            # client transactions (storm client-multiplexing — the wire
            # request's transaction_count carries the full weight so
            # admission control charges the true offered load)
            try:
                weight = int(value)
            except (TypeError, ValueError):
                raise error("invalid_option_value")
            if weight < 1:
                raise error("invalid_option_value")
            self._grv_weight = weight
        elif option == "transaction_tag":
            # tag this transaction for the proxy's per-tag traffic
            # accounting (and the tag throttling that will ride it;
            # ref: the TAG transaction option / TagSet — bounded count
            # and length, duplicates collapse)
            if isinstance(value, str):
                value = value.encode()
            if not isinstance(value, bytes) or not value:
                raise error("invalid_option_value")
            if len(value) > int(
                    flow.SERVER_KNOBS.max_transaction_tag_length):
                raise error("tag_too_long")
            tags = getattr(self, "_tags", ())
            if value not in tags:
                if len(tags) >= int(
                        flow.SERVER_KNOBS.max_tags_per_transaction):
                    raise error("too_many_tags")
                self._tags = tags + (value,)
        else:
            raise error("invalid_option_value")

    def _rpc(self, fut: Future) -> Future:
        """Per-request timeout, clipped to the transaction's TIMEOUT
        deadline so an in-flight stall can't overshoot the configured
        bound by a whole request timeout (review r3)."""
        deadline = getattr(self, "_timeout_deadline", None)
        if deadline is None:
            return _rpc(fut)
        remaining = deadline - flow.now()
        if remaining <= 0:
            fut.abandon()
            return flow.error_future(error("transaction_timed_out"))
        if remaining >= _request_timeout():
            return _rpc(fut)
        return flow.timeout_error(fut, remaining, "transaction_timed_out")

    def _check_writable(self, begin: bytes,
                        end: Optional[bytes] = None) -> None:
        """ACCESS_SYSTEM_KEYS admits the STORED system region
        [\\xff\\x02, \\xff\\xff) — conf/excluded/backup/latency-probe
        rows are real transactional data there — but never the
        materialized \\xff/keyServers/ view (a write there would commit
        into a space reads never consult, a silent black hole) and
        never \\xff\\xff engine metadata."""
        sys_ok = getattr(self, "_access_system", False)
        if end is None:  # point write
            if begin.startswith(SYSTEM_PREFIX) and not (
                    sys_ok and _is_stored_system(begin)):
                raise error("key_outside_legal_range")
        else:            # range [begin, end): end is exclusive
            if begin.startswith(SYSTEM_PREFIX) or end > SYSTEM_PREFIX:
                if not (sys_ok and STORED_SYSTEM_PREFIX <= begin
                        and end <= ENGINE_PREFIX
                        and not (begin < KEY_SERVERS_END
                                 and end > KEY_SERVERS_PREFIX)):
                    raise error("key_outside_legal_range")

    def reset(self) -> None:
        self._access_system = False   # options reset with the txn
        self._read_system = False
        self._debug_id = None
        self._profile = None          # re-armed by __init__/set_option
        self._grv_priority = None     # ...including the priority class
        self._grv_weight = 1          # ...and the multiplexing weight
        self._tags = ()               # ...and the transaction tags
        self._report_conflicting = False
        self._repairable = False      # automatic_repair declaration
        self._conflicting_ranges = None   # last conflicted commit's causes
        # timeout/retry OPTIONS survive an explicit reset, but their
        # spent budgets re-arm — a reused object starts a fresh logical
        # transaction (ref: fdb reset semantics)
        self._retries_used = 0
        if getattr(self, "_timeout_seconds", None) is not None:
            self._timeout_deadline = flow.now() + self._timeout_seconds
        self._used_seq: int = 0       # newest dbinfo seq this attempt saw
        # broadcast sequences are per-controller: remember WHICH leader
        # the seq came from, so a retry after a failover never long-polls
        # the new controller for the dead one's sequence numbers
        self._used_leader_gen: int = getattr(self.db, "_leader_gen", 0)
        self._read_version: Optional[int] = None
        self._writes: Dict[bytes, Optional[bytes]] = {}  # RYW write map
        self._write_order: List[bytes] = []              # sorted keys
        self._cleared: List[Tuple[bytes, bytes]] = []    # ordered clears
        self._ops: Dict[bytes, List[Tuple[int, bytes]]] = {}  # pending atomics
        self._mutations: List[MutationRef] = []
        self._read_conflicts: List[Tuple[bytes, bytes]] = []
        self._write_conflicts: List[Tuple[bytes, bytes]] = []
        self._watches: List[Tuple[bytes, Future]] = []
        self._txn_bytes = 0
        self.committed_version: Optional[int] = None
        self.committed_batch_index: Optional[int] = None

    async def _get_info(self):
        """Cluster picture for this attempt, recording the seq so
        on_error knows which picture actually failed."""
        info = await self.db.info()
        if info.seq > self._used_seq:
            self._used_seq = info.seq
        return info

    async def _proxy(self):
        return _pick_live_proxy(await self._get_info())

    async def _shard(self, key: bytes):
        info = await self._get_info()
        return info.storages[_shard_index(info.storages, key)]

    async def _storage_rpc(self, shard, fn):
        """Latency-modeled replica selection with backup requests (ref:
        fdbrpc/LoadBalance.actor.h — alternatives ordered by measured
        latency; a slow first choice gets a duplicate request to the
        next alternative and the first reply wins; connection-class
        failures penalize the replica's model and rotate on)."""
        db = self.db
        ema = db._latency_ema
        info = await self._get_info()
        down = set(info.failed)
        reps = list(shard.replicas)
        start = flow.g_random.random_int(0, len(reps))
        reps = reps[start:] + reps[:start]     # tie-break rotation
        # replicas the failure monitor pushed as DOWN sort last: they
        # stay reachable as a final fallback but never burn the first
        # attempt's latency (ref: FailureMonitorClient-informed
        # LoadBalance ordering)
        reps.sort(key=lambda r: (r.name in down,
                                 ema.get(r.name, 0.0)))  # stable sort
        inflight = []   # (replica, settled-wrapper, t0)
        last_err = None
        idx = 0
        while True:
            if not inflight:
                if idx >= len(reps):
                    raise last_err or error("all_alternatives_failed")
                rep = reps[idx]
                idx += 1
                inflight.append((rep, flow.catch_errors(self._rpc(fn(rep))),
                                 flow.now()))
            race = [w for _, w, _ in inflight]
            if idx < len(reps):
                race.append(flow.delay(
                    SERVER_KNOBS.load_balance_backup_delay))
            i, settled = await flow.first_of(*race)
            if i >= len(inflight):
                # backup window elapsed: duplicate to the next replica
                rep = reps[idx]
                idx += 1
                inflight.append((rep, flow.catch_errors(self._rpc(fn(rep))),
                                 flow.now()))
                continue
            rep, _w, t0 = inflight.pop(i)
            if not settled.is_error:
                db.note_latency(rep.name, flow.now() - t0)
                # abandoned rivals still pay: elapsed-so-far is a true
                # lower bound on their latency — without it a slow
                # replica never enters the model and (defaulting to 0)
                # would sort FIRST on every later read
                for lrep, _lw, lt0 in inflight:
                    db.note_latency(lrep.name, flow.now() - lt0)
                return settled.get()
            e = settled.exception()
            if e.name not in ("broken_promise", "timed_out"):
                raise e
            db.note_latency(rep.name, _request_timeout())  # penalty
            last_err = e

    # -- read version ---------------------------------------------------
    async def get_read_version(self) -> int:
        if self._read_version is None:
            prof = self._profile
            t0 = flow.now() if prof is not None else 0.0
            # tags ride the GRV request ONLY while tag throttling is
            # armed (one knob read) — the off-posture wire request is
            # byte-identical to the pre-subsystem one. A throttled tag
            # delays locally first; immediate-priority traffic is
            # never tag-throttled (matching the server's gate).
            grv_tags: tuple = ()
            if flow.SERVER_KNOBS.tag_throttling:
                grv_tags = tuple(getattr(self, "_tags", ()))
                if grv_tags:
                    from ..server.types import PRIORITY_IMMEDIATE
                    if getattr(self, "_grv_priority", None) != \
                            PRIORITY_IMMEDIATE:
                        # a TIMEOUT-bounded transaction never sleeps
                        # past its own deadline honoring a throttle
                        ddl = getattr(self, "_timeout_deadline", None)
                        await self.db.honor_tag_throttles(
                            grv_tags,
                            None if ddl is None else ddl - flow.now())
            fut = self.db.batched_grv(getattr(self, "_grv_priority", None),
                                      grv_tags,
                                      getattr(self, "_grv_weight", 1))
            deadline = getattr(self, "_timeout_deadline", None)
            if deadline is not None:
                # the shared class fetch continues for other waiters;
                # only THIS transaction's wait is deadline-bounded
                fut = flow.timeout_error(
                    fut, max(deadline - flow.now(), 0.001),
                    "transaction_timed_out")
            try:
                version, seq = await fut
            except flow.FdbError as e:
                if prof is not None:
                    from .profiling import ErrorEvent
                    prof.add(ErrorEvent(t0, "grv", e.name))
                raise
            if prof is not None:
                from .profiling import GetVersionEvent
                from ..server.types import PRIORITY_DEFAULT
                prio = getattr(self, "_grv_priority", None)
                # explicit None test: PRIORITY_BATCH is 0 and must not
                # fall through to the default label
                prof.add(GetVersionEvent(
                    t0, flow.now() - t0,
                    PRIORITY_DEFAULT if prio is None else prio))
            if seq > self._used_seq:
                self._used_seq = seq
            self._read_version = version
        return self._read_version

    # -- RYW overlay ----------------------------------------------------
    def _overlay_get(self, key: bytes):
        """(found, value) against uncommitted writes, newest-first."""
        if key in self._writes:
            return True, self._writes[key]
        for b, e in reversed(self._cleared):
            if b <= key < e:
                return True, None
        return False, None

    # -- system keyspace -------------------------------------------------
    async def _system_rows(self) -> List[Tuple[bytes, bytes]]:
        """The MATERIALIZED system rows, sorted: only the keyServers
        map is synthesized from the broadcast picture — conf/excluded
        are real stored rows committed through the pipeline (ref:
        SystemData.cpp; round-4 VERDICT Missing #7: \\xff as the
        coordination medium, not a read-only view)."""
        info = await self._get_info()
        rows = [(KEY_SERVERS_PREFIX + s.begin,
                 b",".join(r.name.encode() for r in s.replicas))
                for s in info.storages]
        rows.sort()
        return rows

    async def _system_get(self, key: bytes) -> Optional[bytes]:
        if key.startswith(KEY_SERVERS_PREFIX):
            # the team owning an arbitrary key (ref: keyServers reads)
            k = key[len(KEY_SERVERS_PREFIX):]
            info = await self._get_info()
            s = info.storages[_shard_index(info.storages, k)]
            return b",".join(r.name.encode() for r in s.replicas)
        for rk, rv in await self._system_rows():
            if rk == key:
                return rv
        return None

    # -- reads ----------------------------------------------------------
    def _read_tags(self) -> tuple:
        """Transaction tags for the storage server's read-cost
        accounting — attached only while the storage heat plane is
        armed, so the read requests stay byte-identical to the
        pre-plane ones otherwise (the GRV-tag contract)."""
        if flow.SERVER_KNOBS.storage_heat_tracking:
            return tuple(getattr(self, "_tags", ()))
        return ()

    async def _base_get(self, key: bytes) -> Optional[bytes]:
        found, val = self._overlay_get(key)
        if found:
            return val
        version = await self.get_read_version()
        shard = await self._shard(key)
        debug_id = getattr(self, "_debug_id", None)
        if debug_id is not None:
            # sampled-read stitching (ref: the GetValueDebug stations,
            # NativeAPI getValue Before/After around the storage leg)
            flow.g_trace_batch.add_event("GetValueDebug", debug_id,
                                         "NativeAPI.getValue.Before")
        ok = False
        try:
            val = await self._storage_rpc(
                shard, lambda rep: rep.gets.get_reply(
                    StorageGetRequest(key, version, debug_id,
                                      self._read_tags()),
                    self.db.process))
            ok = True
        finally:
            if debug_id is not None:
                # every exit path — success, FdbError, cancellation —
                # closes the Before station: a duration-pairing
                # consumer must never see a dangling Before (ref: the
                # getValue error station)
                flow.g_trace_batch.add_event(
                    "GetValueDebug", debug_id,
                    "NativeAPI.getValue.After" if ok
                    else "NativeAPI.getValue.Error")
        return val

    async def get(self, key: bytes, snapshot: bool = False) -> Optional[bytes]:
        prof = self._profile
        if prof is None:
            return await self._get_impl(key, snapshot)
        from .profiling import ErrorEvent, GetEvent
        t0 = flow.now()
        try:
            val = await self._get_impl(key, snapshot)
        except flow.FdbError as e:
            prof.add(ErrorEvent(t0, "get", e.name))
            raise
        prof.add(GetEvent(t0, flow.now() - t0, key,
                          -1 if val is None else len(val)))
        return val

    async def _get_impl(self, key: bytes,
                        snapshot: bool = False) -> Optional[bytes]:
        if key.startswith(SYSTEM_PREFIX):
            # \xff reads need READ/ACCESS_SYSTEM_KEYS (ref: NativeAPI
            # validateKey — key_outside_legal_range without the option)
            if not getattr(self, "_read_system", False):
                raise error("key_outside_legal_range")
            if not _is_stored_system(key):
                return await self._system_get(key)
        if not snapshot:
            self._read_conflicts.append((key, _next_key(key)))
        val = await self._base_get(key)
        # pending atomic ops computed over the base (ref: RYW reads of
        # atomically-modified keys, ReadYourWrites.actor.cpp)
        for op, param in self._ops.get(key, ()):
            val = _ATOMIC_APPLY[op](val, param)
        return val

    async def get_key(self, selector: KeySelector,
                      snapshot: bool = False) -> bytes:
        """Resolve a key selector against the READ-YOUR-WRITES view —
        the merged stream of committed data, materialized system rows,
        and this transaction's uncommitted writes/clears (ref:
        ReadYourWrites getKey through RYWIterator; found as a
        divergence by the WriteDuringRead model checker: the old path
        resolved against storage alone). All anchors resolve via
        bounded merged scans over get_range, so get_key always agrees
        with what range reads enumerate; READ_SYSTEM_KEYS widens the
        walk to the system region."""
        # anchor == b"\xff" (allKeys.end) stays legal without the option
        # — last_less_than(\xff) is the canonical "last key" idiom, the
        # same exclusive-end convention the range gate honors
        read_sys = getattr(self, "_read_system", False)
        if selector.key.startswith(SYSTEM_PREFIX) and \
                selector.key != SYSTEM_PREFIX and not read_sys:
            raise error("key_outside_legal_range")
        hi_bound = ENGINE_PREFIX if read_sys else SYSTEM_PREFIX
        anchor = (selector.key + b"\x00" if selector.or_equal
                  else selector.key)
        if selector.offset >= 1:
            # the offset-th present merged key >= anchor
            b = min(anchor, hi_bound)
            rows = []
            if b < hi_bound:
                rows = await self._get_range_impl(b, hi_bound,
                                            limit=selector.offset,
                                            snapshot=True)
            resolved = (rows[selector.offset - 1][0]
                        if len(rows) >= selector.offset else hi_bound)
        else:
            # the (1-offset)-th present merged key < anchor
            needed = 1 - selector.offset
            e = min(anchor, hi_bound)
            rows = []
            if e > b"":
                rows = await self._get_range_impl(b"", e, limit=needed,
                                            snapshot=True, reverse=True)
            resolved = (rows[needed - 1][0] if len(rows) >= needed
                        else b"")
        # without READ_SYSTEM_KEYS a selector walking off the end of
        # user space clamps to maxKey instead of leaking stored \xff
        # rows (ref: getKey clamps at allKeys.end)
        if resolved > SYSTEM_PREFIX and not read_sys:
            resolved = SYSTEM_PREFIX
        if not snapshot:
            lo = min(resolved, selector.key)
            hi = max(resolved, selector.key)
            self._read_conflicts.append((lo, _next_key(hi)))
        return resolved

    async def get_range(self, begin, end, limit: int = UNBOUNDED_ROW_LIMIT,
                        snapshot: bool = False,
                        reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        prof = self._profile
        if prof is None:
            return await self._get_range_impl(begin, end, limit,
                                              snapshot, reverse)
        from .profiling import ErrorEvent, GetRangeEvent
        t0 = flow.now()
        try:
            rows = await self._get_range_impl(begin, end, limit,
                                              snapshot, reverse)
        except flow.FdbError as e:
            prof.add(ErrorEvent(t0, "get_range", e.name))
            raise
        prof.add(GetRangeEvent(
            t0, flow.now() - t0,
            begin.key if isinstance(begin, KeySelector) else begin,
            end.key if isinstance(end, KeySelector) else end, len(rows)))
        return rows

    async def _get_range_impl(self, begin, end,
                              limit: int = UNBOUNDED_ROW_LIMIT,
                              snapshot: bool = False,
                              reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        if isinstance(begin, KeySelector):
            begin = await self.get_key(begin, snapshot=snapshot)
        if isinstance(end, KeySelector):
            end = await self.get_key(end, snapshot=snapshot)
        if begin >= end:
            return []
        if not getattr(self, "_read_system", False):
            # [begin, end) must stay inside user space (ref: NativeAPI
            # validateKeyRange — key_outside_legal_range beyond \xff
            # without READ/ACCESS_SYSTEM_KEYS)
            if begin.startswith(SYSTEM_PREFIX) or end > SYSTEM_PREFIX:
                raise error("key_outside_legal_range")
        elif end > ENGINE_PREFIX:
            raise error("key_outside_legal_range")
        elif not begin.startswith(SYSTEM_PREFIX) and end > SYSTEM_PREFIX:
            # a scan crossing from user space into \xff must see the
            # SAME system rows an \xff-anchored scan serves (materialized
            # + stored) — split at the boundary and merge
            rows = await self._get_range_impl(begin, SYSTEM_PREFIX, limit=limit,
                                        snapshot=snapshot, reverse=reverse)
            rows += await self._get_range_impl(SYSTEM_PREFIX, end, limit=limit,
                                         snapshot=snapshot, reverse=reverse)
            return sorted(rows, reverse=reverse)[:limit]
        if begin.startswith(SYSTEM_PREFIX) and (
                not _is_stored_system(begin)
                or (begin < KEY_SERVERS_END and end > KEY_SERVERS_PREFIX)):
            # the range touches the materialized keyServers view (or
            # starts below the stored region): merge the synthesized
            # rows with the stored subranges around the keyServers hole
            rows = [(k, v) for k, v in await self._system_rows()
                    if begin <= k < end]
            lo = max(begin, STORED_SYSTEM_PREFIX)
            hi = min(end, ENGINE_PREFIX)
            for b2, e2 in ((lo, min(hi, KEY_SERVERS_PREFIX)),
                           (max(lo, KEY_SERVERS_END), hi)):
                if b2 < e2:
                    rows += await self._get_range_impl(b2, e2,
                                                 snapshot=snapshot)
            return sorted(rows, reverse=reverse)[:limit]
        version = await self.get_read_version()
        # With no RYW overlay in the range the storage servers honor the
        # caller's limit/reverse directly. Overlay writes/atomics remove
        # at most one base row each, so the base fetch stays BOUNDED at
        # limit + overlay count (in the requested direction — the
        # truncated prefix then provably contains the merged top-limit
        # rows). Only a clear intersecting the range can delete
        # unboundedly many base rows and forces the full fetch
        # (ref: RYWIterator reads through the WriteMap instead).
        lo = bisect_left(self._write_order, begin)
        hi = bisect_left(self._write_order, end)
        n_ops = sum(1 for k in self._ops if begin <= k < end)
        clear_in_range = any(b < end and e > begin
                             for b, e in self._cleared)
        has_overlay = bool(hi > lo or n_ops or clear_in_range)
        if clear_in_range:
            fetch_limit, fetch_rev = UNBOUNDED_ROW_LIMIT, False
        elif has_overlay:
            fetch_limit = min(limit + (hi - lo) + n_ops,
                              UNBOUNDED_ROW_LIMIT)
            fetch_rev = reverse
        else:
            fetch_limit, fetch_rev = limit, reverse
        base = await self._fetch_range(begin, end, version, fetch_limit,
                                       fetch_rev)
        # overlay uncommitted writes (ref: RYWIterator merge)
        merged: Dict[bytes, bytes] = {k: v for k, v in base}
        for b, e in self._cleared:
            for k in [k for k in merged if b <= k < e]:
                del merged[k]
        for k in self._write_order[lo:hi]:
            v = self._writes[k]
            if v is None:
                merged.pop(k, None)
            else:
                merged[k] = v
        # keys with pending atomic ops materialize from their base value
        for k, ops in self._ops.items():
            if begin <= k < end:
                val = merged.get(k)
                if val is None and k not in self._writes and \
                        not any(b <= k < e for b, e in self._cleared):
                    shard = await self._shard(k)
                    val = await self._storage_rpc(
                        shard, lambda rep, k=k: rep.gets.get_reply(
                            StorageGetRequest(k, version,
                                              tags=self._read_tags()),
                            self.db.process))
                for op, param in ops:
                    val = _ATOMIC_APPLY[op](val, param)
                if val is None:
                    merged.pop(k, None)
                else:
                    merged[k] = val
        out = sorted(merged.items(), reverse=reverse)[:limit]
        if not snapshot:
            # record only the observed portion: when the limit truncates,
            # keys past the last returned row were never promised to the
            # caller (ref: record-what-was-read conflict semantics,
            # NativeAPI getRange → tr.addReadConflictRange of the
            # readThrough bound)
            if len(out) == limit and out:
                if reverse:
                    self._read_conflicts.append((out[-1][0], end))
                else:
                    self._read_conflicts.append((begin, _next_key(out[-1][0])))
            else:
                self._read_conflicts.append((begin, end))
        return out

    async def _fetch_range(self, begin: bytes, end: bytes, version: int,
                           limit: int, reverse: bool):
        """Fan a range read across the shards it overlaps, honoring the
        limit shard by shard (ref: NativeAPI getRange iterating the
        location cache)."""
        info = await self._get_info()
        shards = _overlapping_shards(info.storages, begin, end)
        if reverse:
            shards = shards[::-1]
        # the piece of [begin, end) each shard owns
        clamped = [(s, max(begin, s.begin),
                    end if s.end is None else min(end, s.end))
                   for s in shards]
        if limit >= UNBOUNDED_ROW_LIMIT and len(shards) > 1:
            # effectively-unbounded scan: fan the shards out in
            # PARALLEL and concatenate in shard order — the limit can't
            # truncate, so per-shard requests are independent (ref:
            # NativeAPI getRange issuing parallel requests when limits
            # permit). The race settles on the FIRST error (the serial
            # path's prompt-retry behavior) and cancels the rest.
            rtags = self._read_tags()
            futs = [flow.spawn(self._storage_rpc(
                s, lambda rep, b=b, e=e: rep.ranges.get_reply(
                    StorageGetRangeRequest(b, e, version, limit, reverse,
                                           rtags),
                    self.db.process))) for s, b, e in clamped]
            wrappers = [flow.catch_errors(f) for f in futs]
            results: List = [None] * len(futs)
            pending = set(range(len(futs)))
            try:
                while pending:
                    order = sorted(pending)
                    i, settled = await flow.first_of(
                        *[wrappers[j] for j in order])
                    idx = order[i]
                    pending.discard(idx)
                    if settled.is_error:
                        raise settled.exception()
                    results[idx] = settled.get()
            finally:
                for f in futs:
                    if not f.is_ready:
                        f.cancel()
            out: List[Tuple[bytes, bytes]] = []
            for part in results:
                out.extend(part)
            return out
        out = []
        rtags = self._read_tags()
        for _s, b, e in clamped:
            part = await self._storage_rpc(
                _s, lambda rep, b=b, e=e: rep.ranges.get_reply(
                    StorageGetRangeRequest(b, e, version, limit - len(out),
                                           reverse, rtags),
                    self.db.process))
            out.extend(part)
            if len(out) >= limit:
                break
        return out

    # -- writes ---------------------------------------------------------
    def _check_sizes(self, key: bytes, value: bytes = b"",
                     slack: int = 0) -> None:
        """(ref: NativeAPI size checks — key_too_large /
        value_too_large raised client-side before anything ships).
        `slack` admits synthesized range-end bounds like keyAfter(k),
        which may run one byte past the user key limit."""
        if len(key) > SERVER_KNOBS.key_size_limit + slack:
            raise error("key_too_large")
        if len(value) > SERVER_KNOBS.value_size_limit:
            raise error("value_too_large")
        self._txn_bytes += len(key) + len(value)
        if self._txn_bytes > SERVER_KNOBS.transaction_size_limit:
            raise error("transaction_too_large")

    def _record_write(self, key: bytes, value: Optional[bytes]) -> None:
        if key not in self._writes:
            insort(self._write_order, key)
        self._writes[key] = value

    def set(self, key: bytes, value: bytes) -> None:
        self._check_writable(key)
        self._check_sizes(key, value)
        self._record_write(key, value)
        self._ops.pop(key, None)  # a set supersedes pending atomics
        self._mutations.append(MutationRef(SET_VALUE, key, value))
        self._write_conflicts.append((key, _next_key(key)))

    def clear(self, key: bytes) -> None:
        self.clear_range(key, _next_key(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        if begin >= end:
            return
        self._check_writable(begin, end)
        self._check_sizes(begin)
        self._check_sizes(end, slack=1)  # keyAfter(max-size key) is legal
        self._cleared.append((begin, end))
        lo = bisect_left(self._write_order, begin)
        hi = bisect_left(self._write_order, end)
        for k in self._write_order[lo:hi]:
            self._writes[k] = None
        for k in [k for k in self._ops if begin <= k < end]:
            del self._ops[k]
        self._mutations.append(MutationRef(CLEAR_RANGE, begin, end))
        self._write_conflicts.append((begin, end))

    def atomic_op(self, key: bytes, param: bytes, op_type: int) -> None:
        """(ref: Transaction::atomicOp / fdbclient/Atomic.h op table)"""
        self._check_writable(key)
        self._check_sizes(key, param)
        if op_type in (SET_VERSIONSTAMPED_KEY, SET_VERSIONSTAMPED_VALUE):
            # transformed at the proxy with the commit version; the
            # operand's trailing 4 bytes are the placeholder offset
            self._mutations.append(MutationRef(op_type, key, param))
            wkey = key[:-4] if op_type == SET_VERSIONSTAMPED_KEY else key
            self._write_conflicts.append((wkey, _next_key(wkey)))
            return
        if op_type not in ATOMIC_OPS:
            raise error("client_invalid_operation")
        # a set/clear'd key has a known value: fold the op in directly
        found, cur = self._overlay_get(key)
        if found and key not in self._ops:
            result = _ATOMIC_APPLY[op_type](cur, param)
            if result is None:
                self._record_write(key, None)
                self._mutations.append(
                    MutationRef(CLEAR_RANGE, key, _next_key(key)))
            else:
                self._record_write(key, result)
                self._mutations.append(MutationRef(SET_VALUE, key, result))
        else:
            self._ops.setdefault(key, []).append((op_type, param))
            self._mutations.append(MutationRef(op_type, key, param))
        self._write_conflicts.append((key, _next_key(key)))

    def watch(self, key: bytes) -> Future:
        """Future that fires when the key's value changes after this
        transaction commits (ref: Transaction::watch / storage watches).
        Errors with transaction_cancelled if the commit fails."""
        # same gate as reads: only the stored system region is
        # watchable, and only with the system-keys option (the
        # materialized \xff ranges have no storage to watch)
        if key.startswith(SYSTEM_PREFIX) and not (
                getattr(self, "_read_system", False)
                and _is_stored_system(key)):
            raise error("key_outside_legal_range")
        f = Future()
        self._watches.append((key, f))
        return f

    # -- commit ---------------------------------------------------------
    async def commit(self) -> int:
        """(ref: Transaction::commit :2710 / tryCommit :2498)"""
        prof = self._profile
        if prof is None:
            return await self._commit_impl()
        # sampled: record the commit outcome — latency, payload size,
        # and the conflict verdict (reusing the resolver's attribution
        # when report_conflicting_keys is armed) — then drain the
        # event stream into the \xff\x02/fdbClientInfo/ keyspace in
        # the background (ref: the sampled-commit EventCommit /
        # EventCommitError records)
        from .profiling import (CommitEvent, ErrorEvent, flush_profile)
        t0 = flow.now()
        n_mut, n_bytes = len(self._mutations), self._txn_bytes
        writes = tuple(self._write_conflicts)
        try:
            version = await self._commit_impl()
        except flow.FdbError as e:
            if e.name == "not_committed":
                prof.add(CommitEvent(
                    t0, flow.now() - t0, n_mut, n_bytes, writes,
                    "conflicted", 0,
                    tuple(self._conflicting_ranges or ())))
            else:
                prof.add(ErrorEvent(t0, "commit", e.name))
            raise
        finally:
            flow.spawn(flush_profile(self.db, prof),
                       TaskPriority.LOW_PRIORITY,
                       name="client.profileFlush")
        prof.add(CommitEvent(t0, flow.now() - t0, n_mut, n_bytes,
                             writes, "committed", version, ()))
        return version

    async def _commit_impl(self) -> int:
        if not self._mutations:
            # read-only: succeeds at the read version without a round trip
            self.committed_version = self._read_version or 0
            self._arm_watches(self.committed_version)
            return self.committed_version
        snapshot = await self.get_read_version()
        self._conflicting_ranges = None   # a fresh attempt's outcome only
        debug_id = getattr(self, "_debug_id", None)
        span = None
        if debug_id is not None:
            flow.g_trace_batch.add_event("CommitDebug", debug_id,
                                         "NativeAPI.commit.Before")
            # root of the commit span tree: every server leg opened
            # while this is in flight parents (transitively) onto it
            span = flow.g_trace_batch.begin_span(debug_id,
                                                 "NativeAPI.commit")
        from ..server.types import PRIORITY_DEFAULT as _PRIO_DEFAULT
        prio = getattr(self, "_grv_priority", None)
        req = CommitRequest(snapshot, tuple(self._read_conflicts),
                            tuple(self._write_conflicts),
                            tuple(self._mutations), debug_id=debug_id,
                            report_conflicting_keys=getattr(
                                self, "_report_conflicting", False),
                            priority=(_PRIO_DEFAULT if prio is None
                                      else prio),
                            tags=tuple(getattr(self, "_tags", ())),
                            repairable=getattr(self, "_repairable",
                                               False))
        try:
            # client-side early abort (server/scheduler.py conflict
            # windows): raised INSIDE this try, so watches, trace
            # stations and profiling see exactly what a resolver abort
            # produces — indistinguishable to retry loops by design
            self._check_conflict_windows(snapshot)
            proxy = await self._proxy()
            reply = await self._rpc(
                proxy.commits.get_reply(req, self.db.process))
            from ..server.types import CommitConflictReply
            if isinstance(reply, CommitConflictReply):
                # a reported conflict arrives as a VALUE carrying the
                # attributed ranges; record them and raise the same
                # retryable error a non-reporting commit would see
                self._conflicting_ranges = tuple(reply.conflicting_ranges)
                raise error("not_committed")
        except flow.FdbError as e:
            for _k, f in self._watches:
                if not f.is_ready:
                    f.send_error(error("transaction_cancelled"))
            if debug_id is not None:
                # close the Before station on failure (conflict,
                # unknown result, ...): no dangling Before, same
                # invariant as every other leg
                flow.g_trace_batch.add_event("CommitDebug", debug_id,
                                             "NativeAPI.commit.Error")
            raise e
        finally:
            if span is not None:
                span.finish()
        self.committed_version = reply.version
        self.committed_batch_index = reply.batch_index
        if debug_id is not None:
            flow.g_trace_batch.add_event("CommitDebug", debug_id,
                                         "NativeAPI.commit.After")
        self._arm_watches(reply.version)
        return reply.version

    def _check_conflict_windows(self, snapshot: int) -> None:
        """Hot-key early abort (ref: *Early Detection for MVCC
        Conflicts in Hyperledger Fabric*): a commit whose read ranges
        overlap a cached, still-fresh conflict window NEWER than its
        snapshot is near-certain to abort at the resolver — fail it
        locally before it consumes a proxy round trip and a resolver
        slot. The retry then starts sooner AND with a fresh snapshot.
        Raises the same not_committed a resolver abort produces."""
        cache = self.db._conflict_cache
        if cache is None or \
                not flow.SERVER_KNOBS.client_conflict_windows:
            return
        if getattr(self, "_repairable", False):
            # a repairable transaction PROFITS from submitting: the
            # server repairs the predicted conflict into a commit,
            # which an early abort would forfeit — the two planes
            # compose instead of fighting
            return
        from ..server.types import PRIORITY_IMMEDIATE
        if getattr(self, "_grv_priority", None) == PRIORITY_IMMEDIATE:
            return   # immediate traffic bypasses the heuristic gate
        hit = cache.doomed(self._read_conflicts, snapshot, flow.now())
        if not hit:
            return
        flow.cover("client.window_early_abort")
        from ..server.scheduler import note_early_abort
        note_early_abort()
        if getattr(self, "_report_conflicting", False):
            # same surface as a reported resolver conflict
            self._conflicting_ranges = tuple(hit)
        raise error("not_committed")

    def get_conflicting_ranges(self):
        """The key ranges that aborted the last conflicted commit, or
        None when no reported conflict happened (requires the
        report_conflicting_keys option; ref: reading
        \\xff\\xff/transaction/conflicting_keys/ after not_committed).
        Survives on_error's reset so the retry attempt can inspect
        what went wrong."""
        return getattr(self, "_conflicting_ranges", None)

    def get_versionstamp(self) -> bytes:
        """The committed transaction's 10-byte versionstamp."""
        if self.committed_version is None:
            raise error("client_invalid_operation")
        from ..server.proxy import make_versionstamp
        return make_versionstamp(self.committed_version,
                                 self.committed_batch_index or 0)

    def _arm_watches(self, version: int) -> None:
        """Wire pending watches to their shards at the commit version."""
        watches, self._watches = self._watches, []
        if watches:
            flow.spawn(self._arm_watches_async(watches, version),
                       TaskPriority.DEFAULT_ENDPOINT)

    async def _arm_watches_async(self, watches, version: int) -> None:
        for key, f in watches:
            if f.is_ready:
                continue
            shard = await self.db.shard_for(key)
            rep = shard.replicas[flow.g_random.random_int(
                0, len(shard.replicas))]
            if rep.watches is None:
                # a seam without watch endpoints (older gateways, the C
                # binding's describe): fail the future cleanly instead
                # of crashing the actor
                f.send_error(error("client_invalid_operation"))
                continue
            storage_fut = rep.watches.get_reply(
                StorageWatchRequest(key, version), self.db.process)
            storage_fut.on_ready(
                lambda sf, f=f: (f.send(sf.get()) if not sf.is_error
                                 else f.send_error(sf.exception()))
                if not f.is_ready else None)

    # -- retry loop -----------------------------------------------------
    async def on_error(self, e: BaseException) -> None:
        """(ref: Transaction::onError :2956 — backoff and reset; a
        failure that implies a stale cluster picture re-fetches the
        ServerDBInfo first, which long-polls across an in-flight
        recovery; TIMEOUT/RETRY_LIMIT options bound the loop)"""
        if not (isinstance(e, flow.FdbError) and e.name in RETRYABLE):
            raise e
        deadline = getattr(self, "_timeout_deadline", None)
        if deadline is not None and flow.now() >= deadline:
            raise error("transaction_timed_out")
        limit = getattr(self, "_retry_limit", None)
        if limit is not None:
            self._retries_used = getattr(self, "_retries_used", 0) + 1
            if self._retries_used > limit:
                raise e
        flow.cover("client.retry.conflict", e.name == "not_committed")
        if e.name in REFRESH_ERRORS:
            flow.cover("client.refresh_stale_picture")
            used = self._used_seq \
                if self._used_leader_gen == self.db._leader_gen else -1
            await self.db.refresh_past(used)
        await flow.delay(
            flow.SERVER_KNOBS.client_retry_backoff_min
            + flow.g_random.random01()
            * flow.SERVER_KNOBS.client_retry_backoff_jitter,
                         TaskPriority.DEFAULT_ENDPOINT)
        # a RETRY reset keeps the logical transaction's spent budgets
        # and priority class — only an explicit user reset() re-arms
        retries = getattr(self, "_retries_used", 0)
        prio = getattr(self, "_grv_priority", None)
        tags = getattr(self, "_tags", ())
        repairable = getattr(self, "_repairable", False)
        debug_id = getattr(self, "_debug_id", None)
        profile = self._profile
        report = getattr(self, "_report_conflicting", False)
        conflicting = getattr(self, "_conflicting_ranges", None)
        self.reset()
        self._retries_used = retries
        self._grv_priority = prio
        self._tags = tags
        self._repairable = repairable
        # the RETRY attempt is usually the interesting one (it hit a
        # conflict/failure) — keep it sampled
        self._debug_id = debug_id
        self._profile = profile
        # keep reporting armed AND the failed attempt's attribution
        # readable (ref: the conflicting-keys special keys being read
        # in the retry loop's next attempt)
        self._report_conflicting = report
        self._conflicting_ranges = conflicting
        if deadline is not None:
            self._timeout_deadline = deadline


async def run_transaction(db: Database, body,
                          max_retries: Optional[int] = None,
                          tr: Optional["Transaction"] = None):
    """The standard retry loop (ref: the `doTransaction` idiom / python
    binding @fdb.transactional). Pass `tr` to loop over a specially
    constructed transaction (the profiling machinery's unsampled
    ones) instead of a fresh default."""
    if max_retries is None:
        max_retries = int(flow.SERVER_KNOBS.client_default_max_retries)
    if tr is None:
        tr = db.create_transaction()
    for _ in range(max_retries):
        try:
            result = await body(tr)
            await tr.commit()
            return result
        except flow.FdbError as e:
            await tr.on_error(e)
    raise error("transaction_timed_out")
