"""Sampled transaction profiling: typed ClientLogEvents persisted into
the database itself.

Reference: fdbclient/ClientLogEvents.h (the GetVersion / Get / GetRange
/ Commit / error event vocabulary) + NativeAPI's transaction sampling
(`TRANSACTION_LOGGING_ENABLE` per transaction, the CSI_SAMPLING
database knob) and the \\xff\\x02/fdbClientInfo/client_latency/
keyspace the contrib transaction_profiling_analyzer consumes. The
client records one event per operation on a SAMPLED transaction, wire-
serializes the stream, and writes it back into the cluster in
size-limited chunks so the profile data rides the same replication,
backup, and retention machinery as user data.

Sampling is deterministic: the decision hashes a per-database
transaction sequence number with a salt derived from the seeded RNG,
so the same seed samples the same transactions — reruns reproduce the
profile byte for byte. With PROFILE_SAMPLE_RATE at 0 and no per-txn
option, `Database._maybe_sample` is never called and transactions
carry `_profile = None`: the hot paths pay one attribute test, no
event allocation, no extra keyspace traffic.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

from .. import flow
from ..flow.stats import CounterCollection
from ..rpc import wire
from ..server.systemkeys import (CLIENT_LATENCY_VERSION,
                                 client_latency_key)

# -- event vocabulary (ref: ClientLogEvents.h EventType) ----------------
# Every event is a wire-registered NamedTuple: the record blob is the
# wire encoding of the tuple of events, so the round-trip property
# (client emits == analyzer reads) is the serializer's own contract.


class GetVersionEvent(NamedTuple):
    """GRV latency (ref: EventGetVersion)."""
    time: float
    latency: float
    priority: int


class GetEvent(NamedTuple):
    """Point-read latency + key (ref: EventGet)."""
    time: float
    latency: float
    key: bytes
    value_size: int       # -1 = key absent


class GetRangeEvent(NamedTuple):
    """Range-read latency + bounds (ref: EventGetRange)."""
    time: float
    latency: float
    begin: bytes
    end: bytes
    rows: int


class CommitEvent(NamedTuple):
    """Commit outcome: latency, payload size, the write-conflict
    ranges (what the analyzer folds into hottest-written keys — the
    reference's EventCommit ships the whole CommitTransactionRequest),
    and the conflict verdict (reusing the resolver's attribution:
    conflicting_ranges carries the attributed causes when the client
    asked for them)."""
    time: float
    latency: float
    mutation_count: int
    mutation_bytes: int
    write_ranges: Tuple[Tuple[bytes, bytes], ...]
    verdict: str          # "committed" | "conflicted"
    version: int          # commit version (0 when conflicted)
    conflicting_ranges: Tuple[Tuple[bytes, bytes], ...]


class ErrorEvent(NamedTuple):
    """A failed operation (ref: EventGetError / EventCommitError)."""
    time: float
    op: str               # "grv" | "get" | "get_range" | "commit"
    error_name: str


wire.register_module(__name__)

# process-wide sampler counters (surfaced through status + the
# exporter, like the jitted-kernel profile): how much the sampler is
# doing is itself an observability signal
g_profile_counters = CounterCollection("client_profiler")
_c_sampled = g_profile_counters.counter("transactions_sampled")
_c_events = g_profile_counters.counter("events_recorded")
_c_chunks = g_profile_counters.counter("chunks_written")
_c_records = g_profile_counters.counter("records_written")
_c_flush_failed = g_profile_counters.counter("flushes_failed")
_c_trimmed = g_profile_counters.counter("records_trimmed")


def _mix64(x: int) -> int:
    """splitmix64 finalizer — a stable integer hash (python's hash() is
    identity on small ints, useless for rate thresholding)."""
    x &= (1 << 64) - 1
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & ((1 << 64) - 1)
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & ((1 << 64) - 1)
    return x ^ (x >> 31)


def sample_decision(salt: int, seq: int, rate: float) -> bool:
    """Deterministic hash-based sampling: the (salt, seq) hash lands
    uniformly in [0, 2^64); sample when it falls under rate. The same
    seed therefore samples the same transactions on every run."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return _mix64(salt ^ _mix64(seq)) < int(rate * (1 << 64))


class TransactionProfile:
    """One sampled transaction's accumulating event stream. Events
    survive retries (each attempt's operations append — the retried
    attempt is usually the interesting one), and each commit outcome
    drains the buffer into one chunked record."""

    __slots__ = ("rec_id", "start_ts", "events", "flushes")

    def __init__(self, rec_id: str, start_ts: float):
        self.rec_id = rec_id
        self.start_ts = start_ts
        self.events: List[tuple] = []
        self.flushes = 0

    def add(self, event: tuple) -> None:
        self.events.append(event)
        _c_events.add(1)


# -- record encoding -----------------------------------------------------

def encode_events(events) -> bytes:
    """The record blob: wire encoding of the event tuple."""
    return wire.to_bytes(tuple(events))


def decode_events(blob: bytes) -> Tuple[tuple, ...]:
    """Inverse of encode_events (bit-identical round trip)."""
    return wire.from_bytes(blob, None)


def split_chunks(blob: bytes, chunk_bytes: Optional[int] = None) -> List[bytes]:
    """Size-limited chunks (ref: the analyzer's chunk-number/num-chunks
    suffix pair — values stay under the value size limit no matter how
    chatty the transaction was)."""
    if chunk_bytes is None:
        chunk_bytes = int(flow.SERVER_KNOBS.profile_chunk_bytes)
    chunk_bytes = max(1, chunk_bytes)
    return [blob[i:i + chunk_bytes]
            for i in range(0, len(blob), chunk_bytes)] or [b""]


def record_rows(profile: TransactionProfile, events,
                chunk_bytes: Optional[int] = None) -> List[Tuple[bytes, bytes]]:
    """The (key, value) rows for one drained event stream. The record
    id is suffixed with the flush ordinal so a retried transaction's
    successive outcomes never collide."""
    rec_id = f"{profile.rec_id}{profile.flushes:04x}"
    start_us = int(profile.start_ts * 1e6)
    chunks = split_chunks(encode_events(events), chunk_bytes)
    n = len(chunks)
    return [(client_latency_key(start_us, rec_id, i + 1, n,
                                CLIENT_LATENCY_VERSION), c)
            for i, c in enumerate(chunks)]


async def run_unsampled(db, body, max_retries: int = 100):
    """run_transaction over a transaction that is never itself sampled
    — the retry loop for every piece of profiling infrastructure (the
    flush writer, the janitor, the analyzer's scan): the profiler
    observing the workload must not observe itself."""
    from .transaction import Transaction, run_transaction
    return await run_transaction(db, body, max_retries=max_retries,
                                 tr=Transaction(db, sampled=False))


async def flush_profile(db, profile: TransactionProfile,
                        max_retries: int = 32) -> bool:
    """Drain the profile's events into one chunked record, committed
    through an UNSAMPLED system-keys transaction (a sampled flush would
    recurse). Returns False — and counts — when the write ultimately
    fails; profiling must never fail the workload."""
    if not profile.events:
        return True
    events, profile.events = profile.events, []
    rows = record_rows(profile, events)
    profile.flushes += 1

    async def body(tr):
        tr.set_option("access_system_keys")
        for k, v in rows:
            tr.set(k, v)

    try:
        await run_unsampled(db, body, max_retries=max_retries)
    except flow.FdbError:
        _c_flush_failed.add(1)
        return False
    _c_records.add(1)
    _c_chunks.add(len(rows))
    return True


def note_sampled() -> None:
    _c_sampled.add(1)


def note_trimmed(n: int) -> None:
    _c_trimmed.add(n)


def profiler_counters() -> dict:
    """Snapshot for status/exporter surfacing."""
    return g_profile_counters.snapshot()
