"""foundationdb_tpu — a TPU-native transaction-processing framework.

A brand-new, TPU-first re-design of FoundationDB's capabilities (reference:
tclinken/foundationdb 6.1.0): an ordered, ACID, distributed key-value store
built on a deterministic actor runtime, with its MVCC conflict resolver
re-expressed as a vectorized JAX/XLA interval-overlap kernel.

Layering (mirrors reference layer map, SURVEY.md §1, re-designed for TPU):

  flow/      deterministic async actor runtime (ref: flow/)
  rpc/       token-addressed RPC + deterministic simulator (ref: fdbrpc/)
  ops/       JAX/TPU device kernels (key encoding, RMQ, conflict kernel)
  models/    conflict-set backends: python / native C++ / TPU (ref: fdbserver/SkipList.cpp)
  parallel/  device-mesh sharding of the resolver (ref: multi-resolver key sharding)
  server/    server roles: sequencer, proxy, resolver, tlog, storage (ref: fdbserver/)
  client/    Database / Transaction API (ref: fdbclient/NativeAPI, ReadYourWrites)
  utils/     key manipulation helpers (ref: fdbclient/FDBTypes.h)

Submodules import lazily so that host-only code (flow, server) never pulls
in jax.
"""

__version__ = "0.1.0"
