"""ctypes binding for the native C++ conflict-set backend.

Plugin boundary analogous to the reference's LoadPlugin mechanism
(fdbrpc/LoadPlugin.h:29-44 — loadLibrary + resolve symbols): the resolver
selects a backend ("python" / "native" / "tpu") at startup, and all
backends honor the same ConflictSetBase contract so the deterministic
simulator can replay identical verdicts against any of them.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence

import numpy as np

from .conflict_set import (ConflictSetBase, ConflictSetCheckpoint,
                           ResolverTransaction, checkpoint_from_step)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "build", "libfdbtpu_native.so")

_lib: Optional[ctypes.CDLL] = None


def _build_library() -> None:
    subprocess.run(["make", "-C", os.path.join(_REPO_ROOT, "native")],
                   check=True, capture_output=True)


def load_native_library(build_if_missing: bool = True) -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if build_if_missing:
        # make is mtime-incremental: a no-op when the .so is current, a
        # rebuild when conflictset.cpp changed (the artifact is never
        # committed — it is arch-specific via -march=native). Only an
        # absent toolchain may fall back to an existing .so; a failed
        # BUILD must surface, or a stale binary would silently run old
        # conflict semantics.
        try:
            _build_library()
        except FileNotFoundError:
            if not os.path.exists(_LIB_PATH):
                raise
    lib = ctypes.CDLL(_LIB_PATH)
    lib.fdbtpu_conflictset_new.restype = ctypes.c_void_p
    lib.fdbtpu_conflictset_new.argtypes = [ctypes.c_int64]
    lib.fdbtpu_conflictset_destroy.argtypes = [ctypes.c_void_p]
    lib.fdbtpu_conflictset_oldest.restype = ctypes.c_int64
    lib.fdbtpu_conflictset_oldest.argtypes = [ctypes.c_void_p]
    lib.fdbtpu_conflictset_interval_count.restype = ctypes.c_int64
    lib.fdbtpu_conflictset_interval_count.argtypes = [ctypes.c_void_p]
    lib.fdbtpu_conflictset_resolve.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64),   # snapshots
        ctypes.POINTER(ctypes.c_int32),   # read_counts
        ctypes.POINTER(ctypes.c_int32),   # write_counts
        ctypes.POINTER(ctypes.c_uint8),   # key_blob
        ctypes.POINTER(ctypes.c_int64),   # read_ranges
        ctypes.POINTER(ctypes.c_int64),   # write_ranges
        ctypes.POINTER(ctypes.c_uint8),   # verdicts_out
    ]
    try:
        # attribution entry point (report_conflicting_keys): absent
        # only from a pre-existing stale .so built before the symbol
        # existed — callers degrade to verdicts-only then
        lib.fdbtpu_conflictset_resolve_attributed.argtypes = \
            lib.fdbtpu_conflictset_resolve.argtypes + [
                ctypes.POINTER(ctypes.c_uint8)]   # read_hits_out
    except AttributeError:
        pass
    try:
        # state-export entry points (checkpoint/restore); absent only
        # from a stale .so — checkpoint() raises NotImplementedError then
        lib.fdbtpu_conflictset_export_rows.restype = ctypes.c_int64
        lib.fdbtpu_conflictset_export_rows.argtypes = [ctypes.c_void_p]
        lib.fdbtpu_conflictset_export_key_bytes.restype = ctypes.c_int64
        lib.fdbtpu_conflictset_export_key_bytes.argtypes = [ctypes.c_void_p]
        lib.fdbtpu_conflictset_export.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),   # key_blob_out
            ctypes.POINTER(ctypes.c_int64),   # key_lens_out
            ctypes.POINTER(ctypes.c_int64),   # versions_out
        ]
    except AttributeError:
        pass
    _lib = lib
    return lib


def native_available() -> bool:
    try:
        load_native_library()
        return True
    except Exception:
        return False


def _marshal(txns: Sequence[ResolverTransaction]):
    """Flatten a batch into the C ABI arrays."""
    n = len(txns)
    snapshots = np.empty(n, dtype=np.int64)
    read_counts = np.empty(n, dtype=np.int32)
    write_counts = np.empty(n, dtype=np.int32)
    blob_parts: list[bytes] = []
    read_quads: list[int] = []
    write_quads: list[int] = []
    off = 0

    def push(key: bytes) -> tuple[int, int]:
        nonlocal off
        blob_parts.append(key)
        o = off
        off += len(key)
        return o, len(key)

    for t, tr in enumerate(txns):
        snapshots[t] = tr.read_snapshot
        read_counts[t] = len(tr.read_ranges)
        write_counts[t] = len(tr.write_ranges)
        for b, e in tr.read_ranges:
            read_quads.extend(push(b))
            read_quads.extend(push(e))
        for b, e in tr.write_ranges:
            write_quads.extend(push(b))
            write_quads.extend(push(e))

    blob = np.frombuffer(b"".join(blob_parts) or b"\x00", dtype=np.uint8)
    rr = np.asarray(read_quads or [0], dtype=np.int64)
    wr = np.asarray(write_quads or [0], dtype=np.int64)
    return snapshots, read_counts, write_counts, blob, rr, wr


class NativeConflictSet(ConflictSetBase):
    """Native C++ step-function backend (see native/conflictset.cpp)."""

    def __init__(self, init_version: int = 0):
        self._lib = load_native_library()
        self._handle = self._lib.fdbtpu_conflictset_new(init_version)
        self._last_commit = init_version   # ordering floor for checkpoints

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.fdbtpu_conflictset_destroy(self._handle)
                self._handle = None
        except Exception:
            pass

    @property
    def oldest_version(self) -> int:
        return self._lib.fdbtpu_conflictset_oldest(self._handle)

    @property
    def interval_count(self) -> int:
        return self._lib.fdbtpu_conflictset_interval_count(self._handle)

    # -- checkpoint / restore ------------------------------------------
    def _checkpoint_state(self) -> ConflictSetCheckpoint:
        if not hasattr(self._lib, "fdbtpu_conflictset_export"):
            raise NotImplementedError(
                "stale native library lacks the export ABI: rebuild "
                "native/libfdbtpu_native.so")
        rows = self._lib.fdbtpu_conflictset_export_rows(self._handle)
        nbytes = self._lib.fdbtpu_conflictset_export_key_bytes(self._handle)
        blob = np.empty(max(int(nbytes), 1), np.uint8)
        lens = np.empty(max(int(rows), 1), np.int64)
        vers = np.empty(max(int(rows), 1), np.int64)
        p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))  # noqa: E731
        self._lib.fdbtpu_conflictset_export(
            self._handle, p(blob, ctypes.c_uint8), p(lens, ctypes.c_int64),
            p(vers, ctypes.c_int64))
        raw = blob.tobytes()
        keys: list = []
        off = 0
        for i in range(int(rows)):
            kl = int(lens[i])
            keys.append(raw[off:off + kl])
            off += kl
        vals = [int(v) for v in vers[:int(rows)]]
        return checkpoint_from_step(keys, vals, self.oldest_version,
                                    self._last_commit)

    def _reset_state(self, baseline_version: int) -> None:
        # the generic replay-based restore (ConflictSetBase) rebuilds
        # the step function through resolve(); only the reset is native
        self._lib.fdbtpu_conflictset_destroy(self._handle)
        self._handle = self._lib.fdbtpu_conflictset_new(baseline_version)
        self._last_commit = baseline_version

    def resolve(self, txns: Sequence[ResolverTransaction], commit_version: int,
                new_oldest_version: int) -> list[int]:
        n = len(txns)
        if commit_version > self._last_commit:
            self._last_commit = commit_version
        # empty batches still run: the GC window must advance exactly
        # like the python/TPU backends' empty-batch paths (the silent
        # early return here made an empty batch a no-op, so the next
        # batch's tooOld verdicts could diverge cross-backend)
        snapshots, rc, wc, blob, rr, wr = _marshal(txns)
        out = np.empty(max(n, 1), dtype=np.uint8)
        p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))  # noqa: E731
        self._lib.fdbtpu_conflictset_resolve(
            self._handle, commit_version, new_oldest_version, n,
            p(snapshots, ctypes.c_int64), p(rc, ctypes.c_int32),
            p(wc, ctypes.c_int32), p(blob, ctypes.c_uint8),
            p(rr, ctypes.c_int64), p(wr, ctypes.c_int64),
            p(out, ctypes.c_uint8))
        return out[:n].tolist()

    def resolve_with_attribution(self, txns: Sequence[ResolverTransaction],
                                 commit_version: int,
                                 new_oldest_version: int):
        """Verdicts + conflicting read-range indices via the attributed
        C entry point (same union semantics as every other backend); a
        stale .so without the symbol degrades to verdicts-only."""
        if not hasattr(self._lib, "fdbtpu_conflictset_resolve_attributed"):
            return ConflictSetBase.resolve_with_attribution(
                self, txns, commit_version, new_oldest_version)
        n = len(txns)
        if n == 0:
            # run the empty batch through resolve: the GC window
            # advances identically to every other backend
            return self.resolve(txns, commit_version,
                                new_oldest_version), []
        if commit_version > self._last_commit:
            self._last_commit = commit_version
        snapshots, rc, wc, blob, rr, wr = _marshal(txns)
        out = np.empty(n, dtype=np.uint8)
        n_reads = int(rc.sum())
        hits = np.zeros(max(n_reads, 1), dtype=np.uint8)
        p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))  # noqa: E731
        self._lib.fdbtpu_conflictset_resolve_attributed(
            self._handle, commit_version, new_oldest_version, n,
            p(snapshots, ctypes.c_int64), p(rc, ctypes.c_int32),
            p(wc, ctypes.c_int32), p(blob, ctypes.c_uint8),
            p(rr, ctypes.c_int64), p(wr, ctypes.c_int64),
            p(out, ctypes.c_uint8), p(hits, ctypes.c_uint8))
        attr: list[tuple] = []
        off = 0
        for t in range(n):
            cnt = int(rc[t])
            attr.append(tuple(
                ri for ri in range(cnt) if hits[off + ri]))
            off += cnt
        return out.tolist(), attr


# Every recruitable conflict-set backend, next to the factory that is
# its authority. Config validation EVERYWHERE (client configure,
# cluster-controller management mutations, the conf-sync repair loop)
# keys off THIS tuple, so a new backend cannot be half-supported — the
# conf-sync loop once "repaired" a perfectly valid sharded-tpu row
# every round forever because a second hand-synced list missed it.
CONFLICT_BACKENDS = ("python", "native", "tpu", "tpu-point",
                     "sharded-tpu")


def create_conflict_set(backend: str = "python", init_version: int = 0) -> ConflictSetBase:
    """Backend factory — the plugin selection point (ref: LoadPlugin)."""
    if backend == "python":
        from .conflict_set import PyConflictSet
        return PyConflictSet(init_version)
    if backend == "native":
        return NativeConflictSet(init_version)
    if backend == "tpu":
        try:
            from .tpu_resolver import TpuConflictSet
        except ImportError as e:
            raise ValueError(f"tpu conflict-set backend unavailable: {e}") from e
        return TpuConflictSet(init_version)
    if backend == "tpu-point":
        try:
            from .point_resolver import PointConflictSet
        except ImportError as e:
            raise ValueError(f"tpu conflict-set backend unavailable: {e}") from e
        return PointConflictSet(init_version)
    if backend == "sharded-tpu":
        # key-range sharded over every visible device (the multi-chip
        # resolver deployment; a 1-device mesh degenerates cleanly)
        try:
            from ..parallel import ShardedTpuConflictSet
        except ImportError as e:
            raise ValueError(f"sharded conflict-set backend unavailable: "
                             f"{e}") from e
        return ShardedTpuConflictSet(init_version)
    raise ValueError(f"unknown conflict-set backend: {backend}")
