"""MVCC conflict resolution — semantics and CPU baseline.

Reference behavior (re-implemented, not ported):
  - fdbserver/ConflictSet.h:37-39  verdict enum {Conflict=0, TooOld=1, Committed=2}
  - fdbserver/SkipList.cpp:979     addTransaction — tooOld iff
        read_snapshot < oldestVersion AND the txn has read conflict ranges;
        a tooOld txn contributes no ranges at all
  - fdbserver/SkipList.cpp:1163    detectConflicts pipeline:
        (1) external check: a read range [b,e) at snapshot s conflicts iff
            max history version over intervals intersecting [b,e) is > s
            (strictly greater; ref CheckMax, SkipList.cpp:789-828)
        (2) intra-batch (ref checkIntraBatchConflicts, :1133): sequential in
            transaction order; txns already conflicted are skipped and their
            writes excluded; a txn conflicts if any of its read ranges
            overlaps a write range of an earlier non-conflicted txn
        (3) non-conflicted txns' write ranges are merged into the history
            as an interval assignment at the batch commit version
            (ref addConflictRanges, SkipList.cpp:511-522 — end keeps the old
            suffix version, [b,e) becomes the new version)
        (4) window GC: oldestVersion = max(oldestVersion, newOldestVersion);
            intervals at version < oldestVersion are semantically dead
  - fdbserver/Resolver.actor.cpp:155  newOldestVersion =
        commitVersion - MAX_WRITE_TRANSACTION_LIFE_VERSIONS

The history is modeled as a *step function* over the keyspace: sorted
boundary keys B[i] with V[i] = max commit version of writes to any key in
[B[i], B[i+1}). This is exactly the information content of the reference's
skiplist (per-node maxVersion); the data-structure choice differs because
each backend optimizes for its hardware (sorted arrays + RMQ on TPU,
std::map in native C++, bisect lists here).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Iterable, NamedTuple, Sequence

CONFLICT = 0
TOO_OLD = 1
COMMITTED = 2

VERDICT_NAMES = {CONFLICT: "conflict", TOO_OLD: "too_old", COMMITTED: "committed"}


class ResolverTransaction(NamedTuple):
    """One transaction's conflict information (ref: CommitTransactionRef,
    fdbclient/CommitTransaction.h:136-168 — read/write conflict ranges +
    read_snapshot)."""

    read_snapshot: int
    read_ranges: tuple  # of (begin: bytes, end: bytes), half-open
    write_ranges: tuple  # of (begin: bytes, end: bytes), half-open


class ConflictSetBase:
    """Interface all backends implement; parity across backends is the
    north-star acceptance criterion."""

    BACKEND = "base"

    def resolve(self, txns: Sequence[ResolverTransaction], commit_version: int,
                new_oldest_version: int) -> list[int]:
        raise NotImplementedError

    def resolve_with_attribution(self, txns: Sequence[ResolverTransaction],
                                 commit_version: int,
                                 new_oldest_version: int):
        """Like `resolve`, but additionally attributes each conflicted
        transaction to the read-range indices that CAUSED the conflict
        (ref: report_conflicting_keys — fdbclient grew the option so
        operators can see which keys abort transactions).

        Returns (verdicts, attributions) where attributions[t] is a
        sorted tuple of indices into txns[t].read_ranges, or None in
        place of the whole list when the backend cannot attribute (the
        caller then degrades to verdicts-only). Attribution semantics,
        identical across every backend: a read range is a cause iff it
        conflicts against the pre-batch history at the transaction's
        snapshot, OR it overlaps a write range of an earlier
        NON-conflicted transaction in the same batch — evaluated for
        every non-tooOld transaction, including externally-conflicted
        ones, so the set is order-insensitive. tooOld transactions
        attribute nothing (they contribute no ranges at all)."""
        return self.resolve(txns, commit_version, new_oldest_version), None

    @property
    def oldest_version(self) -> int:
        raise NotImplementedError

    def kernel_stats(self) -> dict:
        """Device-kernel profile for status; non-device backends have
        none (the TPU backends override with pad/occupancy/compile
        accounting)."""
        return {}


class PyConflictSet(ConflictSetBase):
    """Pure-Python step-function baseline (sorted boundary list + bisect)."""

    BACKEND = "python"

    def __init__(self, init_version: int = 0):
        # Invariant: _keys[0] == b"" always; _vals[i] covers [_keys[i], _keys[i+1}).
        # init_version baselines the whole keyspace (ref: clearConflictSet /
        # SkipList(v)); oldestVersion starts at 0 regardless (ref: ConflictSet
        # ctor, SkipList.cpp:926).
        self._keys: list[bytes] = [b""]
        self._vals: list[int] = [init_version]
        self._oldest = 0
        self._resolved_batches = 0

    @property
    def oldest_version(self) -> int:
        return self._oldest

    # -- queries ------------------------------------------------------------
    def _range_max(self, begin: bytes, end: bytes) -> int:
        """Max version over intervals intersecting [begin, end)."""
        lo = bisect_right(self._keys, begin) - 1  # interval containing begin
        hi = bisect_left(self._keys, end)  # first boundary >= end
        return max(self._vals[lo:hi])

    # -- updates ------------------------------------------------------------
    def _assign(self, begin: bytes, end: bytes, version: int) -> None:
        """Set version for all keys in [begin, end) (ref: addConflictRanges)."""
        hi = bisect_right(self._keys, end) - 1
        v_end = self._vals[hi]  # version of the interval containing `end`
        lo = bisect_left(self._keys, begin)
        e_idx = bisect_left(self._keys, end)
        has_end = e_idx < len(self._keys) and self._keys[e_idx] == end
        repl_keys, repl_vals = [begin], [version]
        if not has_end:
            repl_keys.append(end)
            repl_vals.append(v_end)
        self._keys[lo:e_idx] = repl_keys
        self._vals[lo:e_idx] = repl_vals

    def _compact(self) -> None:
        """Collapse adjacent intervals that are both dead (< oldest) or equal.

        Dead intervals (version < oldestVersion) cannot conflict with any
        non-tooOld read, so merging them (keeping the max) is invisible
        (ref: removeBefore, SkipList.cpp:665 — the same window GC)."""
        keys, vals, oldest = self._keys, self._vals, self._oldest
        nk, nv = [keys[0]], [vals[0]]
        for i in range(1, len(keys)):
            v = vals[i]
            if (v < oldest and nv[-1] < oldest) or v == nv[-1]:
                if v > nv[-1]:
                    nv[-1] = v
            else:
                nk.append(keys[i])
                nv.append(v)
        self._keys, self._vals = nk, nv

    # -- the resolve step ---------------------------------------------------
    def resolve(self, txns: Sequence[ResolverTransaction], commit_version: int,
                new_oldest_version: int) -> list[int]:
        return self._resolve(txns, commit_version, new_oldest_version, None)

    def resolve_with_attribution(self, txns: Sequence[ResolverTransaction],
                                 commit_version: int,
                                 new_oldest_version: int):
        collect: list[list[int]] = [[] for _ in txns]
        verdicts = self._resolve(txns, commit_version, new_oldest_version,
                                 collect)
        return verdicts, [tuple(sorted(set(c))) for c in collect]

    def _resolve(self, txns: Sequence[ResolverTransaction],
                 commit_version: int, new_oldest_version: int,
                 collect) -> list[int]:
        n = len(txns)
        too_old = [False] * n
        conflict = [False] * n

        for t, tr in enumerate(txns):
            if tr.read_snapshot < self._oldest and len(tr.read_ranges):
                too_old[t] = True

        # (1) external check against history. Attribution mode checks
        # EVERY range (the short-circuit would under-report causes).
        for t, tr in enumerate(txns):
            if too_old[t]:
                continue
            for ri, (b, e) in enumerate(tr.read_ranges):
                if b < e and self._range_max(b, e) > tr.read_snapshot:
                    conflict[t] = True
                    if collect is None:
                        break
                    collect[t].append(ri)

        # (2) intra-batch, sequential in batch order. Attribution mode
        # also checks the reads of already-conflicted transactions
        # against the written set at their turn (their writes still
        # never join it), so the attributed set covers intra causes of
        # externally-conflicted transactions too.
        written: list[tuple[bytes, bytes]] = []  # sorted by begin, disjoint
        wkeys: list[bytes] = []  # begins, for bisect
        for t, tr in enumerate(txns):
            if conflict[t]:
                if collect is not None and not too_old[t]:
                    for ri, (b, e) in enumerate(tr.read_ranges):
                        if b < e and _overlaps_any(written, wkeys, b, e):
                            collect[t].append(ri)
                continue
            c = too_old[t]
            if not c:
                for ri, (b, e) in enumerate(tr.read_ranges):
                    if b < e and _overlaps_any(written, wkeys, b, e):
                        c = True
                        if collect is None:
                            break
                        collect[t].append(ri)
            conflict[t] = c
            if not c:
                for b, e in tr.write_ranges:
                    if b < e:
                        _interval_union_add(written, wkeys, b, e)

        # (3) merge surviving writes into history at the commit version
        for b, e in written:
            self._assign(b, e, commit_version)

        # (4) window GC
        if new_oldest_version > self._oldest:
            self._oldest = new_oldest_version
        self._resolved_batches += 1
        from ..flow import SERVER_KNOBS
        if self._resolved_batches % int(
                SERVER_KNOBS.conflict_set_compact_every) == 0:
            self._compact()

        return [TOO_OLD if too_old[t] else (CONFLICT if conflict[t] else COMMITTED)
                for t in range(n)]


def _overlaps_any(written: list, wkeys: list, b: bytes, e: bytes) -> bool:
    """Does [b,e) intersect any interval in the sorted disjoint set?"""
    i = bisect_right(wkeys, b) - 1
    if i >= 0 and written[i][1] > b:
        return True
    i += 1
    return i < len(written) and written[i][0] < e


def _interval_union_add(written: list, wkeys: list, b: bytes, e: bytes) -> None:
    """Insert [b,e) into a sorted disjoint interval set, coalescing overlaps."""
    i = bisect_right(wkeys, b) - 1
    start = i if (i >= 0 and written[i][1] >= b) else i + 1
    j = start
    while j < len(written) and written[j][0] <= e:
        j += 1
    if start < j:
        b = min(b, written[start][0])
        e = max(e, written[j - 1][1])
    written[start:j] = [(b, e)]
    wkeys[start:j] = [b]


class BruteForceConflictSet(ConflictSetBase):
    """O(everything) model for randomized cross-checks (ref test model:
    workloads/ConflictRange.actor.cpp:30 — exact conflict-or-not vs a model).

    Keeps every committed write range with its version; no GC compaction, so
    it is the ground truth the optimized backends must match bit-for-bit.
    """

    def __init__(self, init_version: int = 0):
        # \xff*64 stands in for the end of the keyspace; tests stay below it.
        self._writes: list[tuple[bytes, bytes, int]] = [(b"", b"\xff" * 64, init_version)]
        self._oldest = 0

    @property
    def oldest_version(self) -> int:
        return self._oldest

    def resolve(self, txns, commit_version, new_oldest_version):
        return self._resolve(txns, commit_version, new_oldest_version,
                             None)

    def resolve_with_attribution(self, txns, commit_version,
                                 new_oldest_version):
        collect: list[list[int]] = [[] for _ in txns]
        verdicts = self._resolve(txns, commit_version, new_oldest_version,
                                 collect)
        return verdicts, [tuple(sorted(set(c))) for c in collect]

    def _resolve(self, txns, commit_version, new_oldest_version, collect):
        n = len(txns)
        verdicts = [COMMITTED] * n
        added: list[tuple[bytes, bytes]] = []
        for t, tr in enumerate(txns):
            if tr.read_snapshot < self._oldest and len(tr.read_ranges):
                verdicts[t] = TOO_OLD
                continue
            bad = False
            for ri, (b, e) in enumerate(tr.read_ranges):
                if b >= e:
                    continue
                hit = any(wb < e and b < we and wv > tr.read_snapshot
                          for wb, we, wv in self._writes)
                hit = hit or any(wb < e and b < we for wb, we in added)
                if hit:
                    bad = True
                    if collect is None:
                        break
                    collect[t].append(ri)
            if bad:
                verdicts[t] = CONFLICT
            else:
                for b, e in tr.write_ranges:
                    if b < e:
                        added.append((b, e))
        for b, e in added:
            self._writes.append((b, e, commit_version))
        if new_oldest_version > self._oldest:
            self._oldest = new_oldest_version
        return verdicts
