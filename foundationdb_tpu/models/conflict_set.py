"""MVCC conflict resolution — semantics and CPU baseline.

Reference behavior (re-implemented, not ported):
  - fdbserver/ConflictSet.h:37-39  verdict enum {Conflict=0, TooOld=1, Committed=2}
  - fdbserver/SkipList.cpp:979     addTransaction — tooOld iff
        read_snapshot < oldestVersion AND the txn has read conflict ranges;
        a tooOld txn contributes no ranges at all
  - fdbserver/SkipList.cpp:1163    detectConflicts pipeline:
        (1) external check: a read range [b,e) at snapshot s conflicts iff
            max history version over intervals intersecting [b,e) is > s
            (strictly greater; ref CheckMax, SkipList.cpp:789-828)
        (2) intra-batch (ref checkIntraBatchConflicts, :1133): sequential in
            transaction order; txns already conflicted are skipped and their
            writes excluded; a txn conflicts if any of its read ranges
            overlaps a write range of an earlier non-conflicted txn
        (3) non-conflicted txns' write ranges are merged into the history
            as an interval assignment at the batch commit version
            (ref addConflictRanges, SkipList.cpp:511-522 — end keeps the old
            suffix version, [b,e) becomes the new version)
        (4) window GC: oldestVersion = max(oldestVersion, newOldestVersion);
            intervals at version < oldestVersion are semantically dead
  - fdbserver/Resolver.actor.cpp:155  newOldestVersion =
        commitVersion - MAX_WRITE_TRANSACTION_LIFE_VERSIONS

The history is modeled as a *step function* over the keyspace: sorted
boundary keys B[i] with V[i] = max commit version of writes to any key in
[B[i], B[i+1}). This is exactly the information content of the reference's
skiplist (per-node maxVersion); the data-structure choice differs because
each backend optimizes for its hardware (sorted arrays + RMQ on TPU,
std::map in native C++, bisect lists here).
"""

from __future__ import annotations

import time
from bisect import bisect_left, bisect_right, insort
from typing import Iterable, NamedTuple, Sequence

CONFLICT = 0
TOO_OLD = 1
COMMITTED = 2

VERDICT_NAMES = {CONFLICT: "conflict", TOO_OLD: "too_old", COMMITTED: "committed"}


class ConflictSetCheckpoint(NamedTuple):
    """Backend-agnostic snapshot of a conflict-set's live state.

    The history is a step function over the keyspace (see the module
    docstring); a checkpoint captures it as a BASELINE version covering
    every key not named below, plus sorted disjoint interval
    `assignments` (begin, end, version) overriding the baseline — the
    exact information content of every backend's state, whatever its
    data-structure (bisect lists, std::map, device arrays, per-key
    point map). `oldest_version` and `last_commit` restore the MVCC
    window and the version-ordering floor.

    Restore parity contract: any backend restored from a checkpoint
    yields bit-identical verdicts to the backend that produced it, for
    every subsequent batch — dead intervals (version < oldest) are
    clamped to a dead-equivalent value at capture, which is
    verdict-invariant (no non-tooOld read snapshot is below oldest)."""

    oldest_version: int
    last_commit: int
    baseline_version: int
    assignments: tuple  # of (begin: bytes, end: bytes, version: int)


def checkpoint_from_step(keys: Sequence[bytes], vals: Sequence[int],
                         oldest: int, last_commit: int
                         ) -> ConflictSetCheckpoint:
    """Build a checkpoint from a full-coverage step function (keys[0]
    must be b""; vals[i] covers [keys[i], keys[i+1}) with the last
    interval running to +inf). The tail interval's version becomes the
    baseline, so every emitted assignment has a finite end; dead
    intervals are clamped (verdict-invariant, see ConflictSetCheckpoint)."""
    if not keys or keys[0] != b"":
        raise ValueError("step function must cover the keyspace from b''")
    baseline = int(vals[-1])
    dead_v = min(baseline, int(oldest) - 1)
    out = []
    for i in range(len(keys) - 1):
        v = int(vals[i])
        if v < oldest:
            v = dead_v
        if v != baseline:
            out.append((keys[i], keys[i + 1], v))
    return ConflictSetCheckpoint(int(oldest), int(last_commit),
                                 baseline, tuple(out))


def step_from_checkpoint(ckpt: ConflictSetCheckpoint):
    """Materialize a checkpoint back into a full-coverage step function
    (keys, vals) — the inverse of checkpoint_from_step, also correct
    for point-backend checkpoints (baseline between the points)."""
    keys: list[bytes] = [b""]
    vals: list[int] = [int(ckpt.baseline_version)]
    for b, e, v in sorted(ckpt.assignments):
        if e is None or b >= e:
            raise ValueError(f"malformed checkpoint range [{b!r}, {e!r})")
        if b < keys[-1]:
            raise ValueError("checkpoint assignments overlap")
        if b == keys[-1]:
            vals[-1] = int(v)
        else:
            keys.append(b)
            vals.append(int(v))
        keys.append(e)
        vals.append(int(ckpt.baseline_version))
    # coalesce equal neighbors (pure cosmetics: fewer rows on restore)
    ck: list[bytes] = [keys[0]]
    cv: list[int] = [vals[0]]
    for k, v in zip(keys[1:], vals[1:]):
        if v != cv[-1]:
            ck.append(k)
            cv.append(v)
    return ck, cv


def clip_step(keys: Sequence[bytes], vals: Sequence[int], lo: bytes,
              hi: "bytes | None"):
    """Restrict a full-coverage step function to [lo, hi): the returned
    lists start with an explicit boundary AT lo carrying the covering
    version (the shard-state invariant: slot 0 is the shard's lower
    bound)."""
    i = bisect_right(keys, lo) - 1
    out_k: list[bytes] = [lo]
    out_v: list[int] = [int(vals[i])]
    for j in range(i + 1, len(keys)):
        if hi is not None and keys[j] >= hi:
            break
        out_k.append(keys[j])
        out_v.append(int(vals[j]))
    return out_k, out_v


class ConflictRangePiece(NamedTuple):
    """One key range's slice of a conflict-set checkpoint — the unit of
    resolver state handoff (ISSUE 15: a balance-driven split moves
    [begin, end) from donor to recipient; the donor's clipped step
    function rides the wire inside this piece and is grafted into the
    recipient with `graft_checkpoint`).

    `keys`/`vals` are a clip_step-shaped step function over [begin,
    end): keys[0] == begin, vals[i] covers [keys[i], keys[i+1}) with
    the last interval running to `end` (None = keyspace tail).
    `oldest_version`/`last_commit` carry the donor's MVCC window so the
    graft can only ever ADVANCE the recipient's floor."""

    begin: bytes
    end: "bytes | None"
    keys: tuple
    vals: tuple
    oldest_version: int
    last_commit: int


def clip_checkpoint(ckpt: ConflictSetCheckpoint, lo: bytes,
                    hi: "bytes | None") -> ConflictRangePiece:
    """The [lo, hi) slice of a checkpoint as a handoff piece."""
    keys, vals = step_from_checkpoint(ckpt)
    ck, cv = clip_step(keys, vals, lo, hi)
    return ConflictRangePiece(lo, hi, tuple(ck), tuple(cv),
                              int(ckpt.oldest_version),
                              int(ckpt.last_commit))


def _step_at(keys: Sequence[bytes], vals: Sequence[int],
             key: bytes) -> int:
    """Value of the covering interval at `key` (keys[0] <= key)."""
    return int(vals[bisect_right(keys, key) - 1])


def graft_checkpoint(base: ConflictSetCheckpoint,
                     piece: ConflictRangePiece) -> ConflictSetCheckpoint:
    """Merge a handoff piece into a full checkpoint: outside the
    piece's span the base is untouched; inside, each interval takes the
    POINTWISE MAX of base and piece. Max — not replace — because step
    values are monotone (assignments only ever raise a key's version),
    so whichever side saw a write later holds the higher version: the
    recipient may already have recorded post-move writes the donor's
    checkpoint predates, and the piece holds pre-move history the
    recipient never saw. The union is exactly the unsplit oracle's
    step function over the span — the bit-exactness the handoff tests
    pin.

    Watermark discipline under in-flight skew (the donor checkpoints
    at/after the move's effective version; the recipient's install may
    land while it is still resolving earlier batches): the recipient's
    GLOBAL `oldest_version` is KEPT — adopting the donor's (possibly
    further-advanced) watermark would flip near-window-boundary reads
    in the recipient's in-flight batches to tooOld verdicts the
    unsplit oracle never issues. Piece values that were DEAD at the
    donor (below the donor's watermark — including the donor's own
    dead-clamp rows) are re-clamped below the RECIPIENT's watermark:
    a donor clamp value can exceed an in-flight batch's legal read
    snapshot, which would manufacture conflicts; dropping such a value
    loses nothing, because during the double-delivery window the donor
    still votes with full history, and after the early release every
    legal snapshot is above the donor's watermark (the release rides
    the version chain behind the checkpoint). `last_commit` takes the
    max — it is restore-replay metadata, and the span carries writes
    up to the donor's chain position."""
    bk, bv = step_from_checkpoint(base)
    lo, hi = piece.begin, piece.end
    pk, pv = list(piece.keys), list(piece.vals)
    if not pk or pk[0] != lo:
        raise ValueError("piece step must start at its own begin key")
    oldest = int(base.oldest_version)
    # dead-equivalent value, floored at 0: no read snapshot is ever
    # negative, so 0 can never out-version a legal read, and device
    # backends need non-negative versions
    dead_v = max(0, oldest - 1)
    piece_oldest = int(piece.oldest_version)
    # candidate boundaries: the base's, the piece's, plus the span
    # edges; value at each = base outside the span, max(base, piece)
    # inside; equal neighbors coalesce
    bounds = set(bk) | set(pk) | {lo}
    if hi is not None:
        bounds.add(hi)
    out_k: list[bytes] = []
    out_v: list[int] = []
    for k in sorted(bounds):
        v = _step_at(bk, bv, k)
        if k >= lo and (hi is None or k < hi):
            p = _step_at(pk, pv, k)
            if p < piece_oldest:
                p = min(p, dead_v)
            v = max(v, p)
        if out_k and out_v[-1] == v:
            continue
        out_k.append(k)
        out_v.append(v)
    last_commit = max(int(base.last_commit), int(piece.last_commit))
    return checkpoint_from_step(out_k, out_v, oldest, last_commit)


class ResolverTransaction(NamedTuple):
    """One transaction's conflict information (ref: CommitTransactionRef,
    fdbclient/CommitTransaction.h:136-168 — read/write conflict ranges +
    read_snapshot)."""

    read_snapshot: int
    read_ranges: tuple  # of (begin: bytes, end: bytes), half-open
    write_ranges: tuple  # of (begin: bytes, end: bytes), half-open


class ResolveTicket:
    """Handle for one submitted conflict batch (ConflictSetBase.submit).

    Holds either the finished result or a `materialize` closure that
    blocks only on THIS batch's verdict readback (the device serializes
    batches, so materializing ticket k implicitly waits for k-1's
    compute but never for k+1's). Draining is idempotent: the first
    drain runs the closure, later drains return the cached result, so
    duplicate deliveries and out-of-order drains are both safe."""

    __slots__ = ("commit_version", "n", "drained", "_result",
                 "_materialize")

    def __init__(self, commit_version: int, n: int, materialize=None,
                 result=None):
        self.commit_version = commit_version
        self.n = n
        self.drained = False
        self._result = result
        self._materialize = materialize

    @property
    def done(self) -> bool:
        """True once the result is host-resident (no blocking left)."""
        return self._materialize is None

    def _force(self):
        if self._materialize is not None:
            # the closure is cleared only AFTER it succeeds: a device
            # fault raised mid-materialize must leave the ticket
            # un-materialized (drainable again / replayable), never
            # "done" with a silent None result
            result = self._materialize()
            self._materialize = None
            self._result = result
        return self._result


class ResolvePipeline:
    """Ticket queue + accounting for the split submit/drain resolve
    path: up to `depth` batches stay in flight between submit and
    drain (ref: the commit-pipeline overlap the proxy's
    latestLocalCommitBatch* interlocks buy for logging, applied to the
    resolver boundary; batch-level pipelining of conflict checks per
    the batched-conflict-resolution literature, arXiv:1804.00947).

    Submitting past `depth` force-drains the OLDEST ticket — the front
    of the device queue, so the stall is one batch's readback, not the
    whole backlog. Latencies are wall-clock (`time.perf_counter`):
    they measure the host/device boundary, not simulated time."""

    __slots__ = ("_depth", "in_flight", "peak_in_flight", "submits",
                 "drains", "forced_drains", "_occ_sum",
                 "submit_latency", "drain_latency")

    def __init__(self, depth: "int | None" = None):
        self._depth = depth          # None: read the knob per submit
        self.in_flight: list = []    # submitted, not yet materialized
        self.peak_in_flight = 0
        self.submits = 0
        self.drains = 0
        self.forced_drains = 0
        self._occ_sum = 0            # sum of in-flight depth at submit
        from ..flow.latency import RequestLatency
        self.submit_latency = RequestLatency("pipeline_submit")
        self.drain_latency = RequestLatency("pipeline_drain")

    @property
    def depth(self) -> int:
        if self._depth is not None:
            return max(1, int(self._depth))
        from ..flow.knobs import SERVER_KNOBS
        return max(1, int(SERVER_KNOBS.resolve_pipeline_depth))

    def note_submit(self, ticket: ResolveTicket, t0: float) -> None:
        self.submits += 1
        self.submit_latency.record(time.perf_counter() - t0)
        if not ticket.done:
            # backpressure BEFORE admitting the new ticket: the window
            # never exceeds depth, and depth 1 degenerates to the
            # serial submit-block-read path
            while len(self.in_flight) >= self.depth:
                self.forced_drains += 1
                self.drain(self.in_flight[0])
            self.in_flight.append(ticket)
        self._occ_sum += len(self.in_flight)
        if len(self.in_flight) > self.peak_in_flight:
            self.peak_in_flight = len(self.in_flight)

    def drain(self, ticket: ResolveTicket):
        try:
            self.in_flight.remove(ticket)     # list is <= depth+1 long
        except ValueError:
            pass                              # already materialized
        if not ticket.drained:
            if not ticket.done:
                # a materialize failure (device fault) propagates with
                # the ticket still UNDRAINED — the idempotent-drain
                # contract holds: a later drain retries or returns the
                # replayed result, never a silent None
                t0 = time.perf_counter()
                ticket._force()
                self.drain_latency.record(time.perf_counter() - t0)
            ticket.drained = True
            self.drains += 1
        return ticket._result

    def stats(self) -> dict:
        """Status-ready snapshot: depth/occupancy gauges, submit/drain
        counters, and the submit-vs-drain wall-latency bands."""
        return {"depth": self.depth,
                "in_flight": len(self.in_flight),
                "peak_in_flight": self.peak_in_flight,
                "submits": self.submits,
                "drains": self.drains,
                "forced_drains": self.forced_drains,
                # mean in-flight window over configured depth: ~1 means
                # the pipeline actually runs full, ~0 means serial use
                "occupancy": round(
                    self._occ_sum / (self.submits * self.depth), 4)
                if self.submits else None,
                "latency": {
                    "submit": self.submit_latency.snapshot(),
                    "drain": self.drain_latency.snapshot()}}


class ConflictSetBase:
    """Interface all backends implement; parity across backends is the
    north-star acceptance criterion."""

    BACKEND = "base"

    def resolve(self, txns: Sequence[ResolverTransaction], commit_version: int,
                new_oldest_version: int) -> list[int]:
        raise NotImplementedError

    def resolve_with_attribution(self, txns: Sequence[ResolverTransaction],
                                 commit_version: int,
                                 new_oldest_version: int):
        """Like `resolve`, but additionally attributes each conflicted
        transaction to the read-range indices that CAUSED the conflict
        (ref: report_conflicting_keys — fdbclient grew the option so
        operators can see which keys abort transactions).

        Returns (verdicts, attributions) where attributions[t] is a
        sorted tuple of indices into txns[t].read_ranges, or None in
        place of the whole list when the backend cannot attribute (the
        caller then degrades to verdicts-only). Attribution semantics,
        identical across every backend: a read range is a cause iff it
        conflicts against the pre-batch history at the transaction's
        snapshot, OR it overlaps a write range of an earlier
        NON-conflicted transaction in the same batch — evaluated for
        every non-tooOld transaction, including externally-conflicted
        ones, so the set is order-insensitive. tooOld transactions
        attribute nothing (they contribute no ranges at all)."""
        return self.resolve(txns, commit_version, new_oldest_version), None

    @property
    def oldest_version(self) -> int:
        raise NotImplementedError

    def validate_txns(self, txns: Sequence[ResolverTransaction],
                      oldest_version: "int | None" = None) -> None:
        """Host-side mirror of this backend's input contract: raise the
        same ValueError `submit` would raise for a malformed batch (a
        key wider than the device key bucket, a non-point range on the
        point backend), WITHOUT touching device state. The failover
        wrapper runs the PRIMARY's validator while serving from the
        permissive CPU fallback, so the resolver role's batch-reject
        behavior — and with it the verdict stream — stays bit-identical
        across the failover boundary, and every logged batch stays
        device-replayable for reattach. Host backends accept anything."""

    def input_contract(self):
        """`validate_txns` as a STATE-FREE callable, safe to hold long
        after this backend (and any device buffers) are discarded; call
        it with an explicit `oldest_version`. The base no-op reads no
        state, so the bound method is already safe; the device backends
        hand out a view carrying only their key-bucket config."""
        return self.validate_txns

    # -- split submit/drain pipeline ------------------------------------
    @property
    def pipeline(self) -> ResolvePipeline:
        p = getattr(self, "_pipeline", None)
        if p is None:
            p = self._pipeline = ResolvePipeline()
        return p

    def submit(self, txns: Sequence[ResolverTransaction],
               commit_version: int, new_oldest_version: int,
               attribute: bool = False) -> ResolveTicket:
        """Enqueue one batch without waiting for its verdicts; `drain`
        the returned ticket for the result. Submissions must follow
        commit-version order (the same contract as `resolve`); drains
        may happen in any order. The base implementation resolves
        eagerly — host backends have no device work to overlap — so the
        ticket is born materialized; the device backends override this
        with a genuinely asynchronous dispatch and the pipeline keeps
        up to RESOLVE_PIPELINE_DEPTH batches in flight."""
        t0 = time.perf_counter()
        if attribute:
            result = self.resolve_with_attribution(
                txns, commit_version, new_oldest_version)
        else:
            result = (self.resolve(txns, commit_version,
                                   new_oldest_version), None)
        ticket = ResolveTicket(commit_version, len(txns), result=result)
        self.pipeline.note_submit(ticket, t0)
        return ticket

    def drain(self, ticket: ResolveTicket) -> list:
        """Block until THIS ticket's verdicts are host-resident and
        return them (idempotent)."""
        return self.pipeline.drain(ticket)[0]

    def drain_with_attribution(self, ticket: ResolveTicket):
        """(verdicts, attributions) for a ticket submitted with
        `attribute=True`; attributions is None otherwise."""
        return self.pipeline.drain(ticket)

    def pipeline_stats(self) -> dict:
        """Status-ready pipeline counters (every backend has them; the
        device backends are where the in-flight window matters)."""
        return self.pipeline.stats()

    def kernel_stats(self) -> dict:
        """Device-kernel profile for status; non-device backends have
        none (the TPU backends override with pad/occupancy/compile
        accounting)."""
        return {}

    # -- checkpoint / restore -------------------------------------------
    def checkpoint(self) -> ConflictSetCheckpoint:
        """Serialize the live state (oldest-version watermark + the
        history step function) into a backend-agnostic snapshot. Drains
        the resolve pipeline first: a checkpoint must reflect every
        submitted batch, and the device backends D2H their key/version
        arrays — which blocks behind queued kernels anyway."""
        for t in list(self.pipeline.in_flight):
            self.pipeline.drain(t)
        return self._checkpoint_state()

    def restore(self, ckpt: ConflictSetCheckpoint) -> None:
        """Rebuild this backend's state from a checkpoint (taken from
        ANY backend; cross-backend restores yield bit-identical verdicts
        for every later batch). Existing state is discarded."""
        for t in list(self.pipeline.in_flight):
            self.pipeline.drain(t)
        self._restore_state(ckpt)

    def _checkpoint_state(self) -> ConflictSetCheckpoint:
        raise NotImplementedError(
            f"{self.BACKEND} backend does not support checkpoint()")

    def _restore_state(self, ckpt: ConflictSetCheckpoint) -> None:
        """Default restore: reset to the checkpoint baseline, then
        deterministically REPLAY the assignments as write-only batches
        in version order through the backend's own resolve step — every
        backend reconstructs the identical step function through its
        public contract (the merge assigns exactly [b,e) -> commit
        version; disjoint assignments commute, version order keeps
        non-decreasing-commit backends happy). Backends with a cheaper
        direct path (host array rebuilds) override this."""
        self._reset_state(int(ckpt.baseline_version))
        by_version: dict[int, list] = {}
        for b, e, v in ckpt.assignments:
            by_version.setdefault(int(v), []).append((b, e))
        for v in sorted(by_version):
            self.resolve([ResolverTransaction(v, (), tuple(by_version[v]))],
                         v, 0)
        # advance the window + ordering floor with a rangeless txn (it
        # can never conflict or be tooOld, and — unlike an empty batch —
        # every backend runs it through the full GC step)
        self.resolve([ResolverTransaction(ckpt.last_commit, (), ())],
                     ckpt.last_commit, ckpt.oldest_version)

    def _reset_state(self, baseline_version: int) -> None:
        raise NotImplementedError(
            f"{self.BACKEND} backend does not support restore()")


class PyConflictSet(ConflictSetBase):
    """Pure-Python step-function baseline (sorted boundary list + bisect)."""

    BACKEND = "python"

    def __init__(self, init_version: int = 0):
        # Invariant: _keys[0] == b"" always; _vals[i] covers [_keys[i], _keys[i+1}).
        # init_version baselines the whole keyspace (ref: clearConflictSet /
        # SkipList(v)); oldestVersion starts at 0 regardless (ref: ConflictSet
        # ctor, SkipList.cpp:926).
        self._keys: list[bytes] = [b""]
        self._vals: list[int] = [init_version]
        self._oldest = 0
        self._last_commit = init_version
        self._resolved_batches = 0

    @property
    def oldest_version(self) -> int:
        return self._oldest

    # -- checkpoint / restore ------------------------------------------
    def _checkpoint_state(self) -> ConflictSetCheckpoint:
        return checkpoint_from_step(self._keys, self._vals, self._oldest,
                                    self._last_commit)

    def _restore_state(self, ckpt: ConflictSetCheckpoint) -> None:
        self._keys, self._vals = step_from_checkpoint(ckpt)
        self._oldest = int(ckpt.oldest_version)
        self._last_commit = int(ckpt.last_commit)
        self._resolved_batches = 0

    # -- queries ------------------------------------------------------------
    def _range_max(self, begin: bytes, end: bytes) -> int:
        """Max version over intervals intersecting [begin, end)."""
        lo = bisect_right(self._keys, begin) - 1  # interval containing begin
        hi = bisect_left(self._keys, end)  # first boundary >= end
        return max(self._vals[lo:hi])

    # -- updates ------------------------------------------------------------
    def _assign(self, begin: bytes, end: bytes, version: int) -> None:
        """Set version for all keys in [begin, end) (ref: addConflictRanges)."""
        hi = bisect_right(self._keys, end) - 1
        v_end = self._vals[hi]  # version of the interval containing `end`
        lo = bisect_left(self._keys, begin)
        e_idx = bisect_left(self._keys, end)
        has_end = e_idx < len(self._keys) and self._keys[e_idx] == end
        repl_keys, repl_vals = [begin], [version]
        if not has_end:
            repl_keys.append(end)
            repl_vals.append(v_end)
        self._keys[lo:e_idx] = repl_keys
        self._vals[lo:e_idx] = repl_vals

    def _compact(self) -> None:
        """Collapse adjacent intervals that are both dead (< oldest) or equal.

        Dead intervals (version < oldestVersion) cannot conflict with any
        non-tooOld read, so merging them (keeping the max) is invisible
        (ref: removeBefore, SkipList.cpp:665 — the same window GC)."""
        keys, vals, oldest = self._keys, self._vals, self._oldest
        nk, nv = [keys[0]], [vals[0]]
        for i in range(1, len(keys)):
            v = vals[i]
            if (v < oldest and nv[-1] < oldest) or v == nv[-1]:
                if v > nv[-1]:
                    nv[-1] = v
            else:
                nk.append(keys[i])
                nv.append(v)
        self._keys, self._vals = nk, nv

    # -- the resolve step ---------------------------------------------------
    def resolve(self, txns: Sequence[ResolverTransaction], commit_version: int,
                new_oldest_version: int) -> list[int]:
        return self._resolve(txns, commit_version, new_oldest_version, None)

    def resolve_with_attribution(self, txns: Sequence[ResolverTransaction],
                                 commit_version: int,
                                 new_oldest_version: int):
        collect: list[list[int]] = [[] for _ in txns]
        verdicts = self._resolve(txns, commit_version, new_oldest_version,
                                 collect)
        return verdicts, [tuple(sorted(set(c))) for c in collect]

    def _resolve(self, txns: Sequence[ResolverTransaction],
                 commit_version: int, new_oldest_version: int,
                 collect) -> list[int]:
        n = len(txns)
        too_old = [False] * n
        conflict = [False] * n

        for t, tr in enumerate(txns):
            if tr.read_snapshot < self._oldest and len(tr.read_ranges):
                too_old[t] = True

        # (1) external check against history. Attribution mode checks
        # EVERY range (the short-circuit would under-report causes).
        for t, tr in enumerate(txns):
            if too_old[t]:
                continue
            for ri, (b, e) in enumerate(tr.read_ranges):
                if b < e and self._range_max(b, e) > tr.read_snapshot:
                    conflict[t] = True
                    if collect is None:
                        break
                    collect[t].append(ri)

        # (2) intra-batch, sequential in batch order. Attribution mode
        # also checks the reads of already-conflicted transactions
        # against the written set at their turn (their writes still
        # never join it), so the attributed set covers intra causes of
        # externally-conflicted transactions too.
        written: list[tuple[bytes, bytes]] = []  # sorted by begin, disjoint
        wkeys: list[bytes] = []  # begins, for bisect
        for t, tr in enumerate(txns):
            if conflict[t]:
                if collect is not None and not too_old[t]:
                    for ri, (b, e) in enumerate(tr.read_ranges):
                        if b < e and _overlaps_any(written, wkeys, b, e):
                            collect[t].append(ri)
                continue
            c = too_old[t]
            if not c:
                for ri, (b, e) in enumerate(tr.read_ranges):
                    if b < e and _overlaps_any(written, wkeys, b, e):
                        c = True
                        if collect is None:
                            break
                        collect[t].append(ri)
            conflict[t] = c
            if not c:
                for b, e in tr.write_ranges:
                    if b < e:
                        _interval_union_add(written, wkeys, b, e)

        # (3) merge surviving writes into history at the commit version
        for b, e in written:
            self._assign(b, e, commit_version)

        # (4) window GC
        if new_oldest_version > self._oldest:
            self._oldest = new_oldest_version
        if commit_version > self._last_commit:
            self._last_commit = commit_version
        self._resolved_batches += 1
        from ..flow import SERVER_KNOBS
        if self._resolved_batches % int(
                SERVER_KNOBS.conflict_set_compact_every) == 0:
            self._compact()

        return [TOO_OLD if too_old[t] else (CONFLICT if conflict[t] else COMMITTED)
                for t in range(n)]


def _overlaps_any(written: list, wkeys: list, b: bytes, e: bytes) -> bool:
    """Does [b,e) intersect any interval in the sorted disjoint set?"""
    i = bisect_right(wkeys, b) - 1
    if i >= 0 and written[i][1] > b:
        return True
    i += 1
    return i < len(written) and written[i][0] < e


def _interval_union_add(written: list, wkeys: list, b: bytes, e: bytes) -> None:
    """Insert [b,e) into a sorted disjoint interval set, coalescing overlaps."""
    i = bisect_right(wkeys, b) - 1
    start = i if (i >= 0 and written[i][1] >= b) else i + 1
    j = start
    while j < len(written) and written[j][0] <= e:
        j += 1
    if start < j:
        b = min(b, written[start][0])
        e = max(e, written[j - 1][1])
    written[start:j] = [(b, e)]
    wkeys[start:j] = [b]


class BruteForceConflictSet(ConflictSetBase):
    """O(everything) model for randomized cross-checks (ref test model:
    workloads/ConflictRange.actor.cpp:30 — exact conflict-or-not vs a model).

    Keeps every committed write range with its version; no GC compaction, so
    it is the ground truth the optimized backends must match bit-for-bit.
    """

    def __init__(self, init_version: int = 0):
        # \xff*64 stands in for the end of the keyspace; tests stay below it.
        self._writes: list[tuple[bytes, bytes, int]] = [(b"", b"\xff" * 64, init_version)]
        self._oldest = 0

    @property
    def oldest_version(self) -> int:
        return self._oldest

    def resolve(self, txns, commit_version, new_oldest_version):
        return self._resolve(txns, commit_version, new_oldest_version,
                             None)

    def resolve_with_attribution(self, txns, commit_version,
                                 new_oldest_version):
        collect: list[list[int]] = [[] for _ in txns]
        verdicts = self._resolve(txns, commit_version, new_oldest_version,
                                 collect)
        return verdicts, [tuple(sorted(set(c))) for c in collect]

    def _resolve(self, txns, commit_version, new_oldest_version, collect):
        n = len(txns)
        verdicts = [COMMITTED] * n
        added: list[tuple[bytes, bytes]] = []
        for t, tr in enumerate(txns):
            if tr.read_snapshot < self._oldest and len(tr.read_ranges):
                verdicts[t] = TOO_OLD
                continue
            bad = False
            for ri, (b, e) in enumerate(tr.read_ranges):
                if b >= e:
                    continue
                hit = any(wb < e and b < we and wv > tr.read_snapshot
                          for wb, we, wv in self._writes)
                hit = hit or any(wb < e and b < we for wb, we in added)
                if hit:
                    bad = True
                    if collect is None:
                        break
                    collect[t].append(ri)
            if bad:
                verdicts[t] = CONFLICT
            else:
                for b, e in tr.write_ranges:
                    if b < e:
                        added.append((b, e))
        for b, e in added:
            self._writes.append((b, e, commit_version))
        if new_oldest_version > self._oldest:
            self._oldest = new_oldest_version
        return verdicts


# ConflictRangePiece (and the checkpoint it slices) cross the wire in
# the resolver split/merge handoff RPCs (server/resolver_role.py), so
# both are RPC vocabulary; rpc.wire imports nothing from models, so
# the targeted registration is cycle-free.
from ..rpc import wire as _wire

_wire.register_message(ConflictSetCheckpoint)
_wire.register_message(ConflictRangePiece)
