"""Point-op TPU conflict-set backend (host wrapper).

Same `ConflictSetBase` contract and version-offset machinery as the
interval backend (tpu_resolver.TpuConflictSet), specialized to batches
whose conflict ranges are all single keys ([k, k+'\\x00')). The hot
commit path of an FDB-style workload is exactly this shape (ref:
NativeAPI point reads/sets produce single-key conflict ranges,
fdbclient/ReadYourWrites.actor.cpp), and the point restriction admits a
far cheaper device step (ops/point_kernel.py).

Raises ValueError for non-point ranges — callers that may see general
ranges use TpuConflictSet; `create_conflict_set("tpu-point")` is an
explicit opt-in. Parity: tests/test_point_resolver.py replays random
point workloads bit-exactly against BruteForce/PyConflictSet.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..ops.keys import next_pow2
from .conflict_set import ConflictSetCheckpoint, ResolverTransaction
from .tpu_resolver import (_KERNEL_MIN_RANGES, _KERNEL_MIN_TXNS, _MIN_CAP,
                           TpuConflictSet)

_POINT_KEY_BYTES = 8  # max key length the point bucket stores


class PointConflictSet(TpuConflictSet):
    """Latest-version-per-key map on device; single-sort merge step."""

    BACKEND = "tpu-point"

    def __init__(self, init_version: int = 0, key_bytes: int = _POINT_KEY_BYTES,
                 capacity: int = _MIN_CAP):
        self._init_version = init_version  # read by _initial_state hooks
        super().__init__(init_version=init_version, key_bytes=key_bytes,
                         capacity=capacity)
        self._count_hint = 0

    def _initial_state(self, init_version: int):
        """No whole-keyspace sentinel row: state starts empty (all +inf);
        the init_version baseline is enforced via init_off in the kernel."""
        hk = np.full((self._cap, self._n_words + 1), 0xFFFFFFFF, np.uint32)
        hv = np.full((self._cap,), -(1 << 30), np.int32)
        return hk, hv

    # -- checkpoint / restore ------------------------------------------
    def _checkpoint_state(self) -> ConflictSetCheckpoint:
        """Point state is a latest-version-per-key map, not a step
        function: the checkpoint carries one [k, k+'\\x00') assignment
        per live key over the init-version baseline — a representation
        the interval backends restore verbatim (cross-backend parity),
        and exactly what restores back into the point map."""
        from ..ops.fault_injection import convert_device_errors
        with convert_device_errors("drain", f"{self.BACKEND}.checkpoint"):
            hk, hv = np.asarray(self._hk), np.asarray(self._hv)
        keys, vals = self._decode_step(hk, hv)
        baseline = int(self._init_version)
        dead_v = min(baseline, self._oldest - 1)
        # the device map may hold several rows per key (an update adds a
        # new row; queries read the highest version in the key run, GC
        # retires the rest): the checkpoint is the per-key MAX
        latest: dict = {}
        for k, v in zip(keys, vals):
            if v > latest.get(k, v - 1):
                latest[k] = v
        assignments = []
        for k in sorted(latest):
            v = latest[k]
            if v < self._oldest:
                v = dead_v
            if v != baseline:
                assignments.append((k, k + b"\x00", v))
        return ConflictSetCheckpoint(self._oldest, self._last_commit,
                                     baseline, tuple(assignments))

    def _restore_state(self, ckpt: ConflictSetCheckpoint) -> None:
        """Direct point-map rebuild; every assignment must be a point
        within the key bucket (restoring an interval checkpoint into
        the point backend is an explicit opt-in that only works when
        the captured history is point-shaped)."""
        import jax.numpy as jnp

        pts = sorted(ckpt.assignments)
        for b, e, _v in pts:
            self._check_point(b, e)
        self._restore_bookkeeping(ckpt)
        self._cap = max(_MIN_CAP, self._cap, next_pow2(len(pts) + 2))
        hk, hv = self._encode_step([b for b, _e, _v in pts],
                                   [v for _b, _e, v in pts], self._cap)
        self._hk, self._hv = jnp.asarray(hk), jnp.asarray(hv)
        self._count_hint = len(pts)

    def _marshal_ranges(self, txns: Sequence[ResolverTransaction], too_old,
                        attribute: bool = False):
        """Point marshalling: end keys are never encoded (they are
        begin+'\\x00', one byte past the bucket width); each range is
        validated to be a point instead. Same ((lists), read_map)
        contract as the interval backend — keys stay raw bytes here and
        are encoded once, straight into the packed staging buffer, by
        `_dispatch`; txn ids ride one np.repeat per side."""
        n = len(txns)
        r_counts = np.zeros(n, np.int32)
        w_counts = np.zeros(n, np.int32)
        read_k: list = []
        write_k: list = []
        r_src: list = []
        for t, tr in enumerate(txns):
            if too_old[t]:
                continue
            c0 = len(read_k)
            for ri, (b, e) in enumerate(tr.read_ranges):
                if b >= e:
                    continue
                self._check_point(b, e)
                read_k.append(b)
                if attribute:
                    r_src.append(ri)
            r_counts[t] = len(read_k) - c0
            c0 = len(write_k)
            for b, e in tr.write_ranges:
                if b >= e:
                    continue
                self._check_point(b, e)
                write_k.append(b)
            w_counts[t] = len(write_k) - c0
        ids = np.arange(n, dtype=np.int32)
        rt = np.repeat(ids, r_counts)
        wt = np.repeat(ids, w_counts)
        read_map = ((rt, np.asarray(r_src, np.int32)) if attribute else ())
        return (read_k, None, rt, write_k, None, wt), read_map

    def _validate_range(self, b: bytes, e: bytes) -> None:
        self._check_point(b, e)

    def _check_point(self, b: bytes, e: bytes) -> None:
        if e != b + b"\x00":
            raise ValueError(
                "PointConflictSet handles single-key ranges only "
                f"(got [{b!r}, {e!r})); use the interval backend")
        if len(b) > self._key_bytes:
            raise ValueError(
                f"point key length {len(b)} exceeds bucket width "
                f"{self._key_bytes}")

    def resolve_arrays(self, snapshots, has_reads, rb, re, rt, wb, we, wt,
                       commit_version: int, new_oldest_version: int):
        """Pre-encoded fast path for point batches (same contract as the
        interval backend's resolve_arrays). The end-key arrays are
        accepted for signature compatibility but ignored — every range
        MUST be [k, k+'\\x00'); the caller (resolver role / bench
        pipeline) guarantees it, which is what makes the cheaper point
        kernel sound (round-2 VERDICT weak #9: the fastest backend must
        be drivable from the pipeline array path)."""
        for a in (rb, wb):
            if a.shape[1] != self._n_words + 1:
                raise ValueError(
                    f"encoded key width {a.shape[1] - 1} words does not "
                    f"match the point bucket ({self._n_words} words)")
        return super().resolve_arrays(snapshots, has_reads, rb, re, rt,
                                      wb, we, wt, commit_version,
                                      new_oldest_version)

    # -- packed single-buffer feed path --------------------------------
    def _feed_len(self, npad: int, nrp: int, nwp: int) -> int:
        from ..ops.point_kernel import point_feed_len
        return point_feed_len(npad, nrp, nwp, self._n_words)

    def _feed_views(self, buf, npad: int, nrp: int, nwp: int):
        from ..ops.point_kernel import point_batch_views
        return point_batch_views(buf, npad, nrp, nwp, self._n_words)

    def _dispatch(self, n, snapshots, too_old, rb, re, rt, wb, we, wt,
                  offsets, attribute: bool = False):
        commit_off, oldest_off, fixup = offsets
        from ..ops.conflict_kernel import SNAP_CLAMP
        from ..ops.point_kernel import make_point_resolve_packed_fn

        nr, nw = len(rt), len(wt)
        npad = next_pow2(max(n, _KERNEL_MIN_TXNS))
        # exact bucket: one extra slot would double both dimensions
        nrp = next_pow2(max(nr, _KERNEL_MIN_RANGES))
        nwp = next_pow2(max(nw, _KERNEL_MIN_RANGES))
        self._audit_capacity(nw)  # one state row per point write
        self._note_occupancy(n, npad, nr, nrp, nw, nwp)

        snap_off = np.clip(snapshots - self._base, 0,
                           SNAP_CLAMP).astype(np.int32)
        init_off = int(np.clip(self._init_version - self._base, 0,
                               SNAP_CLAMP + 1))
        # donate=True: chained-state entry (one state allocation across
        # the whole in-flight pipeline window, like the interval backend)
        fn = make_point_resolve_packed_fn(self._cap, npad, nrp, nwp,
                                          self._n_words,
                                          attribute=attribute,
                                          donate=True)
        # ONE host->device transfer per batch: the per-transfer latency
        # (not bandwidth) dominates the streamed path on a
        # remote-attached chip, so the eleven logical inputs — version
        # scalars included — ride one contiguous buffer built IN PLACE
        # over reused staging and unpack inside the jit
        buf, v = self._staging_views(npad, nrp, nwp)
        v.hdr[0] = commit_off
        v.hdr[1] = oldest_off
        v.hdr[2] = init_off
        v.snap[:n] = snap_off
        v.snap[n:] = 0
        v.too_old[:n] = too_old
        v.too_old[n:] = 0
        self._fill_keys(v.rk, rb, nr)
        v.rtxn[:nr] = rt
        v.rtxn[nr:] = npad
        v.rvalid[:nr] = 1
        v.rvalid[nr:] = 0
        self._fill_keys(v.wk, wb, nw)
        v.wtxn[:nw] = wt
        v.wtxn[nw:] = npad
        v.wvalid[:nw] = 1
        v.wvalid[nw:] = 0
        dev_buf = self._feed(buf)
        read_hit = None
        if attribute:
            self._hk, self._hv, count, conflict, read_hit = fn(
                self._hk, self._hv, dev_buf)
        else:
            self._hk, self._hv, count, conflict = fn(
                self._hk, self._hv, dev_buf)
        self._apply_fixup(fixup)
        self._note_count(count, nw)
        return conflict, read_hit
