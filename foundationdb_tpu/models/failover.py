"""Conflict-backend fault tolerance: checkpointed failover + shadow
validation around the accelerator backends.

Conflict resolution is the serial heart of the commit pipeline (the
"transactional conflict problem", arXiv:1804.00947): if the device
behind the resolver dies, the resolver — and with it every commit —
dies, because the history lives in donated device buffers with up to
RESOLVE_PIPELINE_DEPTH batches in flight. `FailoverConflictSet` makes
that loss survivable with BIT-IDENTICAL verdicts:

  checkpoint   every CONFLICT_CHECKPOINT_VERSIONS versions (or when the
               replay log hits CONFLICT_REPLAY_LOG_MAX) the active
               backend's state is snapshotted via the backend-agnostic
               checkpoint() API; the bounded replay log holds every
               batch submitted since.
  failover     a DeviceFaultError at any seam (submit dispatch,
               materialize readback, drain) discards the device state,
               rebuilds on a FRESH backend from the last checkpoint
               plus deterministic replay of the logged batches — the
               version chain makes replayed verdicts bit-identical by
               construction — resolves any in-flight tickets from the
               replay, and keeps serving. Up to DEVICE_FAULT_RETRIES
               rebuilds target a fresh device backend; past that the
               device is declared dead and the CPU fallback takes over.
  reattach     once failed over, the wrapper periodically (exponential
               backoff, DEVICE_REATTACH_BACKOFF..._MAX) tries to move
               the state back onto a fresh device backend.
  shadow       every SHADOW_RESOLVE_SAMPLE-th batch is re-resolved on a
               CPU shadow rebuilt from the checkpoint + log and the
               verdicts compared — runtime cross-checking in the
               early-detection spirit of arXiv:2301.06181. A mismatch
               traces SevError, surfaces in status.cluster.messages and
               the exporter, and (behind SHADOW_RESOLVE_FAIL_STOP)
               halts the resolver the way check_consistency treats
               replica corruption.

The wrapper is itself a ConflictSetBase, so the resolver role runs one
code path whatever the backend; host backends (python/native) are not
wrapped by default — they have no device to lose.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from ..flow.knobs import SERVER_KNOBS
from ..flow.stats import CounterCollection
from ..ops.fault_injection import DeviceFaultError, convert_device_errors
from .conflict_set import (ConflictSetBase, ConflictSetCheckpoint,
                           PyConflictSet, ResolverTransaction)

DEVICE_BACKENDS = ("tpu", "tpu-point", "sharded-tpu")


class ShadowResolveMismatch(RuntimeError):
    """The device backend's verdicts diverged from the CPU shadow —
    serializability is no longer guaranteed. Raised only when
    SHADOW_RESOLVE_FAIL_STOP is armed; otherwise the mismatch is
    traced/counted and the (suspect) primary verdicts keep flowing."""


def _sim_now() -> "float | None":
    """flow.now() when a scheduler is ambient; None for bare unit tests
    (the reattach backoff gate then degrades to 'always eligible')."""
    from ..flow.scheduler import _tls
    s = _tls.current
    return s.now() if s is not None else None


class _FailoverTicket:
    """The wrapper's own ticket: remembers the batch so a device fault
    can replay it, and caches the result so drains stay idempotent
    whatever happened to the inner backend in between."""

    __slots__ = ("commit_version", "n", "batch", "inner", "result",
                 "drained", "shadow", "shadow_checked")

    def __init__(self, batch):
        txns, commit_version, new_oldest, attribute = batch
        self.commit_version = commit_version
        self.n = len(txns)
        self.batch = batch
        self.inner = None
        self.result = None       # (verdicts, attributions) once known
        self.drained = False
        self.shadow = False
        self.shadow_checked = False


class FailoverConflictSet(ConflictSetBase):
    BACKEND = "failover"

    def __init__(self, primary_factory: Callable[[], ConflictSetBase],
                 fallback_factory: Optional[Callable[[], ConflictSetBase]]
                 = None,
                 backend_name: str = ""):
        self._primary_factory = primary_factory
        self._fallback_factory = fallback_factory or PyConflictSet
        self.backend_name = backend_name
        self.active: ConflictSetBase = primary_factory()
        # host-only input-contract check (key bucket width, point-range
        # shape): enforced while failed over so the permissive CPU
        # fallback rejects exactly the batches the device would — no
        # verdict divergence across the failover boundary, and nothing
        # un-replayable-on-device ever enters the log
        self._primary_validate = self.active.input_contract()
        self.on_primary = True
        self.stats = CounterCollection("conflict_failover")
        # last checkpoint + every batch submitted since (the replay log)
        self._ckpt: ConflictSetCheckpoint = self.active.checkpoint()
        self._ckpt_version = self._ckpt.last_commit
        self._log: list = []           # (txns, version, new_oldest, attr)
        self._pending: dict = {}       # version -> _FailoverTicket
        self._batches = 0
        self._consecutive_faults = 0
        self._reattach_at = 0.0
        self._reattach_backoff = float(SERVER_KNOBS.device_reattach_backoff)
        self.last_mismatch: Optional[dict] = None

    # -- the ConflictSetBase surface ------------------------------------
    @property
    def oldest_version(self) -> int:
        return self.active.oldest_version

    @property
    def interval_count(self):
        ic = getattr(self.active, "interval_count", None)
        if ic is not None:
            return int(ic() if callable(ic) else ic)
        return len(getattr(self.active, "_keys", ()))

    def kernel_stats(self) -> dict:
        return self.active.kernel_stats()

    def pipeline_stats(self) -> dict:
        return self.active.pipeline_stats()

    def checkpoint(self) -> ConflictSetCheckpoint:
        self._take_checkpoint(self._last_version())
        return self._ckpt

    def restore(self, ckpt: ConflictSetCheckpoint) -> None:
        # in-flight tickets must land BEFORE the state is replaced: a
        # ticket drained later would otherwise read verdicts computed
        # against the restored history (silently wrong), and the replay
        # log that could regenerate them is about to reset
        for t in list(self._pending.values()):
            self._materialize(t)
        self._pending.clear()
        self.active.restore(ckpt)
        self._ckpt = ckpt
        self._ckpt_version = ckpt.last_commit
        self._log.clear()

    def resolve(self, txns, commit_version, new_oldest_version):
        return self.drain(self.submit(txns, commit_version,
                                      new_oldest_version))

    def resolve_with_attribution(self, txns, commit_version,
                                 new_oldest_version):
        return self.drain_with_attribution(
            self.submit(txns, commit_version, new_oldest_version,
                        attribute=True))

    def submit(self, txns: Sequence[ResolverTransaction],
               commit_version: int, new_oldest_version: int,
               attribute: bool = False) -> _FailoverTicket:
        self._maybe_reattach()
        batch = (tuple(txns), commit_version, new_oldest_version,
                 attribute)
        t = _FailoverTicket(batch)
        self._batches += 1
        sample = int(SERVER_KNOBS.shadow_resolve_sample)
        # no sampling while failed over: the active backend IS the
        # shadow implementation, so a re-resolve proves nothing and
        # costs a checkpoint-restore + log replay per sample
        t.shadow = sample > 0 and self.on_primary \
            and self._batches % sample == 0
        while True:
            try:
                if not self.on_primary:
                    self._primary_validate(
                        txns, oldest_version=self.active.oldest_version)
                t.inner = self.active.submit(txns, commit_version,
                                             new_oldest_version,
                                             attribute=attribute)
                break
            except DeviceFaultError as e:
                # the batch was NOT logged yet: the rebuild restores the
                # pre-batch state and this loop re-dispatches it
                self._handle_fault(e, "submit")
        # a submit-time failover lands this batch on the fallback: the
        # sample would compare the shadow implementation to itself
        t.shadow = t.shadow and self.on_primary
        self._log.append(batch)
        self._pending[commit_version] = t
        self._maybe_checkpoint(commit_version)
        return t

    def drain(self, ticket: _FailoverTicket) -> list:
        return self.drain_with_attribution(ticket)[0]

    def drain_with_attribution(self, ticket: _FailoverTicket):
        self._materialize(ticket)
        ticket.drained = True
        self._pending.pop(ticket.commit_version, None)
        return ticket.result

    # -- fault handling --------------------------------------------------
    def _materialize(self, t: _FailoverTicket) -> None:
        if t.result is not None:
            return
        while t.result is None:
            try:
                t.result = self.active.drain_with_attribution(t.inner)
                self._consecutive_faults = 0
            except DeviceFaultError as e:
                # the rebuild replays the log and fills t.result itself
                self._handle_fault(e, "drain")
        if t.shadow and not t.shadow_checked:
            self._shadow_check(t)

    def _last_version(self) -> int:
        return self._log[-1][1] if self._log else self._ckpt_version

    def _rebuild_on(self, target: ConflictSetBase) -> dict:
        """Restore the checkpoint into `target` and deterministically
        replay every logged batch; returns {version: (verdicts, attrs)}.
        Raises DeviceFaultError if the target (a fresh device) faults
        mid-rebuild — the caller escalates."""
        target.restore(self._ckpt)
        results: dict = {}
        for txns, v, new_oldest, attribute in self._log:
            if attribute:
                results[v] = target.resolve_with_attribution(
                    txns, v, new_oldest)
            else:
                results[v] = (target.resolve(txns, v, new_oldest), None)
            self.stats.counter("replayed_batches").add(1)
        return results

    def _handle_fault(self, err: DeviceFaultError, where: str) -> None:
        from .. import flow
        self.stats.counter("device_faults").add(1)
        flow.TraceEvent("ConflictBackendDeviceFault", self.backend_name,
                        severity=flow.trace.SevWarnAlways).detail(
            Error=str(err), At=where, Active=self.active.BACKEND,
            Pending=len(self._pending),
            ReplayLog=len(self._log)).log()
        retries = int(SERVER_KNOBS.device_fault_retries)
        while True:
            self._consecutive_faults += 1
            to_primary = self.on_primary and \
                self._consecutive_faults <= retries
            try:
                # construction and restore touch the device too (H2D of
                # the restored state): a raw runtime error from a dead
                # device must escalate like a seam fault, not escape
                with convert_device_errors(
                        "submit", f"{self.backend_name}.rebuild"):
                    cand = (self._primary_factory() if to_primary
                            else self._fallback_factory())
                    results = self._rebuild_on(cand)
            except DeviceFaultError:
                continue   # fresh device faulted too: escalate
            break
        for v, res in results.items():
            pend = self._pending.get(v)
            if pend is not None and pend.result is None:
                pend.result = res
                pend.inner = None
                # replay-produced verdicts ARE the CPU shadow's answer:
                # re-checking them against another CPU replay proves
                # nothing, so the sample is skipped, not spent
                pend.shadow_checked = True
        self.active = cand
        if to_primary:
            self.stats.counter("device_recoveries").add(1)
        else:
            if self.on_primary:
                self.stats.counter("failovers").add(1)
                flow.TraceEvent("ConflictBackendFailover",
                                self.backend_name,
                                severity=flow.trace.SevWarnAlways).detail(
                    Fallback=cand.BACKEND,
                    ReplayedBatches=len(self._log),
                    CheckpointVersion=self._ckpt_version).log()
            self._bump_reattach_backoff()
        self.on_primary = to_primary

    def _bump_reattach_backoff(self) -> None:
        self._reattach_at = (_sim_now() or 0.0) + self._reattach_backoff
        self._reattach_backoff = min(
            self._reattach_backoff * 2,
            float(SERVER_KNOBS.device_reattach_backoff_max))

    def _maybe_reattach(self) -> None:
        """Try to move a failed-over history back onto a fresh device
        backend once past the backoff horizon. Pending tickets are
        materialized first (cheap on the CPU fallback — its inner
        tickets are born done) so the swap happens at a clean point
        even under overlapped pipelined traffic."""
        if self.on_primary or not int(SERVER_KNOBS.conflict_device_reattach):
            return
        now = _sim_now()
        if now is not None and now < self._reattach_at:
            return
        for t in list(self._pending.values()):
            self._materialize(t)
        try:
            with convert_device_errors(
                    "submit", f"{self.backend_name}.reattach"):
                cand = self._primary_factory()
                self._rebuild_on(cand)
        except Exception as e:  # noqa: BLE001 — the reattach is
            # opportunistic: neither a device fault nor a rebuild bug
            # (submit validation keeps the log device-replayable, but if
            # anything slips through) may take down the serving fallback
            if not isinstance(e, DeviceFaultError):
                from .. import flow
                flow.TraceEvent("ConflictBackendReattachError",
                                self.backend_name,
                                severity=flow.trace.SevWarnAlways).detail(
                    Error=repr(e)).log()
            self.stats.counter("reattach_failures").add(1)
            self._bump_reattach_backoff()
            return
        self.active = cand
        self.on_primary = True
        self._consecutive_faults = 0
        self._reattach_backoff = float(SERVER_KNOBS.device_reattach_backoff)
        self.stats.counter("reattaches").add(1)
        from .. import flow
        flow.TraceEvent("ConflictBackendReattached", self.backend_name
                        ).detail(Backend=cand.BACKEND,
                                 ReplayedBatches=len(self._log)).log()

    # -- checkpoint cadence ---------------------------------------------
    def _maybe_checkpoint(self, version: int) -> None:
        every = int(SERVER_KNOBS.conflict_checkpoint_versions)
        logmax = int(SERVER_KNOBS.conflict_replay_log_max)
        if (every > 0 and version - self._ckpt_version >= every) or \
                len(self._log) >= logmax:
            self._take_checkpoint(version)

    def _take_checkpoint(self, version: int) -> None:
        # the log resets, so replay can no longer regenerate verdicts:
        # materialize every in-flight ticket first (their results cache
        # on the wrapper ticket, keeping drains idempotent)
        for t in list(self._pending.values()):
            self._materialize(t)
        while True:
            try:
                self._ckpt = self.active.checkpoint()
                break
            except DeviceFaultError as e:
                self._handle_fault(e, "checkpoint")
        self._ckpt_version = version
        self._log.clear()
        self.stats.counter("checkpoints").add(1)

    # -- shadow validation ----------------------------------------------
    def _shadow_check(self, t: _FailoverTicket) -> None:
        """Re-resolve this batch on a CPU shadow rebuilt from the last
        checkpoint + the log prefix below it, and compare verdicts.
        Runs at materialize time — the only moment the log is
        guaranteed to still hold the batch's prefix."""
        from .. import flow
        t.shadow_checked = True
        self.stats.counter("shadow_sampled").add(1)
        txns, version, new_oldest, _attr = t.batch
        try:
            shadow = self._fallback_factory()
            shadow.restore(self._ckpt)
            for s_txns, s_v, s_oldest, _a in self._log:
                if s_v >= version:
                    break
                shadow.resolve(s_txns, s_v, s_oldest)
            want = shadow.resolve(list(txns), version, new_oldest)
        except Exception as e:  # noqa: BLE001 — validation must not
            # take down the validated path: an unbuildable shadow is a
            # missed sample, not a resolver outage
            self.stats.counter("shadow_errors").add(1)
            flow.TraceEvent("ShadowResolveError", self.backend_name,
                            severity=flow.trace.SevWarnAlways).detail(
                Version=version, Error=repr(e)).log()
            return
        got = list(t.result[0])
        if got == list(want):
            return
        self.stats.counter("shadow_mismatches").add(1)
        self.last_mismatch = {
            "version": version,
            "backend": self.active.BACKEND,
            "got": got,
            "want": list(want),
        }
        flow.TraceEvent("ShadowResolveMismatch", self.backend_name,
                        severity=flow.trace.SevError).detail(
            Version=version, Backend=self.active.BACKEND,
            Got="".join(map(str, got)),
            Want="".join(map(str, want))).log()
        if int(SERVER_KNOBS.shadow_resolve_fail_stop):
            raise ShadowResolveMismatch(
                f"conflict backend {self.active.BACKEND} verdicts "
                f"diverged from the CPU shadow at version {version}: "
                f"got {got}, shadow says {list(want)}")

    # -- status surface --------------------------------------------------
    def failover_stats(self) -> dict:
        snap = self.stats.snapshot()
        return {
            "active_backend": self.active.BACKEND,
            "on_primary": self.on_primary,
            "checkpoint_version": self._ckpt_version,
            "replay_log": len(self._log),
            "checkpoints": snap.get("checkpoints", 0),
            "device_faults": snap.get("device_faults", 0),
            "device_recoveries": snap.get("device_recoveries", 0),
            "failovers": snap.get("failovers", 0),
            "replayed_batches": snap.get("replayed_batches", 0),
            "reattaches": snap.get("reattaches", 0),
            "reattach_failures": snap.get("reattach_failures", 0),
            "shadow": {
                "sample": int(SERVER_KNOBS.shadow_resolve_sample),
                "sampled": snap.get("shadow_sampled", 0),
                "mismatches": snap.get("shadow_mismatches", 0),
                "errors": snap.get("shadow_errors", 0),
                "fail_stop": int(SERVER_KNOBS.shadow_resolve_fail_stop),
            },
        }


def create_resilient_conflict_set(backend: str,
                                  init_version: int = 0) -> ConflictSetBase:
    """The resolver role's backend factory: device backends are wrapped
    in the failover controller (unless CONFLICT_FAILOVER=0); host
    backends run bare — they have no accelerator to lose, and the
    python baseline IS the fallback/shadow reference."""
    from .native_backend import create_conflict_set
    if backend in DEVICE_BACKENDS and int(SERVER_KNOBS.conflict_failover):
        return FailoverConflictSet(
            primary_factory=lambda: create_conflict_set(backend,
                                                        init_version),
            fallback_factory=lambda: PyConflictSet(init_version),
            backend_name=backend)
    return create_conflict_set(backend, init_version)
