"""TPU conflict-set backend: host wrapper around the jitted kernel.

Same `ConflictSetBase` contract as the CPU baselines (the plugin
boundary, ref fdbrpc/LoadPlugin.h:29-44), so the resolver and the
deterministic simulator can swap backends and demand bit-identical
verdicts (ref self-check pattern: fdbserver/SkipList.cpp:1412-1551
skipListTest vs SlowConflictSet).

Host responsibilities (everything the device can't do with static
shapes):
  - marshal `ResolverTransaction` batches into flat padded arrays,
    bucketing txn/range counts to powers of two to bound recompiles;
  - track the absolute version base: the device stores int32 offsets
    (TPU-native word size) and is re-based long before overflow — valid
    because the MVCC window is only MAX_WRITE_TRANSACTION_LIFE_VERSIONS
    wide (ref fdbserver/Knobs.cpp MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
    Resolver.actor.cpp:155);
  - the tooOld test (snapshot < oldestVersion AND has reads, ref
    SkipList.cpp:979 addTransaction) on absolute versions;
  - grow the history capacity by doubling when the boundary count
    approaches it.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..flow.stats import CounterCollection
from .conflict_set import (COMMITTED, CONFLICT, TOO_OLD, ConflictSetBase,
                           ResolverTransaction)

# Minimum shape buckets: small batches all land in one compiled kernel
# instead of one per size (first compile is the expensive part on TPU).
_KERNEL_MIN_TXNS = 16
_KERNEL_MIN_RANGES = 32
_MIN_CAP = 1 << 10


class TpuConflictSet(ConflictSetBase):
    BACKEND = "tpu"

    def __init__(self, init_version: int = 0, key_bytes: int = 32,
                 capacity: int = _MIN_CAP):
        if key_bytes % 4:
            raise ValueError("key_bytes must be a multiple of 4")
        from ..ops.conflict_kernel import REBASE_THRESHOLD  # noqa: F401
        self._key_bytes = key_bytes
        self._n_words = key_bytes // 4
        self._cap = max(_MIN_CAP, int(capacity))
        if init_version >= (1 << 30):
            raise ValueError("init_version too large for the version window")
        self._base = 0
        self._oldest = 0
        self._last_commit = init_version
        self._count_hint = 1
        self._count_dev = None
        # (device_count, rows_added_since) pairs whose host copies were
        # started asynchronously: reading the OLDEST one rarely stalls
        # because newer batches are queued behind it, so the capacity
        # audit stays off the blocking-readback path (a forced
        # _sync_count drains the whole device pipeline — measured as
        # the dominant stall of the streamed bench)
        self._count_async: list = []
        self._rows_since_async = 0
        # per-backend-instance occupancy profile (ref: the reference's
        # ProxyStats-style accounting, here for the device batch shape:
        # real rows vs padded slots is THE quantity the shape-bucketing
        # trades against recompiles)
        self.profile = CounterCollection(f"{self.BACKEND}_kernel")
        self._hk, self._hv = self._to_device(*self._initial_state(init_version))

    def _initial_state(self, init_version: int):
        """Host arrays for the fresh history: one sentinel row baselining
        the whole keyspace at init_version (subclasses may differ)."""
        hk = np.full((self._cap, self._n_words + 1), 0xFFFFFFFF, np.uint32)
        hk[0] = 0
        hv = np.full((self._cap,), -(1 << 30), np.int32)
        hv[0] = init_version
        return hk, hv

    # -- device state helpers -------------------------------------------
    @staticmethod
    def _to_device(hk: np.ndarray, hv: np.ndarray):
        import jax.numpy as jnp
        return jnp.asarray(hk), jnp.asarray(hv)

    @property
    def oldest_version(self) -> int:
        return self._oldest

    @property
    def interval_count(self) -> int:
        self._sync_count()
        return self._count_hint

    def _sync_count(self) -> None:
        if self._count_dev is not None:
            # scalar for the single-shard backend, [n_shards] when sharded
            self._count_hint = int(np.max(np.asarray(self._count_dev)))
            self._count_dev = None

    def _grow(self, needed: int) -> None:
        from ..ops.keys import next_pow2
        new_cap = max(self._cap * 2, next_pow2(needed + 2))
        hk = np.full((new_cap, self._n_words + 1), 0xFFFFFFFF, np.uint32)
        hv = np.full((new_cap,), -(1 << 30), np.int32)
        hk[:self._cap] = np.asarray(self._hk)
        hv[:self._cap] = np.asarray(self._hv)
        self._cap = new_cap
        self._hk, self._hv = self._to_device(hk, hv)

    def _prepare_versions(self, commit_version: int, new_oldest_version: int,
                          window_floor: int):
        """Pick int32 offsets for this batch, re-basing if needed.

        Returns (commit_off, oldest_off, fixup). `window_floor` is the
        lowest version whose exact ordering still matters this batch:
        min over (the incoming oldestVersion, every non-tooOld read
        snapshot). Stored versions <= the base can never exceed any
        checked snapshot again, so clamping them during a shift is
        verdict-invariant.

        If the batch itself spans >= 2^30 versions (a recovery-style
        jump with pre-jump snapshots still live), verdicts are computed
        as usual — they never depend on the commit version's magnitude —
        with the merge done at a placeholder offset; the returned fixup
        (applied right after the kernel) rewrites placeholder entries to
        the true commit version relative to a fresh base. Valid because
        after the jump every earlier version is below the new
        oldestVersion, hence below every future checked snapshot."""
        from ..ops.conflict_kernel import REBASE_THRESHOLD, make_rebase_fn
        import jax.numpy as jnp

        target = max(self._oldest, new_oldest_version)
        if commit_version - self._base >= REBASE_THRESHOLD:
            new_base = max(self._base, min(target, window_floor))
            if commit_version - new_base < REBASE_THRESHOLD:
                delta = new_base - self._base
                if delta > (1 << 31) - 1:
                    # shift exceeds int32 arithmetic; every stored version
                    # is below the new base, so clamp them all dead
                    from ..ops.conflict_kernel import make_reset_fn
                    self._hv = make_reset_fn()(self._hv)
                else:
                    self._hv = make_rebase_fn()(self._hv, jnp.int32(delta))
                self._base = new_base
            elif commit_version - target < REBASE_THRESHOLD:
                p = REBASE_THRESHOLD
                oldest_off = min(max(target - self._base, 0), p)
                return p, oldest_off, (commit_version, max(self._base, target))
            else:
                raise OverflowError(
                    "version window exceeds 2^30: advance new_oldest_version "
                    "(ref: MAX_WRITE_TRANSACTION_LIFE_VERSIONS keeps the "
                    "live window ~5e6 versions wide)")
        return (commit_version - self._base,
                max(self._oldest, new_oldest_version) - self._base, None)

    def _apply_fixup(self, fixup) -> None:
        if fixup is None:
            return
        from ..ops.conflict_kernel import (REBASE_THRESHOLD,
                                           make_jump_fixup_fn,
                                           make_jump_fixup_large_fn)
        import jax.numpy as jnp
        commit_version, new_base = fixup
        delta = new_base - self._base
        if delta > (1 << 31) - 1:
            self._hv = make_jump_fixup_large_fn()(
                self._hv, jnp.int32(REBASE_THRESHOLD),
                jnp.int32(commit_version - new_base))
        else:
            self._hv = make_jump_fixup_fn()(
                self._hv, jnp.int32(REBASE_THRESHOLD),
                jnp.int32(commit_version - new_base), jnp.int32(delta))
        self._base = new_base

    # -- resolve --------------------------------------------------------
    def resolve(self, txns: Sequence[ResolverTransaction], commit_version: int,
                new_oldest_version: int) -> list[int]:
        conflict, too_old, n, _hit, _rmap = self._resolve_flags(
            txns, commit_version, new_oldest_version, attribute=False)
        if n == 0:
            return []
        return self.finalize_verdicts(conflict, too_old)

    def resolve_with_attribution(self, txns: Sequence[ResolverTransaction],
                                 commit_version: int,
                                 new_oldest_version: int):
        """Verdicts + per-txn conflicting read-range indices (see
        ConflictSetBase.resolve_with_attribution). The kernel computes
        per-read-slot cause flags in the same dispatch as the verdicts;
        the host routes flagged slots back through the marshalling map
        (slot -> (txn, original range index))."""
        conflict, too_old, n, read_hit, read_map = self._resolve_flags(
            txns, commit_version, new_oldest_version, attribute=True)
        if n == 0:
            return [], []
        verdicts = self.finalize_verdicts(conflict, too_old)
        attr: list[list[int]] = [[] for _ in range(n)]
        if read_map:
            hits = np.asarray(read_hit)[:len(read_map)]
            for slot in np.nonzero(hits)[0]:
                t, ri = read_map[slot]
                attr[t].append(ri)
        return verdicts, [tuple(a) for a in attr]

    def _resolve_flags(self, txns, commit_version, new_oldest_version,
                       attribute: bool = False):
        """Dispatch one batch; returns (device conflict flags, too_old,
        n, device per-read-slot cause flags — None unless `attribute` —
        read slot -> (txn, range index) map).

        Kept separate from `resolve` so callers that can overlap host and
        device work (the proxy pipeline / bench) can defer the readback.
        The per-range encoding is delegated to `_marshal_ranges` so the
        point backend can share everything else. `attribute` selects the
        kernel variant compiled WITH the attribution pass — a static
        property of the compiled program, not a runtime switch.
        """
        if commit_version < self._last_commit:
            raise ValueError("commit versions must be non-decreasing "
                             "(ref: Resolver version ordering, "
                             "Resolver.actor.cpp:104-115)")
        n = len(txns)
        if n == 0:
            self._last_commit = commit_version
            self._oldest = max(self._oldest, new_oldest_version)
            return None, None, 0, None, []
        live_snaps = [tr.read_snapshot for tr in txns
                      if len(tr.read_ranges) and tr.read_snapshot >= self._oldest]
        offsets = self._prepare_versions(
            commit_version, new_oldest_version,
            min([max(self._oldest, new_oldest_version)] + live_snaps))

        too_old = np.zeros(n, bool)
        snapshots = np.zeros(n, np.int64)
        for t, tr in enumerate(txns):
            snapshots[t] = tr.read_snapshot
            if tr.read_snapshot < self._oldest and len(tr.read_ranges):
                too_old[t] = True

        arrays, read_map = self._marshal_ranges(txns, too_old)
        conflict, read_hit = self._dispatch(
            n, snapshots, too_old, *arrays, offsets, attribute=attribute)
        self._last_commit = commit_version  # only after a successful batch
        self._oldest = max(self._oldest, new_oldest_version)
        return conflict, too_old, n, read_hit, read_map

    def _marshal_ranges(self, txns, too_old):
        """Flatten and encode the batch's conflict ranges in txn order.

        Returns ((rb, re, rt, wb, we, wt), read_map) — the arrays handed
        to `_dispatch` plus, per read slot, the (txn index, ORIGINAL
        read_ranges index) pair attribution routes hits back through.
        tooOld txns contribute no ranges at all (ref: SkipList.cpp:979
        addTransaction)."""
        read_b: list[bytes] = []
        read_e: list[bytes] = []
        read_t: list[int] = []
        read_map: list[tuple] = []
        write_b: list[bytes] = []
        write_e: list[bytes] = []
        write_t: list[int] = []
        for t, tr in enumerate(txns):
            if too_old[t]:
                continue
            for ri, (b, e) in enumerate(tr.read_ranges):
                if b < e:
                    read_b.append(b)
                    read_e.append(e)
                    read_t.append(t)
                    read_map.append((t, ri))
            for b, e in tr.write_ranges:
                if b < e:
                    write_b.append(b)
                    write_e.append(e)
                    write_t.append(t)

        from ..ops.keys import encode_keys
        nr, nw = len(read_t), len(write_t)
        keys = encode_keys(read_b + read_e + write_b + write_e,
                           self._key_bytes)
        return ((keys[:nr], keys[nr:2 * nr], np.asarray(read_t, np.int32),
                 keys[2 * nr:2 * nr + nw], keys[2 * nr + nw:],
                 np.asarray(write_t, np.int32)), read_map)

    def resolve_arrays(self, snapshots: np.ndarray, has_reads: np.ndarray,
                       rb: np.ndarray, re: np.ndarray, rt: np.ndarray,
                       wb: np.ndarray, we: np.ndarray, wt: np.ndarray,
                       commit_version: int, new_oldest_version: int):
        """Pre-encoded fast path: keys already packed via ops.keys.encode_keys,
        ranges flattened with per-range txn ids. Skips Python marshalling so
        benchmarks/pipelines measure device throughput, and defers the
        verdict readback (returns the device conflict flags + host too_old).
        Ranges of tooOld txns may be included — their writes are excluded by
        the kernel and their reads only affect their own (overridden) flag."""
        if commit_version < self._last_commit:
            raise ValueError("commit versions must be non-decreasing")
        too_old = (snapshots < self._oldest) & has_reads.astype(bool)
        live = has_reads.astype(bool) & ~too_old
        floor = min(int(snapshots[live].min()) if live.any() else commit_version,
                    max(self._oldest, new_oldest_version))
        offsets = self._prepare_versions(commit_version, new_oldest_version,
                                         floor)
        conflict, _read_hit = self._dispatch(
            snapshots.shape[0], snapshots, too_old, rb, re,
            np.asarray(rt, np.int32), wb, we, np.asarray(wt, np.int32),
            offsets)
        self._last_commit = commit_version  # only after a successful batch
        self._oldest = max(self._oldest, new_oldest_version)
        return conflict, too_old

    @staticmethod
    def finalize_verdicts(conflict, too_old) -> list[int]:
        n = too_old.shape[0]
        conflict = np.asarray(conflict)[:n]
        return [TOO_OLD if too_old[t] else
                (CONFLICT if conflict[t] else COMMITTED) for t in range(n)]

    # -- shared marshalling helpers (used by the point subclass too) ----
    def _pad_keys(self, a: np.ndarray, size: int) -> np.ndarray:
        out = np.zeros((size, self._n_words + 1), np.uint32)
        out[:a.shape[0]] = a
        return out

    @staticmethod
    def _pad_idx(a: np.ndarray, size: int, fill: int) -> np.ndarray:
        out = np.full((size,), fill, np.int32)
        out[:a.shape[0]] = a
        return out

    def _note_count(self, count, new_rows: int) -> None:
        """Record a batch's device-resident row count and start its
        host copy without blocking; refresh the hint from the oldest
        pending copy (usually already arrived) plus the rows added
        since it was taken."""
        self._count_dev = count
        self._rows_since_async += new_rows
        try:
            count.copy_to_host_async()
        except AttributeError:
            pass   # numpy-backed (CPU tests)
        self._count_async.append((count, self._rows_since_async))
        if len(self._count_async) > 2:
            old, rows_after = self._count_async.pop(0)
            stale = int(np.max(np.asarray(old)))
            bound = stale + (self._rows_since_async - rows_after)
            if bound < self._count_hint:
                self._count_hint = bound

    def _audit_capacity(self, new_rows: int) -> None:
        """Grow the device state if this batch could overflow it.

        `new_rows` = state rows this batch can add (2 boundaries per
        write for the interval backend, 1 per write for points)."""
        if self._count_hint + new_rows + 2 > self._cap:
            self._sync_count()
            self._count_async.clear()
            self._rows_since_async = 0
        if self._count_hint + new_rows + 2 > self._cap:
            self._grow(self._count_hint + new_rows)
        self._count_hint = min(self._cap - 1, self._count_hint + new_rows)

    def _note_occupancy(self, n, npad, nr, nrp, nw, nwp) -> None:
        """Per-batch pad-shape accounting: real rows vs padded slots per
        dimension. Occupancy = rows/slots over a window; chronically low
        ratios mean the bucket floors are wasting device time, chronic
        recompiles (ops counters) mean they're too tight."""
        p = self.profile
        p.counter("batches").add(1)
        p.counter("txns").add(int(n))
        p.counter("txn_slots").add(int(npad))
        p.counter("reads").add(int(nr))
        p.counter("read_slots").add(int(nrp))
        p.counter("writes").add(int(nw))
        p.counter("write_slots").add(int(nwp))

    def kernel_stats(self) -> dict:
        """This backend INSTANCE's status-ready profile: pad sizes,
        occupancy, backend + platform name, state rows. The jitted
        compile/execute counters are per-process (the lru-cached
        kernels are shared across instances), so they are reported ONCE
        at cluster level by the status assembler — folding them here
        would attribute every instance's compiles to every resolver."""
        import jax
        snap = self.profile.snapshot()
        occ = {}
        for dim in ("txn", "read", "write"):
            rows = snap.get(f"{dim}s", 0)
            slots = snap.get(f"{dim}_slots", 0)
            occ[dim] = round(rows / slots, 4) if slots else None
        return {"backend": self.BACKEND,
                "platform": jax.default_backend(),
                "capacity": self._cap,
                "state_rows": self._count_hint,
                "batches": snap.get("batches", 0),
                "occupancy": occ,
                # raw real-row and padded-slot totals per dimension
                "counts": {k: v for k, v in snap.items()
                           if k != "batches"}}

    def _call_kernel(self, npad, nrp, nwp, args, attribute: bool):
        """Run one padded batch through the single-shard jitted kernel.

        Subclasses (the sharded resolver) override this to dispatch the
        same padded batch across a device mesh."""
        from ..ops.conflict_kernel import make_resolve_fn
        fn = make_resolve_fn(self._cap, npad, nrp, nwp, self._n_words,
                             attribute=attribute)
        read_hit = None
        if attribute:
            self._hk, self._hv, count, conflict, read_hit = fn(
                self._hk, self._hv, *args)
        else:
            self._hk, self._hv, count, conflict = fn(
                self._hk, self._hv, *args)
        return count, conflict, read_hit

    def _dispatch(self, n, snapshots, too_old, rb, re, rt, wb, we, wt,
                  offsets, attribute: bool = False):
        commit_off, oldest_off, fixup = offsets
        import jax.numpy as jnp

        from ..ops.conflict_kernel import SNAP_CLAMP
        from ..ops.keys import next_pow2

        nr, nw = rb.shape[0], wb.shape[0]
        npad = next_pow2(max(n, _KERNEL_MIN_TXNS))
        # exact bucket: one extra slot would double both dimensions
        nrp = next_pow2(max(nr, _KERNEL_MIN_RANGES))
        nwp = next_pow2(max(nw, _KERNEL_MIN_RANGES))
        self._audit_capacity(2 * nw)
        self._note_occupancy(n, npad, nr, nrp, nw, nwp)

        snap_off = np.clip(snapshots - self._base, 0, SNAP_CLAMP).astype(np.int32)
        snap_p = np.zeros(npad, np.int32)
        snap_p[:n] = snap_off
        tooold_p = np.zeros(npad, bool)
        tooold_p[:n] = too_old
        rvalid = np.zeros(nrp, bool)
        rvalid[:nr] = True
        wvalid = np.zeros(nwp, bool)
        wvalid[:nw] = True

        count, conflict, read_hit = self._call_kernel(npad, nrp, nwp, (
            jnp.asarray(snap_p), jnp.asarray(tooold_p),
            jnp.asarray(self._pad_keys(rb, nrp)),
            jnp.asarray(self._pad_keys(re, nrp)),
            jnp.asarray(self._pad_idx(rt, nrp, npad)), jnp.asarray(rvalid),
            jnp.asarray(self._pad_keys(wb, nwp)),
            jnp.asarray(self._pad_keys(we, nwp)),
            jnp.asarray(self._pad_idx(wt, nwp, npad)), jnp.asarray(wvalid),
            jnp.int32(commit_off), jnp.int32(oldest_off)), attribute)
        self._apply_fixup(fixup)
        self._note_count(count, 2 * nw)
        return conflict, read_hit
