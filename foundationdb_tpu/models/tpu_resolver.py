"""TPU conflict-set backend: host wrapper around the jitted kernel.

Same `ConflictSetBase` contract as the CPU baselines (the plugin
boundary, ref fdbrpc/LoadPlugin.h:29-44), so the resolver and the
deterministic simulator can swap backends and demand bit-identical
verdicts (ref self-check pattern: fdbserver/SkipList.cpp:1412-1551
skipListTest vs SlowConflictSet).

Host responsibilities (everything the device can't do with static
shapes):
  - marshal `ResolverTransaction` batches into flat padded arrays,
    bucketing txn/range counts to powers of two to bound recompiles;
  - track the absolute version base: the device stores int32 offsets
    (TPU-native word size) and is re-based long before overflow — valid
    because the MVCC window is only MAX_WRITE_TRANSACTION_LIFE_VERSIONS
    wide (ref fdbserver/Knobs.cpp MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
    Resolver.actor.cpp:155);
  - the tooOld test (snapshot < oldestVersion AND has reads, ref
    SkipList.cpp:979 addTransaction) on absolute versions;
  - grow the history capacity by doubling when the boundary count
    approaches it.
"""

from __future__ import annotations

import time
from typing import Sequence

import numpy as np

from ..flow.stats import CounterCollection
# hoisted out of the resolver hot path (the per-call form re-ran the
# import machinery on every marshalled batch; same fix PR 13 applied to
# the storage metrics path) — this module is only ever imported through
# the lazy backend factory, so the transitive jax import stays off the
# CPU-only paths
from ..ops.keys import decode_keys, encode_keys, encode_keys_into, next_pow2
from .conflict_set import (COMMITTED, CONFLICT, TOO_OLD, ConflictSetBase,
                           ConflictSetCheckpoint, ResolveTicket,
                           ResolverTransaction, checkpoint_from_step,
                           step_from_checkpoint)

# Minimum shape buckets: small batches all land in one compiled kernel
# instead of one per size (first compile is the expensive part on TPU).
_KERNEL_MIN_TXNS = 16
_KERNEL_MIN_RANGES = 32
_MIN_CAP = 1 << 10


def _unaliasable_u32(n: int) -> np.ndarray:
    """A uint32 host staging buffer deliberately NOT 64-byte aligned.

    XLA's CPU client zero-copies ("aliases") sufficiently aligned numpy
    buffers into device arrays (HostBufferSemantics IMMUTABLE_ZERO_COPY)
    instead of copying — mutating a reused staging buffer would then
    corrupt an in-flight batch. Any zero-copy path fundamentally
    requires alignment, so an off-alignment start (4 mod 64) forces a
    real copy on every backend — which is exactly what an H2D transfer
    is on a real accelerator. tests/test_packed_interval.py pins the
    no-alias invariant with a mutate-after-transfer canary."""
    raw = np.empty(n + 16, np.uint32)
    off = ((4 - raw.ctypes.data) % 64) // 4
    return raw[off:off + n]


class TpuConflictSet(ConflictSetBase):
    BACKEND = "tpu"

    def __init__(self, init_version: int = 0, key_bytes: int = 32,
                 capacity: int = _MIN_CAP):
        if key_bytes % 4:
            raise ValueError("key_bytes must be a multiple of 4")
        from ..ops.conflict_kernel import REBASE_THRESHOLD  # noqa: F401
        self._key_bytes = key_bytes
        self._n_words = key_bytes // 4
        self._cap = max(_MIN_CAP, int(capacity))
        if init_version >= (1 << 30):
            raise ValueError("init_version too large for the version window")
        self._init_version = init_version
        self._base = 0
        self._oldest = 0
        self._last_commit = init_version
        self._count_hint = 1
        self._count_dev = None
        # (device_count, rows_added_since) pairs whose host copies were
        # started asynchronously: reading the OLDEST one rarely stalls
        # because newer batches are queued behind it, so the capacity
        # audit stays off the blocking-readback path (a forced
        # _sync_count drains the whole device pipeline — measured as
        # the dominant stall of the streamed bench)
        self._count_async: list = []
        self._rows_since_async = 0
        # per-backend-instance occupancy profile (ref: the reference's
        # ProxyStats-style accounting, here for the device batch shape:
        # real rows vs padded slots is THE quantity the shape-bucketing
        # trades against recompiles)
        self.profile = CounterCollection(f"{self.BACKEND}_kernel")
        # packed-feed staging: per (txn, read, write) shape bucket, a
        # small ROTATING pool of reusable single-transfer host buffers
        # (see _staging_views) + a monotonically grown key-encode
        # scratch matrix — a steady-state batch stream is
        # allocation-flat (counted by the staging_allocs counter)
        self._staging: dict = {}
        self._staging_idx: dict = {}
        self._enc_scratch = np.empty((0, 0), np.uint8)
        self._hk, self._hv = self._to_device(*self._initial_state(init_version))

    def _initial_state(self, init_version: int):
        """Host arrays for the fresh history: one sentinel row baselining
        the whole keyspace at init_version (subclasses may differ)."""
        hk = np.full((self._cap, self._n_words + 1), 0xFFFFFFFF, np.uint32)
        hk[0] = 0
        hv = np.full((self._cap,), -(1 << 30), np.int32)
        hv[0] = init_version
        return hk, hv

    # -- device state helpers -------------------------------------------
    @staticmethod
    def _to_device(hk: np.ndarray, hv: np.ndarray):
        import jax.numpy as jnp
        return jnp.asarray(hk), jnp.asarray(hv)

    @property
    def oldest_version(self) -> int:
        return self._oldest

    @property
    def interval_count(self) -> int:
        """Upper bound on live state rows, refreshed from async count
        copies that HAVE ARRIVED — it never drains the in-flight
        pipeline (this audit was the dominant streamed stall: reading
        the NEWEST count blocks behind every queued batch). Exact
        counts are available via `_sync_count` for tests/debug."""
        while self._count_async and self._is_ready(self._count_async[0][0]):
            self._consume_oldest_count()
        return self._count_hint

    @staticmethod
    def _is_ready(arr) -> bool:
        try:
            return bool(arr.is_ready())
        except AttributeError:
            return True   # numpy-backed (CPU tests): always concrete

    @staticmethod
    def _start_host_copy(arr) -> None:
        """Begin an async D2H copy so a later np.asarray is a wait, not
        a round-trip; no-op for host-resident arrays."""
        if arr is None:
            return
        try:
            arr.copy_to_host_async()
        except AttributeError:
            pass

    def _sync_count(self) -> None:
        """EXACT current row count: blocks until the newest submitted
        batch lands (a full pipeline drain — last resort only)."""
        if self._count_dev is not None:
            # scalar for the single-shard backend, [n_shards] when sharded
            self._count_hint = int(np.max(np.asarray(self._count_dev)))
            self._count_dev = None
        self._count_async.clear()
        self._rows_since_async = 0

    def _grow(self, needed: int) -> None:
        new_cap = max(self._cap * 2, next_pow2(needed + 2))
        hk = np.full((new_cap, self._n_words + 1), 0xFFFFFFFF, np.uint32)
        hv = np.full((new_cap,), -(1 << 30), np.int32)
        hk[:self._cap] = np.asarray(self._hk)
        hv[:self._cap] = np.asarray(self._hv)
        self._cap = new_cap
        self._hk, self._hv = self._to_device(hk, hv)

    def _prepare_versions(self, commit_version: int, new_oldest_version: int,
                          window_floor: int):
        """Pick int32 offsets for this batch, re-basing if needed.

        Returns (commit_off, oldest_off, fixup). `window_floor` is the
        lowest version whose exact ordering still matters this batch:
        min over (the incoming oldestVersion, every non-tooOld read
        snapshot). Stored versions <= the base can never exceed any
        checked snapshot again, so clamping them during a shift is
        verdict-invariant.

        If the batch itself spans >= 2^30 versions (a recovery-style
        jump with pre-jump snapshots still live), verdicts are computed
        as usual — they never depend on the commit version's magnitude —
        with the merge done at a placeholder offset; the returned fixup
        (applied right after the kernel) rewrites placeholder entries to
        the true commit version relative to a fresh base. Valid because
        after the jump every earlier version is below the new
        oldestVersion, hence below every future checked snapshot."""
        from ..ops.conflict_kernel import REBASE_THRESHOLD, make_rebase_fn
        import jax.numpy as jnp

        target = max(self._oldest, new_oldest_version)
        if commit_version - self._base >= REBASE_THRESHOLD:
            new_base = max(self._base, min(target, window_floor))
            if commit_version - new_base < REBASE_THRESHOLD:
                delta = new_base - self._base
                if delta > (1 << 31) - 1:
                    # shift exceeds int32 arithmetic; every stored version
                    # is below the new base, so clamp them all dead
                    from ..ops.conflict_kernel import make_reset_fn
                    self._hv = make_reset_fn()(self._hv)
                else:
                    self._hv = make_rebase_fn()(self._hv, jnp.int32(delta))
                self._base = new_base
            elif commit_version - target < REBASE_THRESHOLD:
                p = REBASE_THRESHOLD
                oldest_off = min(max(target - self._base, 0), p)
                return p, oldest_off, (commit_version, max(self._base, target))
            else:
                raise OverflowError(
                    "version window exceeds 2^30: advance new_oldest_version "
                    "(ref: MAX_WRITE_TRANSACTION_LIFE_VERSIONS keeps the "
                    "live window ~5e6 versions wide)")
        return (commit_version - self._base,
                max(self._oldest, new_oldest_version) - self._base, None)

    def _apply_fixup(self, fixup) -> None:
        if fixup is None:
            return
        from ..ops.conflict_kernel import (REBASE_THRESHOLD,
                                           make_jump_fixup_fn,
                                           make_jump_fixup_large_fn)
        import jax.numpy as jnp
        commit_version, new_base = fixup
        delta = new_base - self._base
        if delta > (1 << 31) - 1:
            self._hv = make_jump_fixup_large_fn()(
                self._hv, jnp.int32(REBASE_THRESHOLD),
                jnp.int32(commit_version - new_base))
        else:
            self._hv = make_jump_fixup_fn()(
                self._hv, jnp.int32(REBASE_THRESHOLD),
                jnp.int32(commit_version - new_base), jnp.int32(delta))
        self._base = new_base

    # -- checkpoint / restore -------------------------------------------
    def _decode_step(self, hk: np.ndarray, hv: np.ndarray):
        """One shard's device state back into a (keys, vals) step
        function with ABSOLUTE versions: D2H'd key rows decode exactly
        (encode_keys keeps the byte length), offsets re-base, and +inf
        pad rows (length word 0xFFFFFFFF) drop out."""
        real = np.flatnonzero(hk[:, -1] != 0xFFFFFFFF)
        keys = decode_keys(hk[real])
        vals = [int(v) + self._base for v in hv[real]]
        return keys, vals

    def _checkpoint_state(self) -> ConflictSetCheckpoint:
        from ..ops.fault_injection import convert_device_errors
        with convert_device_errors("drain", f"{self.BACKEND}.checkpoint"):
            hk, hv = np.asarray(self._hk), np.asarray(self._hv)
        keys, vals = self._decode_step(hk, hv)
        return checkpoint_from_step(keys, vals, self._oldest,
                                    self._last_commit)

    def _restore_bookkeeping(self, ckpt: ConflictSetCheckpoint) -> None:
        """Watermarks + version window + async-count caches after a
        restore (shared by the interval and point restore paths)."""
        self._oldest = int(ckpt.oldest_version)
        self._last_commit = int(ckpt.last_commit)
        self._init_version = int(ckpt.baseline_version)
        # re-base so every live offset fits the int32 device window
        # (same invariant _prepare_versions maintains batch to batch)
        self._base = max(0, int(ckpt.oldest_version))
        self._count_dev = None
        self._count_async.clear()
        self._rows_since_async = 0

    def _restore_state(self, ckpt: ConflictSetCheckpoint) -> None:
        keys, vals = step_from_checkpoint(ckpt)
        self._restore_bookkeeping(ckpt)
        self._install_step(keys, vals)

    def _encode_step(self, keys, vals, cap: int):
        """Host (hk, hv) arrays for one shard's step function: encoded
        keys +inf-padded to cap, versions as clamped offsets from the
        restored base."""
        from ..ops.conflict_kernel import REBASE_THRESHOLD
        from ..ops.rmq import VDEAD
        hk = np.full((cap, self._n_words + 1), 0xFFFFFFFF, np.uint32)
        hv = np.full((cap,), VDEAD, np.int32)
        if keys:
            hk[:len(keys)] = encode_keys(list(keys), self._key_bytes)
        for i, v in enumerate(vals):
            off = int(v) - self._base
            if off >= REBASE_THRESHOLD:
                raise OverflowError(
                    "checkpoint version window exceeds 2^30 (see "
                    "MAX_WRITE_TRANSACTION_LIFE_VERSIONS)")
            hv[i] = max(off, VDEAD)
        return hk, hv

    def _install_step(self, keys, vals) -> None:
        """Install a restored global step function as device state
        (the sharded backend overrides this with a per-shard clip)."""
        import jax.numpy as jnp
        self._cap = max(_MIN_CAP, self._cap, next_pow2(len(keys) + 2))
        hk, hv = self._encode_step(keys, vals, self._cap)
        self._hk, self._hv = jnp.asarray(hk), jnp.asarray(hv)
        self._count_hint = max(1, len(keys))

    # -- resolve --------------------------------------------------------
    def resolve(self, txns: Sequence[ResolverTransaction], commit_version: int,
                new_oldest_version: int) -> list[int]:
        return self.drain(self.submit(txns, commit_version,
                                      new_oldest_version))

    def resolve_with_attribution(self, txns: Sequence[ResolverTransaction],
                                 commit_version: int,
                                 new_oldest_version: int):
        """Verdicts + per-txn conflicting read-range indices (see
        ConflictSetBase.resolve_with_attribution). The kernel computes
        per-read-slot cause flags in the same dispatch as the verdicts;
        the host routes flagged slots back through the marshalling map
        (slot -> (txn, original range index))."""
        return self.drain_with_attribution(
            self.submit(txns, commit_version, new_oldest_version,
                        attribute=True))

    def submit(self, txns: Sequence[ResolverTransaction],
               commit_version: int, new_oldest_version: int,
               attribute: bool = False) -> ResolveTicket:
        """Asynchronous half of the split resolve: marshal + H2D +
        kernel dispatch without blocking on any result (JAX async
        dispatch queues the work; the history carry chains on device,
        with input-buffer donation, so batch N+1's kernel consumes
        batch N's output arrays directly). Up to RESOLVE_PIPELINE_DEPTH
        tickets stay in flight; `drain` awaits only one batch's verdict
        D2H. Verdict order is the submission (= version) order by
        construction — the device serializes the chained state — so
        pipelined verdicts are bit-identical to the serial path."""
        t0 = time.perf_counter()
        conflict, too_old, n, read_hit, read_map = self._resolve_flags(
            txns, commit_version, new_oldest_version, attribute=attribute)
        if n == 0:
            ticket = ResolveTicket(commit_version, 0,
                                   result=([], [] if attribute else None))
        else:
            self._start_host_copy(conflict)
            self._start_host_copy(read_hit)

            def materialize():
                from ..ops.fault_injection import (convert_device_errors,
                                                   g_device_faults)
                g_device_faults.check("materialize", self.BACKEND)
                with convert_device_errors("materialize", self.BACKEND):
                    return _materialize_inner()

            def _materialize_inner():
                verdicts = self.finalize_verdicts(conflict, too_old)
                if not attribute:
                    return verdicts, None
                attr: list[list[int]] = [[] for _ in range(n)]
                if read_map:
                    slot_txn, slot_src = read_map
                    hits = np.asarray(read_hit)[:slot_txn.shape[0]]
                    for slot in np.nonzero(hits)[0]:
                        attr[int(slot_txn[slot])].append(int(slot_src[slot]))
                return verdicts, [tuple(a) for a in attr]

            ticket = ResolveTicket(commit_version, n,
                                   materialize=materialize)
        self.pipeline.note_submit(ticket, t0)
        return ticket

    def submit_arrays(self, snapshots, has_reads, rb, re, rt, wb, we, wt,
                      commit_version: int,
                      new_oldest_version: int) -> ResolveTicket:
        """Pipelined pre-encoded fast path: `resolve_arrays` wrapped in
        a ticket whose `drain_arrays` yields (conflict[:n] ndarray,
        too_old ndarray) — the bench/pipeline callers' contract."""
        t0 = time.perf_counter()
        conflict, too_old = self.resolve_arrays(
            snapshots, has_reads, rb, re, rt, wb, we, wt,
            commit_version, new_oldest_version)
        self._start_host_copy(conflict)
        n = snapshots.shape[0]

        def materialize():
            from ..ops.fault_injection import (convert_device_errors,
                                               g_device_faults)
            g_device_faults.check("materialize", self.BACKEND)
            with convert_device_errors("materialize", self.BACKEND):
                return np.asarray(conflict)[:n], too_old

        ticket = ResolveTicket(commit_version, n, materialize=materialize)
        self.pipeline.note_submit(ticket, t0)
        return ticket

    def drain_arrays(self, ticket: ResolveTicket):
        """(conflict flags ndarray, too_old ndarray) for a ticket from
        `submit_arrays` (idempotent, any order)."""
        return self.pipeline.drain(ticket)

    # -- device-fault seams (ops/fault_injection.py) --------------------
    def drain(self, ticket: ResolveTicket) -> list:
        if not ticket.done:
            from ..ops.fault_injection import g_device_faults
            g_device_faults.check("drain", self.BACKEND)
        return super().drain(ticket)

    def drain_with_attribution(self, ticket: ResolveTicket):
        if not ticket.done:
            from ..ops.fault_injection import g_device_faults
            g_device_faults.check("drain", self.BACKEND)
        return super().drain_with_attribution(ticket)

    def _resolve_flags(self, txns, commit_version, new_oldest_version,
                       attribute: bool = False):
        """Dispatch one batch; returns (device conflict flags, too_old,
        n, device per-read-slot cause flags — None unless `attribute` —
        read slot -> (txn, range index) map).

        Kept separate from `resolve` so callers that can overlap host and
        device work (the proxy pipeline / bench) can defer the readback.
        The per-range encoding is delegated to `_marshal_ranges` so the
        point backend can share everything else. `attribute` selects the
        kernel variant compiled WITH the attribution pass — a static
        property of the compiled program, not a runtime switch.
        """
        if commit_version < self._last_commit:
            raise ValueError("commit versions must be non-decreasing "
                             "(ref: Resolver version ordering, "
                             "Resolver.actor.cpp:104-115)")
        n = len(txns)
        if n == 0:
            self._last_commit = commit_version
            self._oldest = max(self._oldest, new_oldest_version)
            return None, None, 0, None, []
        live_snaps = [tr.read_snapshot for tr in txns
                      if len(tr.read_ranges) and tr.read_snapshot >= self._oldest]
        offsets = self._prepare_versions(
            commit_version, new_oldest_version,
            min([max(self._oldest, new_oldest_version)] + live_snaps))

        too_old = np.zeros(n, bool)
        snapshots = np.zeros(n, np.int64)
        for t, tr in enumerate(txns):
            snapshots[t] = tr.read_snapshot
            if tr.read_snapshot < self._oldest and len(tr.read_ranges):
                too_old[t] = True

        arrays, read_map = self._marshal_ranges(txns, too_old,
                                                attribute=attribute)
        conflict, read_hit = self._dispatch(
            n, snapshots, too_old, *arrays, offsets, attribute=attribute)
        self._last_commit = commit_version  # only after a successful batch
        self._oldest = max(self._oldest, new_oldest_version)
        return conflict, too_old, n, read_hit, read_map

    def validate_txns(self, txns, oldest_version=None):
        """Raises exactly when `_resolve_flags` would: a tooOld
        transaction contributes no ranges, empty ranges are skipped,
        and both ends of every surviving range must fit the key bucket
        (the exact conditions `_marshal_ranges` feeds `encode_keys`)."""
        oldest = self._oldest if oldest_version is None else oldest_version
        for tr in txns:
            if tr.read_snapshot < oldest and len(tr.read_ranges):
                continue
            for b, e in (*tr.read_ranges, *tr.write_ranges):
                if b < e:
                    self._validate_range(b, e)

    def _validate_range(self, b: bytes, e: bytes) -> None:
        for k in (b, e):
            if len(k) > self._key_bytes:
                raise ValueError(
                    f"key length {len(k)} exceeds backend key width "
                    f"{self._key_bytes}")

    def input_contract(self):
        # the bound validate_txns would pin this instance's history
        # arrays for as long as the holder lives (the failover wrapper
        # outlives every faulted device backend): hand out a view
        # carrying ONLY the key-bucket width
        view = object.__new__(type(self))
        view._key_bytes = self._key_bytes
        return view.validate_txns

    def _marshal_ranges(self, txns, too_old, attribute: bool = False):
        """Flatten the batch's conflict ranges in txn order — bulk host
        marshalling, not per-range bookkeeping.

        Returns ((rb, re, rt, wb, we, wt), read_map): rb/re/wb/we are
        flat LISTS of raw key bytes (encoded exactly once, straight
        into the packed staging buffer, by `_dispatch`), rt/wt are
        int32 txn-id arrays built by one np.repeat over per-txn counts
        (the non-decreasing layout the kernel's segment sums require).
        `read_map` — built only when `attribute` asks for it, the
        verdict-only hot path skips the bookkeeping entirely — is a
        (txn-ids, ORIGINAL read_ranges indices) array pair attribution
        routes per-slot hits back through. tooOld txns contribute no
        ranges at all (ref: SkipList.cpp:979 addTransaction)."""
        n = len(txns)
        r_counts = np.zeros(n, np.int32)
        w_counts = np.zeros(n, np.int32)
        rb: list = []
        re_: list = []
        wb: list = []
        we: list = []
        r_src: list = []
        for t, tr in enumerate(txns):
            if too_old[t]:
                continue
            rr = tr.read_ranges
            if rr:
                kept = [p for p in rr if p[0] < p[1]]
                r_counts[t] = len(kept)
                rb += [p[0] for p in kept]
                re_ += [p[1] for p in kept]
                if attribute:
                    if len(kept) == len(rr):
                        r_src += range(len(rr))
                    else:
                        r_src += [i for i, p in enumerate(rr)
                                  if p[0] < p[1]]
            ww = tr.write_ranges
            if ww:
                kept = [p for p in ww if p[0] < p[1]]
                w_counts[t] = len(kept)
                wb += [p[0] for p in kept]
                we += [p[1] for p in kept]
        ids = np.arange(n, dtype=np.int32)
        rt = np.repeat(ids, r_counts)
        wt = np.repeat(ids, w_counts)
        read_map = ((rt, np.asarray(r_src, np.int32)) if attribute else ())
        return (rb, re_, rt, wb, we, wt), read_map

    def resolve_arrays(self, snapshots: np.ndarray, has_reads: np.ndarray,
                       rb: np.ndarray, re: np.ndarray, rt: np.ndarray,
                       wb: np.ndarray, we: np.ndarray, wt: np.ndarray,
                       commit_version: int, new_oldest_version: int):
        """Pre-encoded fast path: keys already packed via ops.keys.encode_keys,
        ranges flattened with per-range txn ids. Skips Python marshalling so
        benchmarks/pipelines measure device throughput, and defers the
        verdict readback (returns the device conflict flags + host too_old).
        Ranges of tooOld txns may be included — their writes are excluded by
        the kernel and their reads only affect their own (overridden) flag.

        CONTRACT: `rt` and `wt` must be NON-DECREASING (ranges flattened
        in transaction order — the layout every marshaller produces).
        The kernel's per-txn reductions are segment sums over that slot
        order; out-of-order ids would yield silently wrong verdicts, so
        the cheap host-side monotonicity check below rejects them
        (ADVICE r5: the scatter-max formulation tolerated any order,
        the segment-sum rewrite does not)."""
        if commit_version < self._last_commit:
            raise ValueError("commit versions must be non-decreasing")
        for name, ids in (("rt", rt), ("wt", wt)):
            # signed view: np.diff on a uint array wraps modulo, which
            # would wave decreasing ids straight through this check
            ids = np.asarray(ids, dtype=np.int64)
            if ids.size > 1 and not np.all(np.diff(ids) >= 0):
                raise ValueError(
                    f"per-range txn ids ({name}) must be non-decreasing: "
                    "flatten conflict ranges in transaction order (the "
                    "kernel reduces per-txn flags as segment sums over "
                    "the slot order)")
        too_old = (snapshots < self._oldest) & has_reads.astype(bool)
        live = has_reads.astype(bool) & ~too_old
        floor = min(int(snapshots[live].min()) if live.any() else commit_version,
                    max(self._oldest, new_oldest_version))
        offsets = self._prepare_versions(commit_version, new_oldest_version,
                                         floor)
        conflict, _read_hit = self._dispatch(
            snapshots.shape[0], snapshots, too_old, rb, re,
            np.asarray(rt, np.int32), wb, we, np.asarray(wt, np.int32),
            offsets)
        self._last_commit = commit_version  # only after a successful batch
        self._oldest = max(self._oldest, new_oldest_version)
        return conflict, too_old

    @staticmethod
    def finalize_verdicts(conflict, too_old) -> list[int]:
        n = too_old.shape[0]
        conflict = np.asarray(conflict)[:n]
        return [TOO_OLD if too_old[t] else
                (CONFLICT if conflict[t] else COMMITTED) for t in range(n)]

    # -- shared marshalling helpers (used by the point subclass too) ----
    def _pad_keys(self, a: np.ndarray, size: int) -> np.ndarray:
        out = np.zeros((size, self._n_words + 1), np.uint32)
        out[:a.shape[0]] = a
        return out

    @staticmethod
    def _pad_idx(a: np.ndarray, size: int, fill: int) -> np.ndarray:
        out = np.full((size,), fill, np.int32)
        out[:a.shape[0]] = a
        return out

    def _note_count(self, count, new_rows: int) -> None:
        """Record a batch's device-resident row count and start its
        host copy without blocking; keep roughly one pending copy per
        in-flight pipeline slot so the front of the list is the OLDEST
        submitted batch — the one whose readback rarely stalls, because
        every newer batch is queued behind it."""
        self._count_dev = count
        self._rows_since_async += new_rows
        self._start_host_copy(count)
        self._count_async.append((count, self._rows_since_async))
        limit = max(2, self.pipeline.depth + 1)
        while len(self._count_async) > limit:
            self._consume_oldest_count()

    def _consume_oldest_count(self) -> bool:
        """Fold the OLDEST pending async count into the hint: its value
        plus every row added since it was taken bounds the current
        count from above (rows only leave via GC), so the hint can only
        tighten. Blocks at most until the front of the device queue
        lands — never behind the in-flight window."""
        if not self._count_async:
            return False
        old, rows_after = self._count_async.pop(0)
        stale = int(np.max(np.asarray(old)))
        bound = stale + (self._rows_since_async - rows_after)
        if bound < self._count_hint:
            self._count_hint = bound
        if not self._count_async:
            # the consumed entry WAS the newest count: the hint is now
            # exact, nothing left for a full sync to add
            self._count_dev = None
            self._rows_since_async = 0
        return True

    def _audit_capacity(self, new_rows: int) -> None:
        """Grow the device state if this batch could overflow it.

        `new_rows` = state rows this batch can add (2 boundaries per
        write for the interval backend, 1 per write for points).

        The grow-check consumes pending async counts OLDEST-first:
        each consume stalls one batch at the front of the device queue
        at most, so the in-flight window keeps pipelining. A full
        `_sync_count` drain (previously the dominant streamed stall)
        only remains as the no-pending-copies fallback."""
        while (self._count_hint + new_rows + 2 > self._cap
               and self._consume_oldest_count()):
            pass
        if self._count_hint + new_rows + 2 > self._cap:
            self._sync_count()
        if self._count_hint + new_rows + 2 > self._cap:
            self._grow(self._count_hint + new_rows)
        self._count_hint = min(self._cap - 1, self._count_hint + new_rows)

    def _note_occupancy(self, n, npad, nr, nrp, nw, nwp) -> None:
        """Per-batch pad-shape accounting: real rows vs padded slots per
        dimension. Occupancy = rows/slots over a window; chronically low
        ratios mean the bucket floors are wasting device time, chronic
        recompiles (ops counters) mean they're too tight."""
        p = self.profile
        p.counter("batches").add(1)
        p.counter("txns").add(int(n))
        p.counter("txn_slots").add(int(npad))
        p.counter("reads").add(int(nr))
        p.counter("read_slots").add(int(nrp))
        p.counter("writes").add(int(nw))
        p.counter("write_slots").add(int(nwp))

    def kernel_stats(self) -> dict:
        """This backend INSTANCE's status-ready profile: pad sizes,
        occupancy, backend + platform name, state rows. The jitted
        compile/execute counters are per-process (the lru-cached
        kernels are shared across instances), so they are reported ONCE
        at cluster level by the status assembler — folding them here
        would attribute every instance's compiles to every resolver."""
        import jax
        snap = self.profile.snapshot()
        occ = {}
        for dim in ("txn", "read", "write"):
            rows = snap.get(f"{dim}s", 0)
            slots = snap.get(f"{dim}_slots", 0)
            occ[dim] = round(rows / slots, 4) if slots else None
        batches = snap.get("batches", 0)
        h2d_t = snap.get("h2d_transfers", 0)
        return {"backend": self.BACKEND,
                "platform": jax.default_backend(),
                "capacity": self._cap,
                "state_rows": self._count_hint,
                "batches": batches,
                "occupancy": occ,
                # feed-path transfer accounting: the packed
                # single-buffer discipline shows as per_batch == 1.0
                # (n_shards for the sharded backend); the unpacked
                # fallback as ~12 — counted, not inferred
                "h2d": {"transfers": h2d_t,
                        "bytes": snap.get("h2d_bytes", 0),
                        "per_batch": (round(h2d_t / batches, 2)
                                      if batches else None),
                        "staging_allocs": snap.get("staging_allocs", 0)},
                # raw real-row and padded-slot totals per dimension
                "counts": {k: v for k, v in snap.items()
                           if k != "batches"},
                # split submit/drain window accounting (in-flight
                # depth, forced drains, submit-vs-drain wall latency)
                "pipeline": self.pipeline.stats()}

    def _call_kernel(self, npad, nrp, nwp, args, attribute: bool):
        """Run one padded batch through the single-shard jitted kernel.

        Subclasses (the sharded resolver) override this to dispatch the
        same padded batch across a device mesh."""
        from ..ops.conflict_kernel import make_resolve_fn
        # donate=True: the chained-state entry — the history carry is
        # donated so K in-flight pipeline batches share ONE state
        # allocation instead of holding K copies alive
        fn = make_resolve_fn(self._cap, npad, nrp, nwp, self._n_words,
                             attribute=attribute, donate=True)
        read_hit = None
        if attribute:
            self._hk, self._hv, count, conflict, read_hit = fn(
                self._hk, self._hv, *args)
        else:
            self._hk, self._hv, count, conflict = fn(
                self._hk, self._hv, *args)
        return count, conflict, read_hit

    # -- packed single-buffer feed path ---------------------------------
    def _feed_len(self, npad: int, nrp: int, nwp: int) -> int:
        from ..ops.conflict_kernel import interval_feed_len
        return interval_feed_len(npad, nrp, nwp, self._n_words)

    def _feed_views(self, buf, npad: int, nrp: int, nwp: int):
        from ..ops.conflict_kernel import interval_batch_views
        return interval_batch_views(buf, npad, nrp, nwp, self._n_words)

    def _staging_views(self, npad: int, nrp: int, nwp: int):
        """Reusable packed-feed staging for one shape bucket.

        Buffers ROTATE through a small per-bucket pool (pipeline depth
        + 2 entries): reuse only comes back around after the pipeline
        has force-drained past the batch that last rode the buffer, so
        an in-flight async H2D can never observe the next batch's
        writes. Buffers are deliberately unaligned (_unaliasable_u32)
        so XLA's zero-copy path cannot alias them either. Steady state
        is allocation-flat — `staging_allocs` counts pool entries, not
        batches."""
        key = (npad, nrp, nwp)
        pool = self._staging.get(key)
        if pool is None:
            pool = self._staging[key] = []
        want = max(2, int(self.pipeline.depth) + 2)
        if len(pool) < want:
            buf = _unaliasable_u32(self._feed_len(npad, nrp, nwp))
            ent = (buf, self._feed_views(buf, npad, nrp, nwp))
            pool.append(ent)
            self.profile.counter("staging_allocs").add(1)
            return ent
        i = self._staging_idx.get(key, 0)
        self._staging_idx[key] = (i + 1) % len(pool)
        return pool[i % len(pool)]

    def _fill_keys(self, dst: np.ndarray, src, nsrc: int) -> None:
        """Fill one padded key sub-matrix of the staging buffer: raw
        byte keys encode STRAIGHT into the buffer (one vectorized pass
        over a reused scratch matrix — the encoded keys never exist as
        a separate array); pre-encoded arrays memcpy. Pad rows are
        zeroed for deterministic buffer content (the kernel masks them,
        verdicts never depend on pad rows)."""
        if isinstance(src, np.ndarray):
            dst[:nsrc] = src[:nsrc]
        else:
            sc = self._enc_scratch
            if sc.shape[0] < nsrc or sc.shape[1] != self._key_bytes:
                sc = np.empty((next_pow2(max(nsrc, _KERNEL_MIN_RANGES)),
                               self._key_bytes), np.uint8)
                self._enc_scratch = sc
                self.profile.counter("staging_allocs").add(1)
            encode_keys_into(src, self._key_bytes, dst, sc)
        dst[nsrc:] = 0

    def _feed(self, buf: np.ndarray):
        """ONE host->device transfer carrying the whole packed batch
        (the sharded backend overrides this with per-device async
        puts). The transfer/bytes counters are the measured evidence
        the packed discipline is live — `kernel_stats()["h2d"]`."""
        import jax.numpy as jnp
        p = self.profile
        p.counter("h2d_transfers").add(1)
        p.counter("h2d_bytes").add(int(buf.nbytes))
        return jnp.asarray(buf)

    def _h2d(self, a):
        """Unpacked-fallback transfer accounting: one device array per
        logical input — the multi-transfer feed the packed path
        replaces, kept behind INTERVAL_PACKED_FEED=0 as the bit-exact
        parity baseline and operational rollback."""
        import jax.numpy as jnp
        arr = jnp.asarray(a)
        p = self.profile
        p.counter("h2d_transfers").add(1)
        p.counter("h2d_bytes").add(int(arr.nbytes))
        return arr

    def _call_kernel_packed(self, npad, nrp, nwp, dev_buf, attribute: bool):
        """Run one packed batch through the single-shard jitted kernel
        (the sharded resolver overrides this to dispatch across the
        device mesh)."""
        from ..ops.conflict_kernel import make_resolve_packed_fn
        # donate=True: the chained-state entry — one history allocation
        # across the whole in-flight pipeline window (see _call_kernel)
        fn = make_resolve_packed_fn(self._cap, npad, nrp, nwp,
                                    self._n_words, attribute=attribute,
                                    donate=True)
        read_hit = None
        if attribute:
            self._hk, self._hv, count, conflict, read_hit = fn(
                self._hk, self._hv, dev_buf)
        else:
            self._hk, self._hv, count, conflict = fn(
                self._hk, self._hv, dev_buf)
        return count, conflict, read_hit

    def _dispatch(self, n, snapshots, too_old, rb, re, rt, wb, we, wt,
                  offsets, attribute: bool = False):
        """Pad one batch to its shape bucket, build the packed feed
        buffer IN PLACE over reused staging, and dispatch: every
        marshalled (`resolve`/`submit`) and pre-encoded
        (`resolve_arrays`/`submit_arrays`) batch rides the same single
        host->device transfer. rb/re/wb/we are either flat lists of raw
        key bytes (from `_marshal_ranges` — encoded straight into the
        buffer) or pre-encoded [n, W+1] arrays (memcpy'd)."""
        commit_off, oldest_off, fixup = offsets
        from ..flow.knobs import SERVER_KNOBS
        from ..ops.conflict_kernel import SNAP_CLAMP

        nr, nw = len(rt), len(wt)
        npad = next_pow2(max(n, _KERNEL_MIN_TXNS))
        # exact bucket: one extra slot would double both dimensions
        nrp = next_pow2(max(nr, _KERNEL_MIN_RANGES))
        nwp = next_pow2(max(nw, _KERNEL_MIN_RANGES))
        self._audit_capacity(2 * nw)
        self._note_occupancy(n, npad, nr, nrp, nw, nwp)

        snap_off = np.clip(snapshots - self._base, 0,
                           SNAP_CLAMP).astype(np.int32)
        if int(SERVER_KNOBS.interval_packed_feed):
            buf, v = self._staging_views(npad, nrp, nwp)
            v.hdr[0] = commit_off
            v.hdr[1] = oldest_off
            v.snap[:n] = snap_off
            v.snap[n:] = 0
            v.too_old[:n] = too_old
            v.too_old[n:] = 0
            self._fill_keys(v.rb, rb, nr)
            self._fill_keys(v.re, re, nr)
            v.rtxn[:nr] = rt
            v.rtxn[nr:] = npad
            v.rvalid[:nr] = 1
            v.rvalid[nr:] = 0
            self._fill_keys(v.wb, wb, nw)
            self._fill_keys(v.we, we, nw)
            v.wtxn[:nw] = wt
            v.wtxn[nw:] = npad
            v.wvalid[:nw] = 1
            v.wvalid[nw:] = 0
            count, conflict, read_hit = self._call_kernel_packed(
                npad, nrp, nwp, self._feed(buf), attribute)
        else:
            count, conflict, read_hit = self._dispatch_unpacked(
                n, npad, nrp, nwp, snap_off, too_old, rb, re, rt,
                wb, we, wt, commit_off, oldest_off, attribute)
        self._apply_fixup(fixup)
        self._note_count(count, 2 * nw)
        return conflict, read_hit

    def _dispatch_unpacked(self, n, npad, nrp, nwp, snap_off, too_old,
                           rb, re, rt, wb, we, wt, commit_off, oldest_off,
                           attribute: bool):
        """Legacy multi-transfer feed (INTERVAL_PACKED_FEED=0): ~12
        separate H2D transfers per batch, all counted — the packed
        path's parity baseline (bench.py --dry, tests) and rollback."""
        nr, nw = len(rt), len(wt)
        if not isinstance(rb, np.ndarray):
            keys = encode_keys(list(rb) + list(re) + list(wb) + list(we),
                               self._key_bytes)
            rb, re = keys[:nr], keys[nr:2 * nr]
            wb, we = keys[2 * nr:2 * nr + nw], keys[2 * nr + nw:]
        snap_p = np.zeros(npad, np.int32)
        snap_p[:n] = snap_off
        tooold_p = np.zeros(npad, bool)
        tooold_p[:n] = too_old
        rvalid = np.zeros(nrp, bool)
        rvalid[:nr] = True
        wvalid = np.zeros(nwp, bool)
        wvalid[:nw] = True
        h2d = self._h2d
        return self._call_kernel(npad, nrp, nwp, (
            h2d(snap_p), h2d(tooold_p),
            h2d(self._pad_keys(rb, nrp)),
            h2d(self._pad_keys(re, nrp)),
            h2d(self._pad_idx(rt, nrp, npad)), h2d(rvalid),
            h2d(self._pad_keys(wb, nwp)),
            h2d(self._pad_keys(we, nwp)),
            h2d(self._pad_idx(wt, nwp, npad)), h2d(wvalid),
            h2d(np.int32(commit_off)), h2d(np.int32(oldest_off))),
            attribute)
