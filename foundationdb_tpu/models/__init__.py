"""Conflict-set backends (ref: fdbserver/ConflictSet.h behind a plugin boundary)."""

from .conflict_set import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    BruteForceConflictSet,
    ConflictSetBase,
    PyConflictSet,
    ResolvePipeline,
    ResolveTicket,
    ResolverTransaction,
)
from .native_backend import NativeConflictSet, create_conflict_set, native_available

__all__ = [
    "COMMITTED", "CONFLICT", "TOO_OLD",
    "BruteForceConflictSet", "ConflictSetBase", "PyConflictSet",
    "ResolvePipeline", "ResolveTicket",
    "ResolverTransaction", "NativeConflictSet", "create_conflict_set",
    "native_available",
]
