"""Conflict-set backends (ref: fdbserver/ConflictSet.h behind a plugin boundary)."""

from .conflict_set import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    BruteForceConflictSet,
    ConflictSetBase,
    ConflictSetCheckpoint,
    PyConflictSet,
    ResolvePipeline,
    ResolveTicket,
    ResolverTransaction,
)
from .failover import (
    FailoverConflictSet,
    ShadowResolveMismatch,
    create_resilient_conflict_set,
)
from .native_backend import (
    CONFLICT_BACKENDS,
    NativeConflictSet,
    create_conflict_set,
    native_available,
)

__all__ = [
    "COMMITTED", "CONFLICT", "CONFLICT_BACKENDS", "TOO_OLD",
    "BruteForceConflictSet", "ConflictSetBase", "ConflictSetCheckpoint",
    "FailoverConflictSet", "PyConflictSet",
    "ResolvePipeline", "ResolveTicket",
    "ResolverTransaction", "NativeConflictSet", "ShadowResolveMismatch",
    "create_conflict_set", "create_resilient_conflict_set",
    "native_available",
]
