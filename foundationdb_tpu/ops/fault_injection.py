"""Simulated accelerator faults for the conflict-resolution backends.

Reference: the simulator's machine/disk fault machinery (sim2's
process kills, BUGGIFY'd IO errors) applied to the one component it
could not previously touch — the device backend behind the resolver.
A real TPU can fail mid-pipeline (device lost, preempted, kernel
error) with K batches in flight and unrecoverable on-device state;
`DeviceFaultInjector` raises the simulated analogue at the three
host/device boundaries (`submit` = kernel dispatch, `materialize` =
verdict D2H readback, `drain` = the blocking wait) so the failover
controller (models/failover.py) is exercised deterministically in sim.

Injection is driven by the `DEVICE_FAULT_INJECTION` knob (a per-seam
probability drawn from the seeded sim RNG, so a given seed reproduces
the same fault schedule) amplified by a BUGGIFY site when already
armed; tests can also `schedule()` one-shot faults at exact points.
The knob defaults to 0.0 and is deliberately NOT buggify-distorted:
the seams sit inside backend code that unit tests drive unwrapped,
and a leaked nonzero probability would fault them with no controller
to recover.
"""

from __future__ import annotations

from collections import deque


class DeviceFaultError(RuntimeError):
    """Simulated OR real device-lost / kernel failure. After one of
    these the on-device state (donated history buffers, queued batches)
    must be treated as unrecoverable — exactly how a real
    XlaRuntimeError on a dead device leaves the host wrapper. Real JAX
    runtime errors are re-raised as this type at the seams
    (`convert_device_errors`), so the failover controller handles
    hardware faults and injected ones through one path."""


_runtime_errors: "tuple | None" = None


def runtime_error_types() -> tuple:
    """The JAX/XLA exception types that mean 'the device call failed'
    (device lost, preempted, kernel error, OOM). Resolved lazily and
    defensively: the names move across jax releases."""
    global _runtime_errors
    if _runtime_errors is None:
        types = []
        try:
            from jax.errors import JaxRuntimeError
            types.append(JaxRuntimeError)
        except Exception:  # noqa: BLE001 — older jax
            pass
        try:
            from jaxlib.xla_extension import XlaRuntimeError
            if XlaRuntimeError not in types:
                types.append(XlaRuntimeError)
        except Exception:  # noqa: BLE001
            pass
        _runtime_errors = tuple(types)
    return _runtime_errors


def convert_device_errors(point: str, where: str = ""):
    """Context manager for the device seams: re-raises real JAX runtime
    errors as DeviceFaultError so the failover controller recovers from
    hardware faults exactly like injected ones (a deterministic kernel
    bug then degrades to the CPU fallback after the retry budget — the
    resolver degrades, never dies)."""
    return _DeviceErrorSeam(point, where)


class _DeviceErrorSeam:
    __slots__ = ("point", "where")

    def __init__(self, point: str, where: str):
        self.point = point
        self.where = where

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and not isinstance(exc, DeviceFaultError) \
                and isinstance(exc, runtime_error_types()):
            raise DeviceFaultError(
                f"device error at {self.point} ({self.where}): "
                f"{exc!r}") from exc
        return False


POINTS = ("submit", "materialize", "drain")


class DeviceFaultInjector:
    """Knob-, BUGGIFY- and schedule-driven fault seam.

    `check(point, where)` is called by the device backends at every
    submit/materialize/drain boundary; it raises DeviceFaultError with
    seeded probability DEVICE_FAULT_INJECTION (x10 when the
    `conflict/device_fault_storm` BUGGIFY site fires — storms only
    amplify an injection campaign that is already armed, so the site
    can never destabilize runs with the knob at 0)."""

    def __init__(self):
        self._scheduled: deque = deque()   # points to fault, one-shot
        self.injected: dict = {p: 0 for p in POINTS}
        self.checks = 0

    def schedule(self, point: str) -> None:
        """Force the NEXT check at `point` to fault (tests: exact fault
        placement without probability)."""
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r}")
        self._scheduled.append(point)

    def clear(self) -> None:
        self._scheduled.clear()

    def check(self, point: str, where: str = "") -> None:
        self.checks += 1
        if self._scheduled and self._scheduled[0] == point:
            self._scheduled.popleft()
            self.injected[point] += 1
            raise DeviceFaultError(
                f"scheduled device fault at {point} ({where})")
        from ..flow.knobs import SERVER_KNOBS
        p = float(getattr(SERVER_KNOBS, "device_fault_injection", 0.0))
        if p <= 0.0:
            return
        from ..flow.rng import buggify, g_random
        if buggify("conflict/device_fault_storm"):
            p = min(1.0, p * 10.0)
        if g_random.random01() < p:
            self.injected[point] += 1
            raise DeviceFaultError(
                f"injected device fault at {point} ({where}), "
                f"p={p}")

    def stats(self) -> dict:
        return {"checks": self.checks, "injected": dict(self.injected)}


g_device_faults = DeviceFaultInjector()
