"""Fixed-width key encoding and vectorized ordered search.

FDB keys are variable-length byte strings ordered lexicographically
(fdbclient/FDBTypes.h `KeyRef`; ordering contract used throughout
fdbserver/SkipList.cpp:147-196). TPUs want fixed shapes, so a key is
encoded as W big-endian uint32 words (zero-padded) plus one trailing
length word. Lexicographic comparison of the (W+1)-word vectors equals
lexicographic comparison of the original byte strings:

  - within min(len_a, len_b) bytes, the first differing byte decides and
    big-endian packing preserves that;
  - if one key is a proper prefix of the other, the padded words are
    equal up to the longer key's next nonzero byte (correct), or fully
    equal, in which case the length word breaks the tie (shorter first —
    exactly the prefix rule).

The all-ones vector (length word 0xFFFFFFFF > any real length) is a
+infinity sentinel strictly above every real key; sorted device arrays
are padded with it so searches need no explicit count.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

INF_WORD = np.uint32(0xFFFFFFFF)


def next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


_ENC_SHIFTS = np.array([1 << 24, 1 << 16, 1 << 8, 1], np.uint32)


def encode_keys_into(keys: Sequence[bytes], key_bytes: int,
                     out: np.ndarray, scratch: np.ndarray = None) -> None:
    """encode_keys writing STRAIGHT into a preallocated uint32 view.

    `out` is a [>=n, W+1] uint32 array (typically a reshaped slice of a
    packed feed staging buffer — the marshalled keys then never exist
    as a separate intermediate array); `scratch` is an optional
    reusable [>=n, key_bytes] uint8 byte-staging matrix so a bucketed
    caller pays zero per-batch allocations for the encode itself."""
    n = len(keys)
    n_words = key_bytes // 4
    if scratch is None:
        scratch = np.zeros((max(n, 1), key_bytes), dtype=np.uint8)
    else:
        scratch = scratch[:n]
        scratch[:] = 0
    for i, k in enumerate(keys):
        kl = len(k)
        if kl > key_bytes:
            raise ValueError(
                f"key length {kl} exceeds backend key width {key_bytes}")
        if kl:
            scratch[i, :kl] = np.frombuffer(k, np.uint8)
        out[i, n_words] = kl
    out[:n, :n_words] = (
        scratch[:n].reshape(n, n_words, 4).astype(np.uint32) * _ENC_SHIFTS
    ).sum(axis=2, dtype=np.uint32)


def encode_keys(keys: Sequence[bytes], key_bytes: int) -> np.ndarray:
    """Encode byte-string keys into [n, W+1] uint32 rows (host side)."""
    n = len(keys)
    n_words = key_bytes // 4
    out = np.zeros((max(n, 1), n_words + 1), dtype=np.uint32)
    encode_keys_into(keys, key_bytes, out)
    return out[:n]


def decode_keys(rows: np.ndarray) -> list:
    """Inverse of encode_keys for real rows: [n, W+1] uint32 -> byte
    strings (the trailing length word truncates the zero padding, so
    the round trip is exact for any key within the bucket width). Rows
    must not be +inf sentinels (length word 0xFFFFFFFF)."""
    rows = np.asarray(rows, np.uint32)
    n, width = rows.shape
    n_words = width - 1
    buf = np.empty((n, n_words, 4), np.uint8)
    words = rows[:, :n_words]
    for i, shift in enumerate((24, 16, 8, 0)):
        buf[:, :, i] = (words >> np.uint32(shift)).astype(np.uint8)
    flat = buf.reshape(n, n_words * 4)
    out = []
    for i in range(n):
        kl = int(rows[i, n_words])
        if kl > n_words * 4:
            raise ValueError(f"row {i} is not a real key (length {kl})")
        out.append(flat[i, :kl].tobytes())
    return out


def lt_rows(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a < b over the trailing word axis ([..., W+1]).

    Unrolled fold from the least-significant word up: pure elementwise
    compare/select chains, no gathers — XLA fuses the whole thing."""
    width = a.shape[-1]
    r = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), bool)
    for w in range(width - 1, -1, -1):
        aw, bw = a[..., w], b[..., w]
        r = (aw < bw) | ((aw == bw) & r)
    return r


def le_rows(a: jax.Array, b: jax.Array) -> jax.Array:
    return ~lt_rows(b, a)


def searchsorted_rows(table: jax.Array, queries: jax.Array,
                      side: str = "left") -> jax.Array:
    """Vectorized multiword binary search.

    `table` is [cap, W+1], sorted, cap a power of two, with at least one
    +inf pad row (so every answer is <= cap-1). Returns for each query
    the count of rows < query ("left") or <= query ("right") — the array
    re-expression of the SkipList finger search
    (fdbserver/SkipList.cpp:587-639), branchless so XLA vectorizes the
    whole query batch per step.
    """
    cap = table.shape[0]
    assert cap & (cap - 1) == 0, "table length must be a power of two"
    logn = cap.bit_length() - 1
    cmp = lt_rows if side == "left" else le_rows
    pos0 = jnp.zeros(queries.shape[0], jnp.int32)

    def body(i, pos):
        step = jnp.int32(cap) >> (i + 1)
        probe = jnp.take(table, pos + step - 1, axis=0)
        return pos + step * cmp(probe, queries).astype(jnp.int32)

    return lax.fori_loop(0, logn, body, pos0)


def searchsorted_rows_mixed(table: jax.Array, queries: jax.Array,
                            right_mask: jax.Array) -> jax.Array:
    """searchsorted_rows with a PER-QUERY side: right where right_mask,
    left elsewhere. Lets callers fuse every search against one table
    into a single binary-search loop — the sequential per-level gathers
    dominate search latency on TPU, so batching queries across call
    sites divides that latency by the number of sites merged."""
    cap = table.shape[0]
    assert cap & (cap - 1) == 0, "table length must be a power of two"
    logn = cap.bit_length() - 1
    pos0 = jnp.zeros(queries.shape[0], jnp.int32)

    def body(i, pos):
        step = jnp.int32(cap) >> (i + 1)
        probe = jnp.take(table, pos + step - 1, axis=0)
        lt = lt_rows(probe, queries)          # probe <  q
        le = ~lt_rows(queries, probe)         # probe <= q
        go = jnp.where(right_mask, le, lt)
        return pos + step * go.astype(jnp.int32)

    return lax.fori_loop(0, logn, body, pos0)


def searchsorted_i32(table: jax.Array, queries: jax.Array,
                     side: str = "left") -> jax.Array:
    """Branchless binary search over a sorted int32 array.

    `table` must be sorted ascending with power-of-two length; no
    sentinel row is required (unlike searchsorted_rows) — a final
    correction step makes the full range [0, len] reachable. Returns
    per query the count of elements < query ("left") or <= query
    ("right"). Pure gathers — on TPU this beats any scatter-based
    histogram by an order of magnitude (scatters serialize; see the
    scatter-free notes in ops/point_kernel.py).
    """
    cap = table.shape[0]
    assert cap & (cap - 1) == 0, "table length must be a power of two"
    logn = cap.bit_length() - 1
    pos0 = jnp.zeros(queries.shape, jnp.int32)

    if side == "left":
        def take(probe, q):
            return probe < q
    else:
        def take(probe, q):
            return probe <= q

    def body(i, pos):
        step = jnp.int32(cap) >> (i + 1)
        probe = jnp.take(table, pos + step - 1)
        return pos + step * take(probe, queries).astype(jnp.int32)

    pos = lax.fori_loop(0, logn, body, pos0)
    # the loop narrows to a candidate index in [0, cap-1]; one more
    # compare yields the exact count in [0, cap] (a query above every
    # element would otherwise undercount by one)
    return pos + take(jnp.take(table, pos), queries).astype(jnp.int32)
