"""Sparse-table range-max over version arrays.

The reference answers "max commit version over intervals intersecting
[begin, end)" with a per-level maxVersion pyramid inside the SkipList
(fdbserver/SkipList.cpp:311-377 Node levels, :755-837 CheckMax). The
array equivalent: an O(n log n) doubling table built once per batch,
then O(1) per query via two overlapping power-of-two windows — every
query in the batch resolved in one vectorized gather pair.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

VDEAD = -(1 << 30)  # version of padded / dead slots; below any live version


def build_range_max_table(vals: jax.Array) -> jax.Array:
    """vals: [n] int32, n a power of two. Returns [L, n] with
    table[k, i] = max(vals[i : i + 2**k])."""
    n = vals.shape[0]
    levels = [vals]
    k = 1
    while (1 << k) <= n:
        prev = levels[-1]
        half = 1 << (k - 1)
        shifted = jnp.concatenate(
            [prev[half:], jnp.full((half,), VDEAD, prev.dtype)])
        levels.append(jnp.maximum(prev, shifted))
        k += 1
    return jnp.stack(levels)


def range_max(table: jax.Array, lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Max over [lo, hi) per query; empty ranges give VDEAD."""
    n = table.shape[1]
    length = hi - lo
    safe_len = jnp.maximum(length, 1)
    k = 31 - lax.clz(safe_len)
    flat = table.reshape(-1)
    a = jnp.take(flat, k * n + lo)
    b = jnp.take(flat, k * n + hi - (jnp.int32(1) << k))
    return jnp.where(length > 0, jnp.maximum(a, b), jnp.int32(VDEAD))
