"""Block two-level range-max over version arrays.

The reference answers "max commit version over intervals intersecting
[begin, end)" with a per-level maxVersion pyramid inside the SkipList
(fdbserver/SkipList.cpp:311-377 Node levels, :755-837 CheckMax). The
TPU-friendly equivalent: split the array into 128-lane blocks, keep
per-block prefix/suffix cumulative maxima (vectorized cummax, no
gathers), and a doubling sparse table only over the ~n/128 block maxima.
A query [lo, hi) is then:
    suffix-max of lo's block  |  block-table max over interior blocks  |
    prefix-max of (hi-1)'s block
with the same-block case handled by a masked gather of one block row.
Build is O(n) elementwise + O(n/128 * log) — versus O(n log n) gathers
for a flat sparse table, which lowers terribly on TPU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

VDEAD = -(1 << 30)  # version of padded / dead slots; below any live version
BLOCK = 128


class RangeMaxTable(NamedTuple):
    pre: jax.Array     # [n] prefix max within each block
    suf: jax.Array     # [n] suffix max within each block
    rows: jax.Array    # [n/BLOCK, BLOCK] raw values, one row per block
    btab: jax.Array    # [L, n/BLOCK] sparse table over block maxima


def build_range_max_table(vals: jax.Array) -> RangeMaxTable:
    """vals: [n] int32, n a power of two >= BLOCK."""
    n = vals.shape[0]
    assert n % BLOCK == 0
    rows = vals.reshape(n // BLOCK, BLOCK)
    pre = lax.cummax(rows, axis=1).reshape(n)
    suf = lax.cummax(rows, axis=1, reverse=True).reshape(n)
    bmax = jnp.max(rows, axis=1)
    nb = bmax.shape[0]
    levels = [bmax]
    k = 1
    while (1 << k) <= nb:
        prev = levels[-1]
        half = 1 << (k - 1)
        shifted = jnp.concatenate(
            [prev[half:], jnp.full((half,), VDEAD, prev.dtype)])
        levels.append(jnp.maximum(prev, shifted))
        k += 1
    return RangeMaxTable(pre, suf, rows, jnp.stack(levels))


def _block_range_max(btab: jax.Array, lo_b: jax.Array, hi_b: jax.Array):
    """Max over block indices [lo_b, hi_b); empty -> VDEAD."""
    nb = btab.shape[1]
    length = hi_b - lo_b
    safe = jnp.maximum(length, 1)
    k = 31 - lax.clz(safe)
    flat = btab.reshape(-1)
    a = jnp.take(flat, k * nb + lo_b)
    b = jnp.take(flat, k * nb + hi_b - (jnp.int32(1) << k))
    return jnp.where(length > 0, jnp.maximum(a, b), jnp.int32(VDEAD))


def range_max(table: RangeMaxTable, lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Max over [lo, hi) per query; empty ranges give VDEAD."""
    last = hi - 1  # inclusive end; guarded by the empty-range where below
    lo_b, lo_l = lo // BLOCK, lo % BLOCK
    hi_b = last // BLOCK
    same = lo_b == hi_b
    # cross-block: suffix of lo's block, interior blocks, prefix to `last`
    cross = jnp.maximum(
        jnp.maximum(jnp.take(table.suf, lo), jnp.take(table.pre, last)),
        _block_range_max(table.btab, lo_b + 1, hi_b))
    # same-block: masked max over one gathered block row
    row = jnp.take(table.rows, lo_b, axis=0)  # [q, BLOCK]
    lanes = jnp.arange(BLOCK, dtype=jnp.int32)
    mask = (lanes[None, :] >= lo_l[:, None]) & \
           (lanes[None, :] <= (last % BLOCK)[:, None])
    within = jnp.max(jnp.where(mask, row, jnp.int32(VDEAD)), axis=1)
    return jnp.where(hi > lo, jnp.where(same, within, cross), jnp.int32(VDEAD))
