"""Point-op MVCC conflict resolution: the TPU fast path.

FDB's commit hot path is dominated by point reads/writes — conflict
ranges of the form [k, k+'\\x00') (single keys). The reference resolves
them through the same SkipList interval machinery as general ranges
(fdbserver/SkipList.cpp:979 addTransaction explodes them into point
boundaries); on TPU the interval kernel's strength (range algebra) is
wasted on points while its costs (big merges, range coverage) remain.

This module is a second, shape-compatible resolve core specialized to
batches whose conflict ranges are all points. Semantics are identical
to the general kernel (and to the reference ConflictBatch) restricted
to point ranges — the host wrapper (models/point_resolver.py) proves it
by replaying randomized point workloads bit-exactly against the CPU
baselines, exactly like the interval backend.

Design, driven by measured TPU cost model (see the scatter-free notes
in conflict_kernel.py; on this part scatters and large scalar gathers
run ~100-300M elem/s while multi-column `lax.sort` sustains orders of
magnitude more):

  state      sorted rows (key words, len, version) — the "latest write
             version per key" map, the point restriction of the
             reference's skiplist step function. Duplicate keys are
             allowed (newest last, the only row ext ever reads);
             rows older than oldestVersion are pruned lazily at the
             next merge sort (ref removeBefore, SkipList.cpp:665).

  ext check  one vectorized binary search of the read keys (query
             count = reads, small) + exact-match compare + version
             vs snapshot (ref CheckMax, SkipList.cpp:755-837).

  intra      batch endpoints sorted by (key, txn, read<write); within
             each equal-key run a segmented prefix-OR of "alive write
             before me" answers every read at once; the same
             antitone-fixpoint iteration as the general kernel
             resolves write-dependency chains (ref MiniConflictSet,
             SkipList.cpp:1028-1161). Per-round routing between
             key-sorted and flat order is a 2-column sort (cheap)
             instead of a scatter.

  merge+GC   ONE 4-key-column sort of [masked state; surviving writes]
             — pre-sort masking (+inf keys) handles both GC pruning
             and conflicted-write exclusion, the version column as the
             last sort key makes the newest duplicate sort last, and
             the slice back to `cap` drops only +inf tails. No
             scatters, no compaction pass.

All versions are int32 offsets from the host-tracked base, identical
to the interval kernel's contract.
"""

from __future__ import annotations

import functools
from collections import namedtuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..flow.stats import CounterCollection
from .conflict_kernel import SNAP_CLAMP, profile_kernel
from .keys import searchsorted_i32, searchsorted_rows

VMASK = SNAP_CLAMP + 1  # version column for masked rows (sorts, never read)
INF = 0xFFFFFFFF

# point-kernel compile/execute accounting, separate from the interval
# family so the fast path's recompiles are visible on their own
g_kernel_counters = CounterCollection("point_kernel")


def _seg_or_scan(vals, seg_start):
    """Inclusive segmented prefix-OR: resets at seg_start rows."""
    def op(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av | bv), af | bf
    out, _ = lax.associative_scan(op, (vals, seg_start))
    return out


def make_point_resolve_core(cap: int, n_txns: int, n_reads: int,
                            n_writes: int, n_words: int,
                            attribute: bool = True):
    """Build the point-mode resolve step for one static shape bucket.

    Shapes: `cap` state rows, `n_txns` txn slots, `n_reads`/`n_writes`
    flat point slots (powers of two). Keys are [*, n_words+1] uint32
    rows (ops.keys.encode_keys layout: big-endian words + length word).
    Returns
      fn(sk, sv, snap, too_old, rk, rtxn, rvalid, wk, wtxn, wvalid,
         commit, oldest, init_off)
        -> (sk', sv', count, conflict[n_txns], read_hit[n_reads])
    `read_hit` is the point restriction of the interval kernel's
    conflict attribution (see conflict_kernel.make_resolve_core): slot
    i conflicted against the state map, against the whole-keyspace
    init baseline, or against a surviving earlier write in the batch.
    `attribute=False` compiles without the attribution pass and
    returns a 4-tuple (jitted outputs are never DCE'd, so verdict-only
    hot paths opt out statically).
    `rtxn`/`wtxn` must be non-decreasing with pad slots = n_txns.
    `count` is the total real-row count BEFORE the slice to cap — the
    host overflow audit compares it against cap. `init_off` is the
    whole-keyspace baseline version (offset): any txn with a valid
    read and snapshot below it conflicts (the point map cannot store
    the "everything written at init_version" interval the general
    backends keep as history row 0).
    """
    assert all(x & (x - 1) == 0 for x in (cap, n_txns, n_reads, n_writes))
    width = n_words + 1
    nb = n_reads + n_writes

    def step(sk, sv, snap, too_old, rk, rtxn, rvalid,
             wk, wtxn, wvalid, commit, oldest, init_off):
        n = n_txns
        inf_row = jnp.full((width,), INF, jnp.uint32)
        r_starts = searchsorted_i32(rtxn, jnp.arange(n + 2, dtype=jnp.int32))
        snap_pad = jnp.concatenate(
            [snap, jnp.full((1,), SNAP_CLAMP, jnp.int32)])

        # ---- 1. external check: point lookup in the state map -----------
        pos = jnp.maximum(searchsorted_rows(sk, rk, side="right") - 1, 0)
        hit_k = jnp.take(sk, pos, axis=0)
        hit_v = jnp.take(sv, pos)
        match = jnp.all(hit_k == rk, axis=1)
        ext_r = rvalid & match & (hit_v > jnp.take(snap_pad, rtxn))

        def seg_count(flags):
            cum = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                   jnp.cumsum(flags.astype(jnp.int32))])
            at = jnp.take(cum, r_starts)
            return at[1:] - at[:-1]

        has_read = seg_count(rvalid)[:n] > 0
        ext = (seg_count(ext_r)[:n] > 0) | (has_read & (snap < init_off))

        # ---- 2. intra-batch fixpoint over (key, txn)-sorted rows --------
        bk = jnp.concatenate([rk, wk], axis=0)
        bvalid = jnp.concatenate([rvalid, wvalid])
        btxn = jnp.concatenate([rtxn, wtxn])
        is_w_slot = (jnp.arange(nb, dtype=jnp.int32) >=
                     n_reads).astype(jnp.int32)
        tie = jnp.where(bvalid, (btxn << 1) | is_w_slot,
                        jnp.int32(0x7FFFFFFF))
        bk = jnp.where(bvalid[:, None], bk, inf_row[None, :])
        meta = jnp.arange(nb, dtype=jnp.int32)
        ops = lax.sort(tuple(bk[:, w] for w in range(width)) + (tie, meta),
                       num_keys=width + 1)
        sk_cols = ops[:width]
        tie_s, meta_s = ops[width], ops[width + 1]
        valid_s = tie_s != jnp.int32(0x7FFFFFFF)
        txn_s = jnp.where(valid_s, tie_s >> 1, jnp.int32(n))
        isw_s = valid_s & ((tie_s & 1) == 1)
        isr_s = valid_s & ((tie_s & 1) == 0)
        prev_ne = jnp.zeros((nb,), bool)
        for w in range(width):
            col = sk_cols[w]
            prev_ne = prev_ne | jnp.concatenate(
                [jnp.ones((1,), bool), col[1:] != col[:-1]])
        seg_start = prev_ne

        base_c = jnp.concatenate([ext | too_old, jnp.ones((1,), bool)])
        nhot = jnp.arange(n + 1) == n

        def s_map(c):
            alive = isw_s & ~jnp.take(c, txn_s)
            # alive-write-strictly-before-me within my key run
            shifted = jnp.concatenate([jnp.zeros((1,), bool), alive[:-1]])
            shifted = shifted & ~seg_start
            pref = _seg_or_scan(shifted, seg_start)
            hit_row = isr_s & pref
            # route back to flat order via a 2-column sort (meta is a
            # permutation of arange, so the sorted payload IS flat order)
            _, hit_flat = lax.sort((meta_s, hit_row.astype(jnp.int32)),
                                   num_keys=1)
            hit = seg_count(hit_flat[:n_reads] > 0) > 0
            return base_c | hit | nhot

        def cond(carry):
            prev, cur, i = carry
            return jnp.any(prev != cur) & (i < n + 2)

        def body(carry):
            _, cur, i = carry
            return cur, s_map(cur), i + 1

        first = s_map(base_c)
        _, conflict_pad, _ = lax.while_loop(
            cond, body, (base_c, first, jnp.int32(1)))
        conflict = conflict_pad[:n]

        read_hit = None
        if attribute:
            # per-read attribution at the settled fixpoint (the
            # interval kernel's read_hit, restricted to points): re-run
            # the alive-write-before-me scan once against the final
            # verdicts and route the hits back to flat read order
            alive_f = isw_s & ~jnp.take(conflict_pad, txn_s)
            shifted_f = jnp.concatenate(
                [jnp.zeros((1,), bool), alive_f[:-1]])
            shifted_f = shifted_f & ~seg_start
            pref_f = _seg_or_scan(shifted_f, seg_start)
            hit_row_f = isr_s & pref_f
            _, hit_flat_f = lax.sort(
                (meta_s, hit_row_f.astype(jnp.int32)), num_keys=1)
            init_r = rvalid & (jnp.take(snap_pad, rtxn) < init_off)
            read_hit = ext_r | init_r | (hit_flat_f[:n_reads] > 0)

        # ---- 3. merge + GC: one sort, pre-masked ------------------------
        surv = wvalid & ~jnp.take(conflict_pad, wtxn)
        live = sv >= jnp.maximum(oldest, jnp.int32(0))
        live = live & (sk[:, -1] != jnp.uint32(INF))
        mk = jnp.where(live[:, None], sk, inf_row[None, :])
        mv = jnp.where(live, sv, jnp.int32(VMASK))
        ik = jnp.where(surv[:, None], wk, inf_row[None, :])
        iv = jnp.where(surv, commit, jnp.int32(VMASK))
        allk = jnp.concatenate([mk, ik], axis=0)
        allv = jnp.concatenate([mv, iv])
        sorted_ops = lax.sort(
            tuple(allk[:, w] for w in range(width)) + (allv,),
            num_keys=width + 1)
        out_k = jnp.stack(sorted_ops[:width], axis=1)[:cap]
        out_v = sorted_ops[width][:cap]
        count = (jnp.sum(live.astype(jnp.int32)) +
                 jnp.sum(surv.astype(jnp.int32)))
        if not attribute:
            return out_k, out_v, count, conflict
        return out_k, out_v, count, conflict, read_hit

    return step


@functools.lru_cache(maxsize=None)
def make_point_resolve_fn(cap: int, n_txns: int, n_reads: int,
                          n_writes: int, n_words: int,
                          attribute: bool = True, donate: bool = False):
    """Jitted point-mode resolve step (see make_point_resolve_core).
    `donate` donates the (sk, sv) state carry — the chained-state entry
    the resolve pipeline uses so in-flight batches share one state
    allocation; leave False when reusing inputs after the call."""
    core = make_point_resolve_core(cap, n_txns, n_reads, n_writes, n_words,
                                   attribute=attribute)
    fn = (jax.jit(core, donate_argnums=(0, 1)) if donate
          else jax.jit(core))
    tag = ("" if attribute else "/noattr") + ("/don" if donate else "")
    fn = profile_kernel(
        fn, f"point[{cap}c/{n_txns}t/{n_reads}r/{n_writes}w{tag}]",
        g_kernel_counters)
    from .conflict_kernel import _fault_seamed
    return _fault_seamed(fn, f"point[{cap}c]")


# Packed single-buffer feed layout (the point sibling of
# conflict_kernel.pack_interval_batch): the three version scalars ride
# the buffer head, so one batch is exactly ONE host->device transfer.
PointBatchViews = namedtuple(
    "PointBatchViews", "hdr snap too_old rk rtxn rvalid wk wtxn wvalid")


def point_feed_len(n_txns: int, n_reads: int, n_writes: int,
                   n_words: int) -> int:
    """Total uint32 words of one packed point feed buffer."""
    width = n_words + 1
    return 3 + 2 * n_txns + (n_reads + n_writes) * (width + 2)


def point_batch_views(buf: np.ndarray, n_txns: int, n_reads: int,
                      n_writes: int, n_words: int) -> PointBatchViews:
    """Named numpy views over one packed point feed buffer; `hdr` is
    [commit_off, oldest_off, init_off] as int32. The views alias `buf`
    so marshallers build the batch in place (see
    conflict_kernel.interval_batch_views)."""
    width = n_words + 1
    o = [3]

    def take(n):
        part = buf[o[0]:o[0] + n]
        o[0] += n
        return part

    v = PointBatchViews(
        hdr=buf[0:3].view(np.int32),
        snap=take(n_txns).view(np.int32),
        too_old=take(n_txns),
        rk=take(n_reads * width).reshape(n_reads, width),
        rtxn=take(n_reads).view(np.int32),
        rvalid=take(n_reads),
        wk=take(n_writes * width).reshape(n_writes, width),
        wtxn=take(n_writes).view(np.int32),
        wvalid=take(n_writes))
    assert o[0] == buf.shape[0], (o[0], buf.shape)
    return v


def pack_point_batch(snap, too_old, rk, rtxn, rvalid, wk, wtxn, wvalid,
                     commit_off: int = 0, oldest_off: int = 0,
                     init_off: int = 0):
    """Pack one batch's host arrays into a single contiguous uint32
    buffer for make_point_resolve_packed_fn. One host->device transfer
    per batch instead of eleven: on a remote-attached accelerator the
    per-transfer latency dominates the streamed resolve path, and the
    unpack on device is free (fused slices/bitcasts)."""
    npad = snap.shape[0]
    nrp, width = rk.shape
    nwp = wk.shape[0]
    buf = np.empty(point_feed_len(npad, nrp, nwp, width - 1), np.uint32)
    v = point_batch_views(buf, npad, nrp, nwp, width - 1)
    v.hdr[0] = commit_off
    v.hdr[1] = oldest_off
    v.hdr[2] = init_off
    v.snap[:] = np.asarray(snap, np.int32)
    v.too_old[:] = np.asarray(too_old, np.uint32)
    v.rk[:] = rk
    v.rtxn[:] = np.asarray(rtxn, np.int32)
    v.rvalid[:] = np.asarray(rvalid, np.uint32)
    v.wk[:] = wk
    v.wtxn[:] = np.asarray(wtxn, np.int32)
    v.wvalid[:] = np.asarray(wvalid, np.uint32)
    return buf


@functools.lru_cache(maxsize=None)
def make_point_resolve_packed_fn(cap: int, n_txns: int, n_reads: int,
                                 n_writes: int, n_words: int,
                                 attribute: bool = True,
                                 donate: bool = False):
    """Jitted point resolve taking the pack_point_batch buffer; the
    unpack happens inside the jit so the eleven logical inputs never
    exist as separate device buffers. `donate` donates the (sk, sv)
    state carry (see make_point_resolve_fn)."""
    core = make_point_resolve_core(cap, n_txns, n_reads, n_writes, n_words,
                                   attribute=attribute)
    width = n_words + 1

    def packed(sk, sv, buf):
        o = 3

        def take(n):
            nonlocal o
            part = buf[o:o + n]
            o += n
            return part

        commit = lax.bitcast_convert_type(buf[0], jnp.int32)
        oldest = lax.bitcast_convert_type(buf[1], jnp.int32)
        init_off = lax.bitcast_convert_type(buf[2], jnp.int32)
        snap = lax.bitcast_convert_type(take(n_txns), jnp.int32)
        too_old = take(n_txns) != 0
        rk = take(n_reads * width).reshape(n_reads, width)
        rtxn = lax.bitcast_convert_type(take(n_reads), jnp.int32)
        rvalid = take(n_reads) != 0
        wk = take(n_writes * width).reshape(n_writes, width)
        wtxn = lax.bitcast_convert_type(take(n_writes), jnp.int32)
        wvalid = take(n_writes) != 0
        return core(sk, sv, snap, too_old, rk, rtxn, rvalid,
                    wk, wtxn, wvalid, commit, oldest, init_off)

    fn = (jax.jit(packed, donate_argnums=(0, 1)) if donate
          else jax.jit(packed))
    tag = ("" if attribute else "/noattr") + ("/don" if donate else "")
    fn = profile_kernel(
        fn,
        f"point_packed[{cap}c/{n_txns}t/{n_reads}r/{n_writes}w{tag}]",
        g_kernel_counters)
    from .conflict_kernel import _fault_seamed
    return _fault_seamed(fn, f"point_packed[{cap}c]")
