"""Device-side primitive ops (JAX/XLA) shared by the TPU backends.

These are the TPU-first building blocks: fixed-width multiword key
arithmetic, branchless vectorized binary search, and sparse-table
range-max — the array re-expression of the reference's SkipList
traversals (fdbserver/SkipList.cpp:524-639).
"""

from .keys import (
    INF_WORD,
    encode_keys,
    le_rows,
    lt_rows,
    next_pow2,
    searchsorted_rows,
)
from .rmq import build_range_max_table, range_max

__all__ = [
    "INF_WORD", "encode_keys", "le_rows", "lt_rows", "next_pow2",
    "searchsorted_rows", "build_range_max_table", "range_max",
]
