"""The vectorized MVCC conflict-resolution step (the north-star kernel).

Re-expresses one `ConflictBatch::detectConflicts` round
(fdbserver/SkipList.cpp:1163) as a single jitted array program:

  history state   sorted boundary keys HK[cap, W+1] (uint32 words,
                  +inf padded) + HV[cap] int32 version offsets — the
                  step function over the keyspace that the reference's
                  skiplist encodes via per-node maxVersion
                  (fdbserver/SkipList.cpp:311-377).

  1. external check (ref CheckMax sweeps, SkipList.cpp:524-553,:789-828):
     per read range [b,e): intervals intersecting it are
     [upper_bound(b)-1, lower_bound(e)); conflict iff range-max of HV
     over that span exceeds the txn's read snapshot. All reads at once:
     two vectorized binary searches + O(1) sparse-table range-max each.

  2. intra-batch check (ref MiniConflictSet, SkipList.cpp:1028-1161):
     the reference walks txns sequentially, skipping conflicted txns'
     writes. That recurrence
         c[t] = ext[t] or (exists t' < t: not c[t'] and
                           writes(t') overlap reads(t))
     is computed here without any sequential scan: endpoint keys are
     ranked by one batch sort, the read x write overlap matrix is built
     with integer compares, and the antitone map
         S(c)[t] = ext[t] or any(ov[t', t] and not c[t'])
     is iterated from c0 = ext to its unique fixpoint (unique because
     c[t] depends only on c[<t]; iteration k settles every txn whose
     write-dependency depth is <= k, so it terminates exactly — in
     practice a handful of fully-parallel rounds).

  3. history merge (ref addConflictRanges/mergeWriteConflictRanges,
     SkipList.cpp:511-522,:1260-1318): surviving writes' endpoints are
     merged into the boundary array by a searchsorted stable merge
     (position = own index + cross-rank; no full re-sort), coverage is
     applied as a +-1 delta cumsum, and commit-version assignment is a
     masked maximum (commit versions are monotone, so assign == max).

  4. window GC + compaction (ref removeBefore, SkipList.cpp:665):
     duplicate boundaries and equal-version / dead-dead neighbors are
     dropped by a keep-mask + cumsum scatter. Intervals whose version
     is below oldestVersion can never beat a live snapshot, so merging
     them is verdict-invariant.

Everything is int32/uint32 (versions are offsets from a host-tracked
base, re-based long before overflow): no float, no atomics, fixed
reduction orders — deterministic on TPU by construction, so the
simulator can replay identical verdicts vs the CPU baselines
(the plugin contract, fdbrpc/LoadPlugin.h:29-44 analogue).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .keys import next_pow2, searchsorted_rows, searchsorted_rows_mixed
from .rmq import VDEAD, build_range_max_table, range_max

SNAP_CLAMP = (1 << 30) + 1  # above any storable version offset
REBASE_THRESHOLD = 1 << 30


def make_resolve_core(cap: int, n_txns: int, n_reads: int, n_writes: int,
                      n_words: int, axis_name=None):
    """Build the (unjitted) resolve step for one static shape bucket.

    Shapes: cap history slots, n_txns txn slots, n_reads / n_writes flat
    conflict-range slots (each a power of two). Returns
      fn(HK, HV, snap, too_old, rb, re, rtxn, rvalid,
         wb, we, wtxn, wvalid, commit, oldest)
        -> (HK', HV', count, conflict[n_txns] bool)

    With `axis_name` set, the step runs as one key-range shard of a
    multi-device resolver (ref: key-range sharded resolvers,
    MasterProxyServer.actor.cpp keyResolvers / ResolutionRequestBuilder):
    the external-conflict verdicts and each intra-batch fixpoint round
    are combined across shards with a psum over the mesh axis. Unlike
    the reference — where each resolver runs its intra-batch check on
    local knowledge only and may record writes of transactions another
    resolver aborted (conservative false conflicts) — the ICI collective
    makes every round globally consistent, so the sharded resolver is
    bit-identical to the single-shard one.
    """
    assert all(x & (x - 1) == 0 for x in (cap, n_txns, n_reads, n_writes))
    # batch-rank table: the union {rb, wb, we} order-embeds every compare
    # the overlap test needs (re is EXCLUDED — see the proof at its use)
    mb = next_pow2(n_reads + 2 * n_writes + 1)
    width = n_words + 1
    # overlap-matrix bit-packing: 32 write slots per uint32 lane — the
    # fixpoint rounds then move 32x fewer bytes than a bool matrix
    pack_w = min(32, n_writes)
    n_lanes = n_writes // pack_w

    def _all_shards(flags):
        if axis_name is None:
            return flags
        return lax.psum(flags.astype(jnp.int32), axis_name) > 0

    def step(hk, hv, snap, too_old, rb, re, rtxn, rvalid,
             wb, we, wtxn, wvalid, commit, oldest):
        n = n_txns
        inf_row = jnp.full((width,), 0xFFFFFFFF, jnp.uint32)

        # ---- 1. external check against history --------------------------
        # one fused binary search for both bounds (per-query side)
        ext_q = jnp.concatenate([rb, re], axis=0)
        ext_side = jnp.concatenate([
            jnp.ones((rb.shape[0],), bool), jnp.zeros((re.shape[0],), bool)])
        ext_pos = searchsorted_rows_mixed(hk, ext_q, ext_side)
        lo = ext_pos[:rb.shape[0]] - 1
        hi = ext_pos[rb.shape[0]:]
        vmax = range_max(build_range_max_table(hv), lo, hi)
        snap_pad = jnp.concatenate([snap, jnp.full((1,), SNAP_CLAMP, jnp.int32)])
        ext_r = rvalid & (vmax > snap_pad[rtxn])
        ext = (jnp.zeros(n + 1, jnp.int32).at[rtxn].max(ext_r.astype(jnp.int32))
               [:n] > 0)
        ext = _all_shards(ext)

        # ---- 2. intra-batch fixpoint ------------------------------------
        # Rank space: searchsorted(A, x, left) is an order embedding that
        # is STRICT between x and y exactly when A has an element in
        # [min(x,y), max(x,y)). The overlap test needs only
        #   w_lo < r_hi  (<=> wb < re: wb itself is in A)
        #   r_lo < w_hi  (<=> rb < we: rb itself is in A)
        # so A = {rb, wb, we} suffices — re ranks against A but need not
        # be in it, cutting the sort input by n_reads rows.
        endpoints = jnp.concatenate([rb, wb, we], axis=0)
        ep_valid = jnp.concatenate([rvalid, wvalid, wvalid])
        endpoints = jnp.where(ep_valid[:, None], endpoints, inf_row[None, :])
        pad = jnp.broadcast_to(inf_row, (mb - endpoints.shape[0], width))
        cols = tuple(jnp.concatenate([endpoints, pad], axis=0)[:, w]
                     for w in range(width))
        ranked = jnp.stack(lax.sort(cols, num_keys=width), axis=1)

        rank_q = jnp.concatenate([rb, re, wb, we], axis=0)
        rank_pos = searchsorted_rows(ranked, rank_q)  # all side=left
        r_lo = rank_pos[:n_reads]
        r_hi = rank_pos[n_reads:2 * n_reads]
        w_lo = rank_pos[2 * n_reads:2 * n_reads + n_writes]
        w_hi = rank_pos[2 * n_reads + n_writes:]
        ov = ((w_lo[None, :] < r_hi[:, None]) & (r_lo[:, None] < w_hi[None, :])
              & rvalid[:, None] & wvalid[None, :]
              & (wtxn[None, :] < rtxn[:, None]))  # [n_reads, n_writes]
        # pack write columns into uint32 lanes: the compare->shift->sum
        # chain fuses, so the full bool matrix never hits HBM and each
        # fixpoint round streams n_writes/32 words per read row
        bits = jnp.left_shift(jnp.uint32(1),
                              jnp.arange(pack_w, dtype=jnp.uint32))
        ovp = jnp.sum(ov.reshape(n_reads, n_lanes, pack_w)
                      .astype(jnp.uint32) * bits[None, None, :],
                      axis=2, dtype=jnp.uint32)       # [n_reads, n_lanes]

        base_c = jnp.concatenate([ext | too_old, jnp.ones((1,), bool)])

        def s_map(c):
            alive_w = ~jnp.take(c, wtxn)
            alive_p = jnp.sum(alive_w.reshape(n_lanes, pack_w)
                              .astype(jnp.uint32) * bits[None, :],
                              axis=1, dtype=jnp.uint32)
            hit_r = jnp.any((ovp & alive_p[None, :]) != 0, axis=1)
            hit = (jnp.zeros(n + 1, jnp.int32)
                   .at[rtxn].max(hit_r.astype(jnp.int32)) > 0)
            hit = _all_shards(hit)
            return (base_c | hit).at[n].set(True)

        def cond(carry):
            prev, cur, i = carry
            return jnp.any(prev != cur) & (i < n + 2)

        def body(carry):
            _, cur, i = carry
            return cur, s_map(cur), i + 1

        first = s_map(base_c)
        _, conflict_pad, _ = lax.while_loop(
            cond, body, (base_c, first, jnp.int32(1)))
        conflict = conflict_pad[:n]

        # ---- 3. merge surviving writes into the history -----------------
        surv = wvalid & ~jnp.take(conflict_pad, wtxn)
        ins = jnp.concatenate([wb, we], axis=0)
        ins_valid = jnp.concatenate([surv, surv])
        ins = jnp.where(ins_valid[:, None], ins, inf_row[None, :])
        # one pre-sort search serves both the covering version AND the
        # merge rank: both are pure functions of the key value, so they
        # ride the sort as carried columns (equal keys carry equal
        # values — any permutation among ties is safe)
        ins_pos = searchsorted_rows(hk, ins, side="right")
        cover = jnp.take(hv, ins_pos - 1)
        cover = jnp.where(ins_valid, cover, jnp.int32(VDEAD))
        sorted_ops = lax.sort(
            tuple(ins[:, w] for w in range(width)) + (cover, ins_pos),
            num_keys=width)
        ins_sorted = jnp.stack(sorted_ops[:width], axis=1)
        ins_cover = sorted_ops[width]

        # Stable two-way merge positions. The small side (2*n_writes ins
        # rows) binary-searches the big side; the big side's shifts are
        # recovered from a scatter+cumsum of those positions — O(cap)
        # elementwise instead of cap binary searches.
        mi = ins_sorted.shape[0]
        ins_live = ins_sorted[:, -1] != jnp.uint32(0xFFFFFFFF)
        ins_ub = sorted_ops[width + 1]                       # hist<=ins
        u = jnp.where(ins_live, ins_ub, jnp.int32(cap))
        shifts = jnp.cumsum(jnp.zeros(cap, jnp.int32).at[u].add(
            1, mode="drop", indices_are_sorted=True))
        pos_h = jnp.arange(cap, dtype=jnp.int32) + shifts
        pos_i = jnp.arange(mi, dtype=jnp.int32) + ins_ub
        sorted_unique = dict(mode="drop", unique_indices=True,
                             indices_are_sorted=True)
        merged_k = jnp.broadcast_to(inf_row, (cap, width))
        merged_k = merged_k.at[pos_h].set(hk, **sorted_unique)
        merged_k = merged_k.at[pos_i].set(ins_sorted, **sorted_unique)
        merged_v = jnp.full((cap,), VDEAD, jnp.int32)
        merged_v = merged_v.at[pos_h].set(hv, **sorted_unique)
        merged_v = merged_v.at[pos_i].set(ins_cover, **sorted_unique)

        # coverage: +1 at each surviving write begin, -1 at its end
        o_pos = searchsorted_rows(
            merged_k, jnp.concatenate([wb, we], axis=0), side="left")
        o_lo = o_pos[:n_writes]
        o_hi = o_pos[n_writes:]
        s32 = surv.astype(jnp.int32)
        delta = (jnp.zeros(cap + 1, jnp.int32)
                 .at[o_lo].add(s32).at[o_hi].add(-s32))
        covered = jnp.cumsum(delta)[:cap] > 0
        merged_v = jnp.where(covered, jnp.maximum(merged_v, commit), merged_v)

        # ---- 4. GC window + dedup/compaction ----------------------------
        oldest2 = jnp.maximum(oldest, jnp.int32(0))
        nxt_eq = jnp.concatenate([
            jnp.all(merged_k[:-1] == merged_k[1:], axis=1),
            jnp.zeros((1,), bool)])
        keep1 = ~nxt_eq  # keep last of each duplicate-key run
        dead = merged_v < oldest2
        prev_keep = jnp.concatenate([jnp.zeros((1,), bool), keep1[:-1]])
        prev_v = jnp.concatenate([jnp.full((1,), VDEAD, jnp.int32),
                                  merged_v[:-1]])
        prev_dead = jnp.concatenate([jnp.ones((1,), bool), dead[:-1]])
        redundant = prev_keep & ((merged_v == prev_v) | (dead & prev_dead))
        redundant = redundant.at[0].set(False)
        keep = keep1 & ~redundant
        is_real = ~jnp.all(merged_k == inf_row[None, :], axis=1)
        # Stable-partition targets: kept rows pack left in order, dropped
        # rows (overwritten with +inf/dead values) fill the tail — every
        # target unique, so XLA lowers the scatter without collision
        # handling.
        csum = jnp.cumsum(keep.astype(jnp.int32))
        nkeep = csum[cap - 1]
        iota = jnp.arange(cap, dtype=jnp.int32)
        tgt = jnp.where(keep, csum - 1, nkeep + iota - csum)
        val_k = jnp.where(keep[:, None], merged_k, inf_row[None, :])
        val_v = jnp.where(keep, merged_v, jnp.int32(VDEAD))
        out_k = jnp.broadcast_to(inf_row, (cap, width))
        out_k = out_k.at[tgt].set(val_k, mode="drop", unique_indices=True)
        out_v = jnp.full((cap,), VDEAD, jnp.int32)
        out_v = out_v.at[tgt].set(val_v, mode="drop", unique_indices=True)
        count = jnp.sum((keep & is_real).astype(jnp.int32))
        return out_k, out_v, count, conflict

    return step


@functools.lru_cache(maxsize=None)
def make_resolve_fn(cap: int, n_txns: int, n_reads: int, n_writes: int,
                    n_words: int):
    """Jitted single-shard resolve step (see make_resolve_core)."""
    return jax.jit(make_resolve_core(cap, n_txns, n_reads, n_writes, n_words))


@functools.lru_cache(maxsize=None)
def make_rebase_fn():
    """Shift stored version offsets down by delta (overflow-safe clamp)."""
    def rebase(hv, delta):
        return jnp.maximum(hv, jnp.int32(VDEAD) + delta) - delta
    return jax.jit(rebase)


@functools.lru_cache(maxsize=None)
def make_reset_fn():
    """Rebase for deltas too large for int32 arithmetic (> 2^31-1): every
    stored version is below the new base, hence dead — clamp them all."""
    def reset(hv):
        return jnp.full_like(hv, jnp.int32(VDEAD))
    return jax.jit(reset)


@functools.lru_cache(maxsize=None)
def make_jump_fixup_fn():
    """Post-merge fixup for recovery-style version jumps: entries written
    at the placeholder offset become the true commit offset under the new
    base; everything older shifts (and saturates at VDEAD — it is all
    below the post-jump oldestVersion, so exact values no longer matter)."""
    def fixup(hv, placeholder, commit_off, delta):
        shifted = jnp.maximum(hv, jnp.int32(VDEAD) + delta) - delta
        return jnp.where(hv == placeholder, commit_off, shifted)
    return jax.jit(fixup)


@functools.lru_cache(maxsize=None)
def make_jump_fixup_large_fn():
    """Jump fixup when the base shift exceeds int32: placeholder entries
    get the commit offset, everything else is dead."""
    def fixup(hv, placeholder, commit_off):
        return jnp.where(hv == placeholder, commit_off,
                         jnp.int32(VDEAD))
    return jax.jit(fixup)
