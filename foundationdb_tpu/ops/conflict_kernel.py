"""The vectorized MVCC conflict-resolution step (the north-star kernel).

Re-expresses one `ConflictBatch::detectConflicts` round
(fdbserver/SkipList.cpp:1163) as a single jitted array program:

  history state   sorted boundary keys HK[cap, W+1] (uint32 words,
                  +inf padded) + HV[cap] int32 version offsets — the
                  step function over the keyspace that the reference's
                  skiplist encodes via per-node maxVersion
                  (fdbserver/SkipList.cpp:311-377).

  1. external check (ref CheckMax sweeps, SkipList.cpp:524-553,:789-828):
     per read range [b,e): intervals intersecting it are
     [upper_bound(b)-1, lower_bound(e)); conflict iff range-max of HV
     over that span exceeds the txn's read snapshot. All reads at once:
     two vectorized binary searches + O(1) sparse-table range-max each.

  2. intra-batch check (ref MiniConflictSet, SkipList.cpp:1028-1161):
     the reference walks txns sequentially, skipping conflicted txns'
     writes. That recurrence
         c[t] = ext[t] or (exists t' < t: not c[t'] and
                           writes(t') overlap reads(t))
     is computed here without any sequential scan: endpoint keys are
     ranked by one batch sort, the read x write overlap matrix is built
     with integer compares, and the antitone map
         S(c)[t] = ext[t] or any(ov[t', t] and not c[t'])
     is iterated from c0 = ext to its unique fixpoint (unique because
     c[t] depends only on c[<t]; iteration k settles every txn whose
     write-dependency depth is <= k, so it terminates exactly — in
     practice a handful of fully-parallel rounds).

  3. history merge (ref addConflictRanges/mergeWriteConflictRanges,
     SkipList.cpp:511-522,:1260-1318): ONE multi-column sort merges
     history rows and surviving boundary rows; the covering version,
     the +-1 coverage counter, and commit-version assignment are
     segmented scans over the sorted order (commit versions are
     monotone, so assign == max).

  4. window GC + compaction (ref removeBefore, SkipList.cpp:665):
     duplicate boundaries and equal-version / dead-dead neighbors are
     masked to +inf and one more key sort packs the survivors left.
     Intervals whose version is below oldestVersion can never beat a
     live snapshot, so merging them is verdict-invariant.

  TPU cost model (measured on v5e through this kernel's rewrites):
  multi-column `lax.sort` sustains ~200M rows/s; binary searches
  (logn dependent gather rounds) and scatters run 10-50x slower, so
  every rank/merge/route-back is expressed as a sort + scans, and
  per-txn reductions ride the REQUIRED non-decreasing rtxn/wtxn slot
  order as cumsum differences.

Everything is int32/uint32 (versions are offsets from a host-tracked
base, re-based long before overflow): no float, no atomics, fixed
reduction orders — deterministic on TPU by construction, so the
simulator can replay identical verdicts vs the CPU baselines
(the plugin contract, fdbrpc/LoadPlugin.h:29-44 analogue).
"""

from __future__ import annotations

import functools
import time
from collections import namedtuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..flow.stats import CounterCollection
from .keys import searchsorted_i32
from .rmq import VDEAD, build_range_max_table, range_max

SNAP_CLAMP = (1 << 30) + 1  # above any storable version offset
REBASE_THRESHOLD = 1 << 30

# Per-process kernel profile (ref: the reference's --knob_profiling
# GetHistogram metrics around the conflict batch): every jitted resolve
# family accounts compiles, compile time, and sampled execute time here;
# the resolver role folds the snapshot into status/trace rollups.
g_kernel_counters = CounterCollection("conflict_kernel")


def profile_kernel(fn, kernel: str,
                   counters: CounterCollection = g_kernel_counters):
    """Wrap a jitted kernel with compile/execute accounting.

    The FIRST call per wrapped function (one shape bucket each, thanks
    to the lru_caches below) is always fenced with block_until_ready —
    that delta is dominated by XLA compilation, the single most
    important number when an interval/streamed ratio regresses
    (recompiles show up as `compiles` climbing past the bucket count).
    Afterward only 1-in-KERNEL_PROFILE_EVERY dispatches are fenced, so
    the async dispatch pipeline the streamed bench depends on stays
    intact; 0 disables the periodic fence entirely."""
    from ..flow.knobs import SERVER_KNOBS
    state = {"compiled": False, "calls": 0}
    # counter objects and name strings are invariant per wrapped
    # kernel: bind them once so the unfenced hot path (the streamed
    # pipeline with KERNEL_PROFILE_EVERY=0) pays one increment and one
    # knob read per dispatch, not f-string builds and dict lookups
    calls_c = counters.counter(f"{kernel}.calls")

    def call(*args):
        state["calls"] += 1
        first = not state["compiled"]
        if not first:
            every = int(SERVER_KNOBS.kernel_profile_every)
            if not every or state["calls"] % every:
                calls_c.add(1)
                return fn(*args)
        # drain already-queued async device work first (the inputs are
        # the producer chain): the fenced delta must time THIS dispatch,
        # not the pipeline backlog behind it
        jax.block_until_ready(args)
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        calls_c.add(1)
        if first:
            state["compiled"] = True
            counters.counter(f"{kernel}.compiles").add(1)
            counters.counter(f"{kernel}.compile_us").add(int(dt * 1e6))
            from ..flow.trace import SevDebug, TraceEvent
            TraceEvent("KernelCompile", kernel,
                       severity=SevDebug).detail(
                Backend=jax.default_backend(),
                Seconds=round(dt, 6)).log()
        else:
            counters.counter(f"{kernel}.timed_calls").add(1)
            counters.counter(f"{kernel}.execute_us").add(int(dt * 1e6))
        return out

    return call


def make_resolve_core(cap: int, n_txns: int, n_reads: int, n_writes: int,
                      n_words: int, axis_name=None, attribute: bool = True):
    """Build the (unjitted) resolve step for one static shape bucket.

    Shapes: cap history slots, n_txns txn slots, n_reads / n_writes flat
    conflict-range slots (each a power of two). Returns
      fn(HK, HV, snap, too_old, rb, re, rtxn, rvalid,
         wb, we, wtxn, wvalid, commit, oldest)
        -> (HK', HV', count, conflict[n_txns] bool, read_hit[n_reads] bool)
    `read_hit[i]` marks read slot i as a CAUSE of its transaction's
    conflict (ref: report_conflicting_keys, fdbclient/NativeAPI — the
    conflicting key ranges surfaced to the client): it conflicted
    against the history (external check) or, at the final intra-batch
    fixpoint, overlaps a surviving write of an earlier transaction.
    The union of both is evaluated for EVERY transaction — including
    externally-conflicted ones — so attribution is order-insensitive
    and bit-comparable across the CPU baselines and device backends.

    `attribute=False` compiles WITHOUT the attribution pass (a 4-tuple,
    no read_hit): outputs of a jitted function are never dead-code
    eliminated, so verdict-only callers — the bench hot paths — must
    opt out statically rather than discard the extra output.
    `rtxn`/`wtxn` must be NON-DECREASING with pad slots = n_txns (the
    flattened-in-txn-order layout every marshaller produces): per-txn
    reductions are segment sums over that order.

    With `axis_name` set, the step runs as one key-range shard of a
    multi-device resolver (ref: key-range sharded resolvers,
    MasterProxyServer.actor.cpp keyResolvers / ResolutionRequestBuilder):
    the external-conflict verdicts and each intra-batch fixpoint round
    are combined across shards with a psum over the mesh axis. Unlike
    the reference — where each resolver runs its intra-batch check on
    local knowledge only and may record writes of transactions another
    resolver aborted (conservative false conflicts) — the ICI collective
    makes every round globally consistent, so the sharded resolver is
    bit-identical to the single-shard one.
    """
    assert all(x & (x - 1) == 0 for x in (cap, n_txns, n_reads, n_writes))
    width = n_words + 1
    # overlap-matrix bit-packing: 32 write slots per uint32 lane — the
    # fixpoint rounds then move 32x fewer bytes than a bool matrix
    pack_w = min(32, n_writes)
    n_lanes = n_writes // pack_w

    def _all_shards(flags):
        if axis_name is None:
            return flags
        return lax.psum(flags.astype(jnp.int32), axis_name) > 0

    def step(hk, hv, snap, too_old, rb, re, rtxn, rvalid,
             wb, we, wtxn, wvalid, commit, oldest):
        n = n_txns
        inf_row = jnp.full((width,), 0xFFFFFFFF, jnp.uint32)

        # ---- 1. external check against history --------------------------
        # Rank the read bounds against the history by SORT-MERGE, not
        # binary search: measured on v5e, a multi-column lax.sort of
        # cap+queries rows costs ~5ms while logn sequential gather
        # rounds of searchsorted cost ~22ms (the dependent-gather chain
        # is latency-bound). Tie order encodes the side: re (left)
        # sorts before equal history rows, rb (right) after. (A single
        # mega-sort folding the merge's boundary rows in here was
        # measured SLOWER: the wider payload outweighs the saved sort.)
        nq = rb.shape[0] + re.shape[0]
        tie_e = jnp.concatenate([
            jnp.full((cap,), 1, jnp.int32),
            jnp.full((rb.shape[0],), 2, jnp.int32),
            jnp.zeros((re.shape[0],), jnp.int32)])
        qid_e = jnp.concatenate([
            jnp.full((cap,), nq, jnp.int32),
            jnp.arange(nq, dtype=jnp.int32)])
        rows_e = jnp.concatenate([hk, rb, re], axis=0)
        sorted_e = lax.sort(
            tuple(rows_e[:, w] for w in range(width)) + (tie_e, qid_e),
            num_keys=width + 1)
        is_q = sorted_e[width] != 1
        cq = jnp.cumsum(is_q.astype(jnp.int32))
        # for a query at sorted index i: #history rows before it
        ranks_e = jnp.arange(cap + nq, dtype=jnp.int32) - cq + 1
        # route ranks back to query order by a 2-column sort (qids are
        # unique; history rows carry qid=nq and sort to the tail) — a
        # scatter here runs ~50x slower than the sort on TPU
        pos_q = lax.sort((sorted_e[width + 1], ranks_e), num_keys=1)[1]
        lo = pos_q[:rb.shape[0]] - 1
        hi = pos_q[rb.shape[0]:nq]
        vmax = range_max(build_range_max_table(hv), lo, hi)
        snap_pad = jnp.concatenate([snap, jnp.full((1,), SNAP_CLAMP, jnp.int32)])
        ext_r = rvalid & (vmax > snap_pad[rtxn])

        # per-txn reductions ride rtxn's non-decreasing slot order as
        # cumsum differences at the txn boundaries — the scatter-max
        # formulation was the fixpoint's dominant cost (measured ~6ms
        # per round for a 32k-slot scatter vs sub-ms for the cumsum)
        r_starts = searchsorted_i32(rtxn, jnp.arange(n + 2,
                                                     dtype=jnp.int32))

        def seg_any(flags):
            cum = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                   jnp.cumsum(flags.astype(jnp.int32))])
            at = jnp.take(cum, r_starts)
            return (at[1:] - at[:-1])[:n] > 0

        ext = _all_shards(seg_any(ext_r))

        # ---- 2. intra-batch fixpoint ------------------------------------
        # Rank space: searchsorted(A, x, left) is an order embedding that
        # is STRICT between x and y exactly when A has an element in
        # [min(x,y), max(x,y)). The overlap test needs only
        #   w_lo < r_hi  (<=> wb < re: wb itself is in A)
        #   r_lo < w_hi  (<=> rb < we: rb itself is in A)
        # so A = {rb, wb, we} suffices — re ranks against A but need not
        # be in it, cutting the sort input by n_reads rows.
        # One sort ranks all four endpoint groups against A (side=left
        # for everyone): sort {A rows, re queries} together; the rank
        # of EVERY row in an equal-key run is the A-count at the run's
        # first row (#A strictly less), carried forward by a segmented
        # keep-first scan — no searchsorted, no tie bookkeeping.
        endpoints = jnp.concatenate([rb, wb, we], axis=0)
        ep_valid = jnp.concatenate([rvalid, wvalid, wvalid])
        endpoints = jnp.where(ep_valid[:, None], endpoints, inf_row[None, :])
        na = endpoints.shape[0]
        nall = na + re.shape[0]
        rows_r = jnp.concatenate([endpoints, re], axis=0)
        is_a = (jnp.arange(nall, dtype=jnp.int32) < na).astype(jnp.int32)
        qid_r = jnp.concatenate([
            jnp.arange(na, dtype=jnp.int32),
            jnp.arange(re.shape[0], dtype=jnp.int32) + na])
        sorted_r = lax.sort(
            tuple(rows_r[:, w] for w in range(width)) + (is_a, qid_r),
            num_keys=width)
        a_s = sorted_r[width]
        rank_a = jnp.cumsum(a_s) - a_s          # #A rows strictly before i
        prev_ne = jnp.zeros((nall,), bool)
        for w in range(width):
            col = sorted_r[w]
            prev_ne = prev_ne | jnp.concatenate(
                [jnp.ones((1,), bool), col[1:] != col[:-1]])

        def keep_first(vals, seg_start):
            def op(a, b):
                av, af = a
                bv, bf = b
                return jnp.where(bf, bv, av), af | bf
            out, _ = lax.associative_scan(op, (vals, seg_start))
            return out

        rank_run = keep_first(rank_a, prev_ne)
        # qids are a permutation of arange: the 2-col sort IS the
        # inverse permutation (scatters are ~50x slower here)
        pos_r = lax.sort((sorted_r[width + 1], rank_run), num_keys=1)[1]
        r_lo = pos_r[:n_reads]
        w_lo = pos_r[n_reads:n_reads + n_writes]
        w_hi = pos_r[n_reads + n_writes:na]
        r_hi = pos_r[na:]
        ov = ((w_lo[None, :] < r_hi[:, None]) & (r_lo[:, None] < w_hi[None, :])
              & rvalid[:, None] & wvalid[None, :]
              & (wtxn[None, :] < rtxn[:, None]))  # [n_reads, n_writes]
        # pack write columns into uint32 lanes: the compare->shift->sum
        # chain fuses, so the full bool matrix never hits HBM and each
        # fixpoint round streams n_writes/32 words per read row
        bits = jnp.left_shift(jnp.uint32(1),
                              jnp.arange(pack_w, dtype=jnp.uint32))
        ovp = jnp.sum(ov.reshape(n_reads, n_lanes, pack_w)
                      .astype(jnp.uint32) * bits[None, None, :],
                      axis=2, dtype=jnp.uint32)       # [n_reads, n_lanes]

        base_c = jnp.concatenate([ext | too_old, jnp.ones((1,), bool)])

        def s_map(c):
            alive_w = ~jnp.take(c, wtxn)
            alive_p = jnp.sum(alive_w.reshape(n_lanes, pack_w)
                              .astype(jnp.uint32) * bits[None, :],
                              axis=1, dtype=jnp.uint32)
            hit_r = jnp.any((ovp & alive_p[None, :]) != 0, axis=1)
            hit = _all_shards(seg_any(hit_r))
            return jnp.concatenate(
                [base_c[:n] | hit, jnp.ones((1,), bool)])

        def cond(carry):
            prev, cur, i = carry
            return jnp.any(prev != cur) & (i < n + 2)

        def body(carry):
            _, cur, i = carry
            return cur, s_map(cur), i + 1

        first = s_map(base_c)
        _, conflict_pad, _ = lax.while_loop(
            cond, body, (base_c, first, jnp.int32(1)))
        conflict = conflict_pad[:n]

        read_hit = None
        if attribute:
            # per-read attribution at the settled fixpoint: a read slot
            # is a conflict CAUSE iff it hit the history (ext_r) or
            # overlaps a write that survived (earlier txn, not
            # conflicted) — one more masked pass over the packed
            # overlap matrix, no extra sorts
            alive_final = ~jnp.take(conflict_pad, wtxn)
            alive_fp = jnp.sum(alive_final.reshape(n_lanes, pack_w)
                               .astype(jnp.uint32) * bits[None, :],
                               axis=1, dtype=jnp.uint32)
            intra_r = jnp.any((ovp & alive_fp[None, :]) != 0, axis=1)
            read_hit = _all_shards(ext_r | intra_r)

        # ---- 3. merge surviving writes into the history -----------------
        # One sort does the whole merge: history rows and the surviving
        # writes' boundary rows ride together; the covering version,
        # the coverage counter, and the dedup logic are scans over the
        # sorted order (no binary searches, no big scatters).
        surv = wvalid & ~jnp.take(conflict_pad, wtxn)
        ins_valid = jnp.concatenate([surv, surv])
        ins = jnp.concatenate([wb, we], axis=0)
        ins = jnp.where(ins_valid[:, None], ins, inf_row[None, :])
        mi = ins.shape[0]
        mtot = cap + mi
        rows_m = jnp.concatenate([hk, ins], axis=0)
        # one combined tie column carries both the merge order and the
        # coverage delta: history rows (1) sort before equal-key ins
        # rows (the covering version of a boundary equal to a history
        # key is that row's version — side=right semantics); among ins
        # rows we (4) vs wb (6) order is irrelevant (coverage is a
        # cumsum at the run's last row either way)
        tie_m = jnp.concatenate([
            jnp.full((cap,), 1, jnp.int32),
            jnp.where(surv, 6, 1), jnp.where(surv, 4, 1)])
        vcol = jnp.concatenate([hv, jnp.full((mi,), VDEAD, jnp.int32)])
        sm = lax.sort(
            tuple(rows_m[:, w] for w in range(width)) + (tie_m, vcol),
            num_keys=width + 1)
        is_ins = sm[width] >= 4
        merged_k = jnp.stack(sm[:width], axis=1)
        mv_raw = sm[width + 1]
        delta_s = jnp.where(is_ins, sm[width] - 5, 0)

        # covering version: last history version at or before each row
        def carry_last(vals, present):
            def op(a, b):
                av, af = a
                bv, bf = b
                return jnp.where(bf, bv, av), af | bf
            out, _ = lax.associative_scan(op, (vals, present))
            return out

        lhv = carry_last(mv_raw, ~is_ins)
        merged_v = jnp.where(is_ins, lhv, mv_raw)

        prev_ne_m = jnp.zeros((mtot,), bool)
        for w in range(width):
            col = sm[w]
            prev_ne_m = prev_ne_m | jnp.concatenate(
                [jnp.ones((1,), bool), col[1:] != col[:-1]])
        run_end = jnp.concatenate([prev_ne_m[1:], jnp.ones((1,), bool)])
        dtot = jnp.cumsum(delta_s)
        # searchsorted(side=left) coverage semantics require the value
        # at each run's LAST row — but a row's coverage (and version)
        # is only ever read where the row survives dedup, and dedup
        # keeps exactly the run-end rows, where the plain inclusive
        # cumsum IS the run-end value. No backward scan needed.
        covered = dtot > 0
        merged_v = jnp.where(covered, jnp.maximum(merged_v, commit),
                             merged_v)

        # ---- 4. GC window + dedup, compacted by one more sort -----------
        oldest2 = jnp.maximum(oldest, jnp.int32(0))
        keep1 = run_end  # keep last of each duplicate-key run
        dead = merged_v < oldest2
        prev_keep = jnp.concatenate([jnp.zeros((1,), bool), keep1[:-1]])
        prev_v = jnp.concatenate([jnp.full((1,), VDEAD, jnp.int32),
                                  merged_v[:-1]])
        prev_dead = jnp.concatenate([jnp.ones((1,), bool), dead[:-1]])
        redundant = prev_keep & ((merged_v == prev_v) | (dead & prev_dead))
        redundant = redundant.at[0].set(False)
        keep = keep1 & ~redundant
        is_real = ~jnp.all(merged_k == inf_row[None, :], axis=1)
        # dropped rows mask to +inf and one final key sort packs the
        # kept rows left; the slice back to cap drops only the masked
        # tail (overflow past cap is caught by the host count audit)
        val_k = jnp.where(keep[:, None], merged_k, inf_row[None, :])
        val_v = jnp.where(keep, merged_v, jnp.int32(VDEAD))
        sc = lax.sort(tuple(val_k[:, w] for w in range(width)) + (val_v,),
                      num_keys=width)
        out_k = jnp.stack(sc[:width], axis=1)[:cap]
        out_v = sc[width][:cap]
        count = jnp.sum((keep & is_real).astype(jnp.int32))
        if not attribute:
            return out_k, out_v, count, conflict
        return out_k, out_v, count, conflict, read_hit

    return step


@functools.lru_cache(maxsize=None)
def make_resolve_fn(cap: int, n_txns: int, n_reads: int, n_writes: int,
                    n_words: int, attribute: bool = True,
                    donate: bool = False):
    """Jitted single-shard resolve step (see make_resolve_core).
    `attribute` is part of the compile cache key: the attributing and
    verdict-only variants are distinct programs.

    `donate` is the chained-state entry point: the history carry
    (HK, HV) is donated back to the kernel, so batch N+1 reuses batch
    N's output buffers in place and capacity doubling — not steady
    state — is the only realloc. The resolve pipeline depends on it
    (K in-flight batches would otherwise hold K history copies alive).
    Callers that reuse the input arrays after the call (direct kernel
    tests) must leave it False."""
    core = make_resolve_core(cap, n_txns, n_reads, n_writes, n_words,
                             attribute=attribute)
    fn = (jax.jit(core, donate_argnums=(0, 1)) if donate
          else jax.jit(core))
    tag = ("" if attribute else "/noattr") + ("/don" if donate else "")
    fn = profile_kernel(
        fn, f"resolve[{cap}c/{n_txns}t/{n_reads}r/{n_writes}w{tag}]")
    return _fault_seamed(fn, f"resolve[{cap}c]")


# ---------------------------------------------------------------------------
# Packed single-buffer feed path (the interval mirror of
# point_kernel.pack_point_batch): every per-batch input — snapshots,
# tooOld flags, read/write boundary keys, per-range txn ids, valid
# masks, AND the commit/oldest version offsets — rides ONE contiguous
# uint32 host buffer, so a batch costs exactly one host->device
# transfer instead of ~12. On a remote-attached accelerator the
# per-transfer latency (not bandwidth) dominates the streamed resolve
# path; the unpack on device is free (fused slices/bitcasts).
#
# Layout (uint32 words; int32 values ride as bit patterns):
#   [0]                commit_off        [1]              oldest_off
#   [2           : 2+T]         snapshots          (int32)
#   [2+T         : 2+2T]        too_old            (0/1)
#   [..          : +R*(W+1)]    read begin rows
#   [..          : +R*(W+1)]    read end rows
#   [..          : +R]          read txn ids       (int32, pad = T)
#   [..          : +R]          read valid         (0/1)
#   [..          : +Wr*(W+1)]   write begin rows
#   [..          : +Wr*(W+1)]   write end rows
#   [..          : +Wr]         write txn ids
#   [..          : +Wr]         write valid
# with T = n_txns slots, R = n_reads slots, Wr = n_writes slots and
# W+1 the encoded key width (ops.keys layout).

IntervalBatchViews = namedtuple(
    "IntervalBatchViews",
    "hdr snap too_old rb re rtxn rvalid wb we wtxn wvalid")


def interval_feed_len(n_txns: int, n_reads: int, n_writes: int,
                      n_words: int) -> int:
    """Total uint32 words of one packed interval feed buffer."""
    width = n_words + 1
    return 2 + 2 * n_txns + (n_reads + n_writes) * (2 * width + 2)


def interval_batch_views(buf: np.ndarray, n_txns: int, n_reads: int,
                         n_writes: int, n_words: int) -> IntervalBatchViews:
    """Named numpy views over one packed feed buffer (see layout above).

    The views alias `buf`, so a marshaller can build the batch IN PLACE
    — keys encoded straight into the rb/re/wb/we sub-matrices — and
    hand the single buffer to the device. int32 fields come back as
    int32 views of the same words."""
    width = n_words + 1
    o = [2]

    def take(n):
        part = buf[o[0]:o[0] + n]
        o[0] += n
        return part

    v = IntervalBatchViews(
        hdr=buf[0:2].view(np.int32),
        snap=take(n_txns).view(np.int32),
        too_old=take(n_txns),
        rb=take(n_reads * width).reshape(n_reads, width),
        re=take(n_reads * width).reshape(n_reads, width),
        rtxn=take(n_reads).view(np.int32),
        rvalid=take(n_reads),
        wb=take(n_writes * width).reshape(n_writes, width),
        we=take(n_writes * width).reshape(n_writes, width),
        wtxn=take(n_writes).view(np.int32),
        wvalid=take(n_writes))
    assert o[0] == buf.shape[0], (o[0], buf.shape)
    return v


def pack_interval_batch(snap, too_old, rb, re, rtxn, rvalid,
                        wb, we, wtxn, wvalid,
                        commit_off: int, oldest_off: int) -> np.ndarray:
    """Pack one padded interval batch into a fresh single-transfer
    buffer for make_resolve_packed_fn (tests / one-shot callers; the
    resolver builds batches in place over reused staging buffers via
    interval_batch_views instead)."""
    npad = snap.shape[0]
    nrp, width = rb.shape
    nwp = wb.shape[0]
    buf = np.empty(interval_feed_len(npad, nrp, nwp, width - 1), np.uint32)
    v = interval_batch_views(buf, npad, nrp, nwp, width - 1)
    v.hdr[0] = commit_off
    v.hdr[1] = oldest_off
    v.snap[:] = np.asarray(snap, np.int32)
    v.too_old[:] = np.asarray(too_old, np.uint32)
    v.rb[:] = rb
    v.re[:] = re
    v.rtxn[:] = np.asarray(rtxn, np.int32)
    v.rvalid[:] = np.asarray(rvalid, np.uint32)
    v.wb[:] = wb
    v.we[:] = we
    v.wtxn[:] = np.asarray(wtxn, np.int32)
    v.wvalid[:] = np.asarray(wvalid, np.uint32)
    return buf


def make_interval_unpack(n_txns: int, n_reads: int, n_writes: int,
                         n_words: int):
    """Device-side unpack of the packed feed buffer: static slices +
    bitcasts that XLA fuses away — the logical arrays never exist as
    separate device buffers. Shared by the single-shard packed entry
    point and the sharded per-shard wrapper."""
    width = n_words + 1

    def unpack(buf):
        o = [2]

        def take(n):
            part = buf[o[0]:o[0] + n]
            o[0] += n
            return part

        commit = lax.bitcast_convert_type(buf[0], jnp.int32)
        oldest = lax.bitcast_convert_type(buf[1], jnp.int32)
        snap = lax.bitcast_convert_type(take(n_txns), jnp.int32)
        too_old = take(n_txns) != 0
        rb = take(n_reads * width).reshape(n_reads, width)
        re = take(n_reads * width).reshape(n_reads, width)
        rtxn = lax.bitcast_convert_type(take(n_reads), jnp.int32)
        rvalid = take(n_reads) != 0
        wb = take(n_writes * width).reshape(n_writes, width)
        we = take(n_writes * width).reshape(n_writes, width)
        wtxn = lax.bitcast_convert_type(take(n_writes), jnp.int32)
        wvalid = take(n_writes) != 0
        return (snap, too_old, rb, re, rtxn, rvalid,
                wb, we, wtxn, wvalid, commit, oldest)

    return unpack


@functools.lru_cache(maxsize=None)
def make_resolve_packed_fn(cap: int, n_txns: int, n_reads: int,
                           n_writes: int, n_words: int,
                           attribute: bool = True, donate: bool = False):
    """Jitted interval resolve taking the packed single-transfer buffer
    (see pack_interval_batch); the unpack happens inside the jit. Same
    contract and outputs as make_resolve_fn — `attribute` stays part of
    the compile cache key, and `donate` donates the (HK, HV) history
    carry exactly like the unpacked chained-state entry point."""
    core = make_resolve_core(cap, n_txns, n_reads, n_writes, n_words,
                             attribute=attribute)
    unpack = make_interval_unpack(n_txns, n_reads, n_writes, n_words)

    def packed(hk, hv, buf):
        return core(hk, hv, *unpack(buf))

    fn = (jax.jit(packed, donate_argnums=(0, 1)) if donate
          else jax.jit(packed))
    tag = ("" if attribute else "/noattr") + ("/don" if donate else "")
    fn = profile_kernel(
        fn,
        f"resolve_packed[{cap}c/{n_txns}t/{n_reads}r/{n_writes}w{tag}]")
    return _fault_seamed(fn, f"resolve_packed[{cap}c]")


def _fault_seamed(fn, where: str):
    """Device-fault seam at kernel dispatch (the `submit` point): an
    injected fault models the device rejecting the dispatch, and a REAL
    JAX runtime error (device lost, kernel failure) is converted to the
    same DeviceFaultError — either way the chained history carry is in
    an unknown state and the failover controller must rebuild
    (models/failover.py)."""
    from .fault_injection import convert_device_errors, g_device_faults

    def call(*args):
        g_device_faults.check("submit", where)
        with convert_device_errors("submit", where):
            return fn(*args)

    return call


@functools.lru_cache(maxsize=None)
def make_rebase_fn():
    """Shift stored version offsets down by delta (overflow-safe clamp)."""
    def rebase(hv, delta):
        return jnp.maximum(hv, jnp.int32(VDEAD) + delta) - delta
    return jax.jit(rebase)


@functools.lru_cache(maxsize=None)
def make_reset_fn():
    """Rebase for deltas too large for int32 arithmetic (> 2^31-1): every
    stored version is below the new base, hence dead — clamp them all."""
    def reset(hv):
        return jnp.full_like(hv, jnp.int32(VDEAD))
    return jax.jit(reset)


@functools.lru_cache(maxsize=None)
def make_jump_fixup_fn():
    """Post-merge fixup for recovery-style version jumps: entries written
    at the placeholder offset become the true commit offset under the new
    base; everything older shifts (and saturates at VDEAD — it is all
    below the post-jump oldestVersion, so exact values no longer matter)."""
    def fixup(hv, placeholder, commit_off, delta):
        shifted = jnp.maximum(hv, jnp.int32(VDEAD) + delta) - delta
        return jnp.where(hv == placeholder, commit_off, shifted)
    return jax.jit(fixup)


@functools.lru_cache(maxsize=None)
def make_jump_fixup_large_fn():
    """Jump fixup when the base shift exceeds int32: placeholder entries
    get the commit offset, everything else is dead."""
    def fixup(hv, placeholder, commit_off):
        return jnp.where(hv == placeholder, commit_off,
                         jnp.int32(VDEAD))
    return jax.jit(fixup)
