"""Cluster-side backup runner: watches the \\xff\\x02/backup/ control
rows and drives the continuous-backup agent against their container.

Reference: the backup_agent processes an operator runs alongside
fdbserver (`fdbbackup agent`, fdbbackup/backup.actor.cpp — agent mode
polling the backup config subspace written by `fdbbackup start`). The
split is the point: the fdbtpu-backup TOOL only ever commits control
rows through the ordinary client surface (so it works identically
in-sim and over TCP), while this driver — a process that lives with
the cluster — notices the rows, runs the BackupAgent lifecycle, and
uploads to the container URL the rows name.

Row protocol (server/systemkeys.py BACKUP_*): `dest` = container URL;
`state` walks submitted -> running -> (abort ->) stopped, or error;
`base_version` / `restorable_version` / `error` are driver-written
status the tool polls.
"""

from __future__ import annotations

from .. import flow
from ..flow import TaskPriority
from ..client import run_transaction
from ..server.systemkeys import (BACKUP_END, BACKUP_PREFIX,
                                 BACKUP_STATE_ABORT, BACKUP_STATE_ERROR,
                                 BACKUP_STATE_RUNNING,
                                 BACKUP_STATE_STOPPED,
                                 BACKUP_STATE_SUBMITTED)
from .backup_agent import BackupAgent
from .backup_container import open_container


async def read_backup_rows(db, max_retries: int = 2000) -> dict:
    """The \\xff\\x02/backup/ control rows, prefix-stripped — the ONE
    reader both the driver and the fdbtpu-backup tool use."""
    async def body(tr):
        tr.set_option("read_system_keys")
        return dict(await tr.get_range(BACKUP_PREFIX, BACKUP_END))
    full = await run_transaction(db, body, max_retries=max_retries)
    return {k[len(BACKUP_PREFIX):]: v for k, v in full.items()}


class BackupDriver:
    """One driver per cluster; at most one backup at a time (the
    reference multiplexes tagged backups — this slice has the default
    tag only)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.db = cluster.client("backup-driver")
        self.agent: BackupAgent = None
        self._container = None
        self._task = None
        self._last_upload = 0.0

    def start(self) -> None:
        self._task = flow.spawn(self._run(), TaskPriority.DEFAULT_ENDPOINT,
                                name="backupDriver")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -- row IO ----------------------------------------------------------
    async def _read_rows(self) -> dict:
        return await read_backup_rows(self.db, max_retries=10000)

    async def _write_rows(self, expect_state=None, **rows) -> None:
        """Commit status rows. With `expect_state`, the write happens
        only if the state row still matches — an operator command
        (abort, resubmit) committed while the driver was mid-transition
        must win, not be clobbered by the driver's stale intention (the
        read rides the same transaction, so the check is atomic). A
        skipped write needs no signal: every caller converges on the
        next poll round by re-reading the rows."""

        async def body(tr):
            tr.set_option("access_system_keys")
            if expect_state is not None:
                cur = await tr.get(BACKUP_PREFIX + b"state")
                if cur != expect_state:
                    return
            for k, v in rows.items():
                tr.set(BACKUP_PREFIX + k.encode(), v)
        await run_transaction(self.db, body, max_retries=10000)

    # -- the state machine ----------------------------------------------
    async def _run(self) -> None:
        while True:
            await flow.delay(
                flow.SERVER_KNOBS.backup_driver_poll_interval,
                TaskPriority.LOW_PRIORITY)
            try:
                rows = await self._read_rows()
            except flow.FdbError:
                continue          # cluster mid-recovery: try again
            state = rows.get(b"state")
            try:
                if state == BACKUP_STATE_SUBMITTED and self.agent is None:
                    await self._begin(rows)
                elif state == BACKUP_STATE_RUNNING and \
                        self.agent is not None:
                    await self._maybe_upload()
                elif state == BACKUP_STATE_RUNNING:
                    # rows say running but nothing is (driver/server
                    # restarted: the tail history died with it) — an
                    # honest error beats a backup frozen in `running`
                    # forever; the operator resubmits (ref: a restarted
                    # reference agent RESUMES from container state —
                    # resumable backups are out of this slice's scope)
                    await self._write_rows(
                        expect_state=BACKUP_STATE_RUNNING,
                        state=BACKUP_STATE_ERROR,
                        error=b"backup driver restarted mid-backup; "
                              b"abort is not needed, resubmit")
                elif state == BACKUP_STATE_ABORT:
                    await self._finish()
            except flow.ActorCancelled:
                raise
            except BaseException as e:  # noqa: BLE001 — surfaced in rows
                # ANY failure — container IO, cluster errors past the
                # transaction retry budget — must tear the agent down
                # (or the backup tag would pin TLog records forever)
                # and surface through the rows, never kill the driver
                flow.TraceEvent("BackupDriverError", "backup-driver",
                                severity=flow.trace.SevWarnAlways).detail(
                    Error=repr(e)).log()
                if self.agent is not None:
                    try:
                        await self.agent.stop()
                    except (flow.FdbError, flow.ActorCancelled):
                        pass
                    self.agent = None
                self._container = None
                try:
                    # compare-and-set against the state this iteration
                    # acted on: an operator command (abort/resubmit)
                    # that committed while we were tearing down wins —
                    # the next poll acts on it instead of finding our
                    # ERROR stamped over it
                    await self._write_rows(expect_state=state,
                                           state=BACKUP_STATE_ERROR,
                                           error=repr(e).encode())
                except flow.FdbError:
                    pass   # cluster unhealthy: rows update next round

    async def _begin(self, rows: dict) -> None:
        dest = rows.get(b"dest", b"").decode()
        self._container = open_container(dest)
        self.agent = BackupAgent(self.cluster, self.db)
        base = await self.agent.start()
        # save_to serializes LIVE agent state (log records the puller
        # actor keeps appending): it must run on the loop, never on a
        # pool thread — a concurrent snapshot could certify a version
        # window while missing a mutation inside it. Its blob retries
        # skip the backoff sleep on the loop (_retry_backoff). Pure
        # container IO (describe) is offloaded via arun (ADVICE r5).
        self.agent.save_to(self._container)
        self._last_upload = flow.now()
        d = await self._container.arun(self._container.describe)
        # start() spans a full epoch recovery — if an abort committed
        # meanwhile, the abort wins: don't stamp `running` over it (the
        # next poll sees `abort` and finishes the agent)
        await self._write_rows(
            expect_state=BACKUP_STATE_SUBMITTED,
            state=BACKUP_STATE_RUNNING,
            base_version=str(base).encode(),
            restorable_version=str(
                d["max_restorable_version"] or base).encode())

    async def _maybe_upload(self) -> None:
        if flow.now() - self._last_upload < \
                flow.SERVER_KNOBS.backup_driver_upload_interval:
            return
        self._last_upload = flow.now()
        self.agent.save_to(self._container)   # live agent state: on-loop
        d = await self._container.arun(self._container.describe)
        if d["max_restorable_version"] is not None:
            await self._write_rows(
                expect_state=BACKUP_STATE_RUNNING,
                restorable_version=str(d["max_restorable_version"]).encode())

    async def _finish(self) -> None:
        if self.agent is not None:
            await self.agent.stop()
            self.agent.save_to(self._container)   # agent stopped; on-loop
            d = await self._container.arun(self._container.describe)
            extra = {}
            if d["max_restorable_version"] is not None:
                extra["restorable_version"] = str(
                    d["max_restorable_version"]).encode()
            # a fresh submit committed while we were stopping the old
            # agent must not be clobbered with `stopped`
            await self._write_rows(expect_state=BACKUP_STATE_ABORT,
                                   state=BACKUP_STATE_STOPPED, **extra)
            self.agent = None
            self._container = None
        else:
            await self._write_rows(expect_state=BACKUP_STATE_ABORT,
                                   state=BACKUP_STATE_STOPPED)
