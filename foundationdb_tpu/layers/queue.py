"""Queue recipes: FIFO queue and priority queue over the tuple layer.

Reference: recipes/python-recipes (Queue / PriorityQueue) — the classic
FDB patterns: items keyed by (priority, sequencer, random tiebreak) so
pops take the head transactionally and concurrent pushers never
conflict with each other.
"""

from __future__ import annotations

from typing import Optional, Tuple

from .. import flow
from .subspace import Subspace


class PriorityQueue:
    """Lower priority value pops first; FIFO within a priority."""

    def __init__(self, subspace: Subspace = None):
        self.ss = subspace if subspace is not None else Subspace(("pq",))

    async def push(self, tr, item: bytes, priority: int = 0) -> None:
        """Keyed (priority, next-index, random): pushers only read a
        snapshot of their priority's tail, so they don't conflict."""
        b, e = self.ss.range((priority,))
        last = await tr.get_range(b, e, limit=1, reverse=True,
                                  snapshot=True)
        idx = self.ss.unpack(last[0][0])[1] + 1 if last else 0
        tie = flow.g_random.random_int(0, 1 << 30)
        tr.set(self.ss.pack((priority, idx, tie)), item)

    async def pop(self, tr) -> Optional[bytes]:
        """Take the head (lowest priority, oldest index); None if
        empty. Pops DO conflict with a racing pop of the same head —
        exactly-once delivery."""
        b, e = self.ss.range()
        head = await tr.get_range(b, e, limit=1)
        if not head:
            return None
        tr.clear(head[0][0])
        return head[0][1]

    async def peek(self, tr) -> Optional[Tuple[int, bytes]]:
        b, e = self.ss.range()
        head = await tr.get_range(b, e, limit=1)
        if not head:
            return None
        return self.ss.unpack(head[0][0])[0], head[0][1]


class Queue(PriorityQueue):
    """Plain FIFO: a PriorityQueue with one priority."""

    def __init__(self, subspace: Subspace = None):
        super().__init__(subspace if subspace is not None
                         else Subspace(("queue",)))

    async def push(self, tr, item: bytes) -> None:  # noqa: D102
        await super().push(tr, item, 0)
