"""Directory layer: a hierarchy of named subspaces with allocated
short prefixes.

Reference: the directory layer shipped with every reference binding
(bindings/python/fdb/directory_impl.py; Subspace/Tuple in fdbclient) —
paths map to compact allocated prefixes via a node tree stored in the
database itself, so layers address data by name without embedding long
paths in every key. Prefix allocation uses a windowed high-contention
allocator (candidates drawn randomly inside a window that advances as
it fills — the HCA pattern) so concurrent creates rarely conflict.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .. import flow
from ..flow import error
from . import tuple_layer
from .subspace import Subspace

_NODE_ROOT = b"\xfe"       # node-tree home (ref: DirectoryLayer defaults)
_SUB_DIRS = 0              # node field: child name -> child node key
_SUB_LAYER = b"layer"


class Directory:
    """A handle to an opened directory: a Subspace plus its path."""

    def __init__(self, layer: "DirectoryLayer", path: Tuple[str, ...],
                 prefix: bytes, layer_tag: bytes):
        self.directory_layer = layer
        self.path = path
        self.subspace = Subspace((), prefix)
        self.layer_tag = layer_tag

    def pack(self, t: Tuple = ()) -> bytes:
        return self.subspace.pack(t)

    def unpack(self, key: bytes) -> Tuple:
        return self.subspace.unpack(key)

    def range(self, t: Tuple = ()) -> Tuple[bytes, bytes]:
        return self.subspace.range(t)


class DirectoryLayer:
    def __init__(self, node_prefix: bytes = _NODE_ROOT,
                 content_prefix: bytes = b""):
        self._nodes = Subspace((), node_prefix)
        self._content_prefix = content_prefix
        self._alloc = _Allocator(self._nodes.subspace(("alloc",)))

    def _node_key(self, path: Tuple[str, ...]) -> bytes:
        return self._nodes.pack(("node",) + path)

    async def create_or_open(self, tr, path, layer: bytes = b"") -> Directory:
        return await self._open(tr, tuple(path), layer, create=True)

    async def open(self, tr, path, layer: bytes = b"") -> Directory:
        return await self._open(tr, tuple(path), layer, create=False)

    async def _open(self, tr, path: Tuple[str, ...], layer: bytes,
                    create: bool) -> Directory:
        if not path:
            raise error("client_invalid_operation")
        # parents must exist (created on demand under create=True)
        for i in range(1, len(path)):
            await self._open(tr, path[:i], b"", create=create)
        raw = await tr.get(self._node_key(path))
        if raw is not None:
            prefix, existing_layer = _decode_node(raw)
            if layer and existing_layer and layer != existing_layer:
                raise error("client_invalid_operation")
            return Directory(self, path, prefix, existing_layer)
        if not create:
            raise error("key_outside_legal_range")  # directory_not_exists
        prefix = self._content_prefix + await self._alloc.allocate(tr)
        tr.set(self._node_key(path), _encode_node(prefix, layer))
        return Directory(self, path, prefix, layer)

    async def exists(self, tr, path) -> bool:
        return await tr.get(self._node_key(tuple(path))) is not None

    async def list(self, tr, path=()) -> List[str]:
        base = ("node",) + tuple(path)
        b, e = self._nodes.range(base)
        out = []
        depth = len(base)
        rows = await tr.get_range(b, e)
        for k, _v in rows:
            t = self._nodes.unpack(k)
            if len(t) == depth + 1:
                out.append(t[-1])
        return out

    async def remove(self, tr, path) -> None:
        """Remove the directory, its children, and its contents."""
        path = tuple(path)
        raw = await tr.get(self._node_key(path))
        if raw is None:
            return
        prefix, _layer = _decode_node(raw)
        # contents
        tr.clear_range(prefix, prefix + b"\xff")
        # node subtree (the node itself + all descendants)
        b, e = self._nodes.range(("node",) + path)
        for k, v in await tr.get_range(b, e):
            child_prefix, _cl = _decode_node(v)
            tr.clear_range(child_prefix, child_prefix + b"\xff")
        tr.clear_range(b, e)
        tr.clear(self._node_key(path))

    async def move(self, tr, old_path, new_path) -> Directory:
        """Re-point a directory node (contents keep their prefix, so a
        move never rewrites data — ref: directory move semantics)."""
        old_path, new_path = tuple(old_path), tuple(new_path)
        raw = await tr.get(self._node_key(old_path))
        if raw is None:
            raise error("key_outside_legal_range")
        if await tr.get(self._node_key(new_path)) is not None:
            raise error("client_invalid_operation")
        for i in range(1, len(new_path)):
            if not await self.exists(tr, new_path[:i]):
                raise error("client_invalid_operation")
        # move the whole node subtree
        b, e = self._nodes.range(("node",) + old_path)
        for k, v in await tr.get_range(b, e):
            sub = self._nodes.unpack(k)[1 + len(old_path):]
            tr.set(self._nodes.pack(("node",) + new_path + sub), v)
        tr.set(self._node_key(new_path), raw)
        tr.clear_range(b, e)
        tr.clear(self._node_key(old_path))
        prefix, layer = _decode_node(raw)
        return Directory(self, new_path, prefix, layer)


def _encode_node(prefix: bytes, layer: bytes) -> bytes:
    return tuple_layer.pack((prefix, layer))


def _decode_node(raw: bytes):
    prefix, layer = tuple_layer.unpack(raw)
    return prefix, layer


class _Allocator:
    """Windowed high-contention prefix allocator (ref: the binding
    directory layer's HCA: counters advance a window; allocators pick
    random candidates inside it so concurrent transactions usually
    claim distinct slots and conflicts stay rare)."""

    WINDOW = 64

    def __init__(self, space: Subspace):
        self._counter = space.pack(("counter",))
        self._claims = space.subspace(("claims",))

    async def allocate(self, tr) -> bytes:
        raw = await tr.get(self._counter, snapshot=True)
        start = int(raw) if raw is not None else 0
        for _ in range(64):
            slot = start + flow.g_random.random_int(0, self.WINDOW)
            claim_key = self._claims.pack((slot,))
            if await tr.get(claim_key, snapshot=True) is None:
                # claiming writes the slot; OCC on the claim key makes
                # two same-slot allocations conflict at commit
                tr.set(claim_key, b"")
                self._bump(tr, start, slot)
                return tuple_layer.pack((slot,))
            start += 1  # window drifts forward as slots fill
        raise error("operation_failed")

    def _bump(self, tr, start: int, slot: int) -> None:
        tr.set(self._counter, b"%d" % max(start, slot + 1))
