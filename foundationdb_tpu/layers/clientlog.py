"""Client-log janitor: retention trimming for the sampled-transaction
profiling keyspace.

Reference: the ClientTransactionProfileCorrectness workload's cleanup
of \\xff\\x02/fdbClientInfo/client_latency/ and the TaskBucket-style
periodic maintenance agents (fdbclient/TaskBucket.actor.cpp): profile
records are ordinary replicated rows, so without a trimmer a 100%
sample rate grows the system keyspace without bound. The janitor is a
cluster-side actor (like the BackupDriver) that periodically deletes
every record older than PROFILE_RETENTION_SECONDS.

Record keys are ordered by start timestamp (server/systemkeys.py), so
the trim is one bounded scan (to COUNT what dies — the analyzer's
`records_trimmed` signal) followed by a single clear_range.
"""

from __future__ import annotations

from .. import flow
from ..flow import TaskPriority
from ..server.systemkeys import (CLIENT_LATENCY_PREFIX,
                                 CLIENT_LATENCY_VERSION,
                                 client_latency_cutoff_key,
                                 parse_client_latency_key)


async def trim_client_log(db, cutoff_ts: float, max_retries: int = 100,
                          scan_limit: int = 10_000) -> int:
    """Delete every profile record that STARTED before `cutoff_ts`
    (sim seconds); returns how many distinct records died. The count
    comes from scanning the doomed prefix (bounded — a pathological
    backlog still trims, it just under-counts), the deletion from one
    clear_range over the same bound."""
    cutoff = client_latency_cutoff_key(int(cutoff_ts * 1e6),
                                       CLIENT_LATENCY_VERSION)

    async def body(tr):
        tr.set_option("access_system_keys")
        rows = await tr.get_range(CLIENT_LATENCY_PREFIX, cutoff,
                                  limit=scan_limit)
        seen = set()
        for k, _v in rows:
            parsed = parse_client_latency_key(k)
            if parsed is not None:
                seen.add((parsed[1], parsed[2]))   # (start_ts, rec_id)
        if rows:
            tr.clear_range(CLIENT_LATENCY_PREFIX, cutoff)
        return len(seen)

    from ..client import profiling
    trimmed = await profiling.run_unsampled(db, body,
                                            max_retries=max_retries)
    if trimmed:
        profiling.note_trimmed(trimmed)
        flow.TraceEvent("ClientLogTrimmed").detail(
            Records=trimmed, CutoffTs=cutoff_ts).log()
    return trimmed


class ClientLogJanitor:
    """One janitor per cluster (ref: the BackupDriver lifecycle): wakes
    every PROFILE_JANITOR_INTERVAL and trims the profiling keyspace to
    the PROFILE_RETENTION_SECONDS window."""

    def __init__(self, cluster, retention: float = None,
                 interval: float = None):
        self.cluster = cluster
        self.db = cluster.client("clientlog-janitor")
        self.retention = retention
        self.interval = interval
        self.records_trimmed = 0
        self.rounds = 0
        self._task = None

    def start(self) -> None:
        self._task = flow.spawn(self._run(), TaskPriority.LOW_PRIORITY,
                                name="clientLogJanitor")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        while True:
            await flow.delay(
                self.interval if self.interval is not None
                else flow.SERVER_KNOBS.profile_janitor_interval,
                TaskPriority.LOW_PRIORITY)
            retention = (self.retention if self.retention is not None
                         else flow.SERVER_KNOBS.profile_retention_seconds)
            try:
                self.records_trimmed += await trim_client_log(
                    self.db, flow.now() - retention)
                self.rounds += 1
            except flow.FdbError as e:
                if e.name == "operation_cancelled":
                    raise
                # a trim round losing to a recovery just waits for the
                # next interval — retention is best-effort maintenance
