"""Tuple layer: order-preserving encoding of mixed-type tuples.

Reference: fdbclient/Tuple.cpp + the cross-binding tuple spec
(design/tuple.md; bindings/python/fdb/tuple.py) — the SAME type codes
and byte transforms, so keys packed here sort exactly like the
reference's and interoperate with its bindings:

  0x00 null; 0x01 bytes (0x00 escaped as 0x00 0xFF, 0x00 terminator);
  0x02 utf-8 string (same escaping); 0x05 nested tuple (null inside is
  escaped 0x00 0xFF, 0x00 terminates); 0x0C..0x1C integers (0x14 zero,
  0x14+n n-byte big-endian positive, 0x14-n n-byte negative stored
  complemented); 0x21 double (big-endian IEEE, sign-flipped transform);
  0x26 false; 0x27 true; 0x30 UUID (16 bytes); 0x33 versionstamp.

The ordering property — pack(a) < pack(b) iff a < b under the layer's
type ordering — is what makes tuples usable as keys.
"""

from __future__ import annotations

import struct
import uuid as _uuid
from typing import Any, Tuple

from ..flow import error

_NULL = 0x00
_BYTES = 0x01
_STRING = 0x02
_NESTED = 0x05
_INT_ZERO = 0x14
_DOUBLE = 0x21
_FALSE = 0x26
_TRUE = 0x27
_UUID = 0x30
_VERSIONSTAMP = 0x33

_size_limits = [(1 << (i * 8)) - 1 for i in range(9)]


class Versionstamp:
    """(ref: the 12-byte versionstamp type: 10 bytes transaction
    version + 2 bytes user version)"""

    __slots__ = ("bytes_",)

    def __init__(self, bytes_: bytes):
        if len(bytes_) != 12:
            raise ValueError("versionstamp is 12 bytes")
        self.bytes_ = bytes(bytes_)

    def __eq__(self, other):
        return isinstance(other, Versionstamp) and \
            self.bytes_ == other.bytes_

    def __lt__(self, other):
        return self.bytes_ < other.bytes_

    def __hash__(self):
        return hash(self.bytes_)

    def __repr__(self):
        return f"Versionstamp({self.bytes_.hex()})"


def _encode_escaped(out: list, b: bytes) -> None:
    out.append(b.replace(b"\x00", b"\x00\xff"))
    out.append(b"\x00")


def _encode_one(out: list, v: Any, nested: bool) -> None:
    if v is None:
        out.append(b"\x00\xff" if nested else b"\x00")
    elif v is True:
        out.append(bytes([_TRUE]))
    elif v is False:
        out.append(bytes([_FALSE]))
    elif isinstance(v, (bytes, bytearray)):
        out.append(bytes([_BYTES]))
        _encode_escaped(out, bytes(v))
    elif isinstance(v, str):
        out.append(bytes([_STRING]))
        _encode_escaped(out, v.encode("utf-8"))
    elif isinstance(v, int):
        if v == 0:
            out.append(bytes([_INT_ZERO]))
        elif v > 0:
            n = (v.bit_length() + 7) // 8
            if n > 8:
                raise error("client_invalid_operation")
            out.append(bytes([_INT_ZERO + n]))
            out.append(v.to_bytes(n, "big"))
        else:
            n = ((-v).bit_length() + 7) // 8
            if n > 8:
                raise error("client_invalid_operation")
            out.append(bytes([_INT_ZERO - n]))
            out.append((v + _size_limits[n]).to_bytes(n, "big"))
    elif isinstance(v, float):
        out.append(bytes([_DOUBLE]))
        raw = struct.pack(">d", v)
        # order-preserving transform: flip the sign bit for positives,
        # complement everything for negatives (ref: Tuple.cpp float code)
        if raw[0] & 0x80:
            raw = bytes(x ^ 0xFF for x in raw)
        else:
            raw = bytes([raw[0] ^ 0x80]) + raw[1:]
        out.append(raw)
    elif isinstance(v, _uuid.UUID):
        out.append(bytes([_UUID]))
        out.append(v.bytes)
    elif isinstance(v, Versionstamp):
        out.append(bytes([_VERSIONSTAMP]))
        out.append(v.bytes_)
    elif isinstance(v, (tuple, list)):
        out.append(bytes([_NESTED]))
        for item in v:
            _encode_one(out, item, nested=True)
        out.append(b"\x00")
    else:
        raise error("client_invalid_operation")


def pack(t: Tuple) -> bytes:
    out: list = []
    for v in t:
        _encode_one(out, v, nested=False)
    return b"".join(out)


def _find_terminator(b: bytes, off: int) -> int:
    while True:
        i = b.index(b"\x00", off)
        if i + 1 < len(b) and b[i + 1] == 0xFF:
            off = i + 2
            continue
        return i


def _decode_one(b: bytes, off: int, nested: bool):
    code = b[off]
    if code == _NULL:
        if nested and off + 1 < len(b) and b[off + 1] == 0xFF:
            return None, off + 2
        return None, off + 1
    if code == _BYTES or code == _STRING:
        end = _find_terminator(b, off + 1)
        raw = b[off + 1:end].replace(b"\x00\xff", b"\x00")
        return (raw if code == _BYTES else raw.decode("utf-8")), end + 1
    if code == _NESTED:
        items = []
        off += 1
        while True:
            if b[off] == 0x00 and not (off + 1 < len(b)
                                       and b[off + 1] == 0xFF):
                return tuple(items), off + 1
            v, off = _decode_one(b, off, nested=True)
            items.append(v)
    if _INT_ZERO - 8 <= code <= _INT_ZERO + 8:
        n = code - _INT_ZERO
        if n == 0:
            return 0, off + 1
        if n > 0:
            return int.from_bytes(b[off + 1:off + 1 + n], "big"), \
                off + 1 + n
        n = -n
        return int.from_bytes(b[off + 1:off + 1 + n], "big") - \
            _size_limits[n], off + 1 + n
    if code == _DOUBLE:
        raw = b[off + 1:off + 9]
        if raw[0] & 0x80:
            raw = bytes([raw[0] ^ 0x80]) + raw[1:]
        else:
            raw = bytes(x ^ 0xFF for x in raw)
        return struct.unpack(">d", raw)[0], off + 9
    if code == _FALSE:
        return False, off + 1
    if code == _TRUE:
        return True, off + 1
    if code == _UUID:
        return _uuid.UUID(bytes=bytes(b[off + 1:off + 17])), off + 17
    if code == _VERSIONSTAMP:
        return Versionstamp(b[off + 1:off + 13]), off + 13
    raise error("client_invalid_operation")


def unpack(b: bytes) -> Tuple:
    out = []
    off = 0
    while off < len(b):
        v, off = _decode_one(b, off, nested=False)
        out.append(v)
    return tuple(out)


def range_of(t: Tuple) -> Tuple[bytes, bytes]:
    """The key range of every tuple extending `t` (ref: Tuple::range /
    fdb.tuple.range)."""
    p = pack(t)
    return p + b"\x00", p + b"\xff"
