"""Layers: stateless client libraries on the KV API (ref: layers/ +
the tuple/subspace/directory machinery in the reference bindings)."""

from . import tuple_layer
from .directory import Directory, DirectoryLayer
from .subspace import Subspace
from .taskbucket import Task, TaskBucket
from .tuple_layer import Versionstamp, pack, range_of, unpack

__all__ = ["tuple_layer", "Subspace", "Versionstamp", "pack", "range_of",
           "unpack", "Directory", "DirectoryLayer", "Task", "TaskBucket"]
