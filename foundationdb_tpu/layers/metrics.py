"""MetricLogger: persist role counters into the database itself.

Reference: fdbclient/MetricLogger.actor.cpp + flow/TDMetric — counter
samples written into a system-ish keyspace so the database stores its
own time series. Here: one snapshot per role per flush under a tuple
subspace keyed (role, counter, sim_time)."""

from __future__ import annotations

from .. import flow
from ..client import run_transaction
from .subspace import Subspace

DEFAULT_SPACE = Subspace(("\x02metrics",))


async def log_counters(db, collections, space: Subspace = DEFAULT_SPACE,
                       max_retries: int = 100, extra: dict = None) -> int:
    """Write one timestamped sample per counter; returns rows written.

    `extra` persists series that have no CounterCollection behind them
    — the latency-probe readings and the conflict hot-spot scores the
    cluster controller assembles for status: a mapping
    {series_role: {counter_name: int_value}} written under the same
    (role, counter, ms_timestamp) tuple keys, so `read_series` replays
    probe and conflict history exactly like any role counter."""
    now = flow.now()
    rows = []
    for col in collections:
        for name, value in col.snapshot().items():
            rows.append((space.pack((col.role, name, int(now * 1000))),
                         b"%d" % value))
    for role, counters in (extra or {}).items():
        for name, value in counters.items():
            rows.append((space.pack((role, name, int(now * 1000))),
                         b"%d" % int(value)))

    async def body(tr):
        for k, v in rows:
            tr.set(k, v)
    await run_transaction(db, body, max_retries=max_retries)
    return len(rows)


async def read_series(db, role: str, counter: str,
                      space: Subspace = DEFAULT_SPACE,
                      start: int = None, end: int = None):
    """Samples for one counter: [(ms_timestamp, value)], optionally
    bounded to start <= ms_timestamp < end (tuple-encoded bounds ride
    the ordinary range read, so the cut happens server-side — the
    whole-history fetch was the round-1 shape; a dashboard asking for
    the last minute must not page years of samples)."""
    if start is None and end is None:
        b, e = space.range((role, counter))
    else:
        full_b, full_e = space.range((role, counter))
        b = space.pack((role, counter, int(start))) if start is not None \
            else full_b
        e = space.pack((role, counter, int(end))) if end is not None \
            else full_e
    tr = db.create_transaction()
    rows = await tr.get_range(b, e)
    return [(space.unpack(k)[-1], int(v)) for k, v in rows]


async def metric_logger(db, collections, interval: float = None,
                        space: Subspace = DEFAULT_SPACE,
                        extra_fn=None):
    """Periodic flush actor (ref: runMetrics). `extra_fn`, when given,
    is called each round for the `extra` sample dict (the probe /
    hot-spot series a status assembler exposes)."""
    if interval is None:
        interval = flow.SERVER_KNOBS.metric_logger_interval
    while True:
        await flow.delay(interval)
        await log_counters(db, collections, space,
                           extra=extra_fn() if extra_fn else None)
