"""MetricLogger: persist role counters into the database itself.

Reference: fdbclient/MetricLogger.actor.cpp + flow/TDMetric — counter
samples written into a system-ish keyspace so the database stores its
own time series. Here: one snapshot per role per flush under a tuple
subspace keyed (role, counter, sim_time)."""

from __future__ import annotations

from .. import flow
from ..client import run_transaction
from .subspace import Subspace

DEFAULT_SPACE = Subspace(("\x02metrics",))


async def log_counters(db, collections, space: Subspace = DEFAULT_SPACE,
                       max_retries: int = 100, extra: dict = None) -> int:
    """Write one timestamped sample per counter; returns rows written.

    `extra` persists series that have no CounterCollection behind them
    — the latency-probe readings and the conflict hot-spot scores the
    cluster controller assembles for status: a mapping
    {series_role: {counter_name: int_value}} written under the same
    (role, counter, ms_timestamp) tuple keys, so `read_series` replays
    probe and conflict history exactly like any role counter."""
    now = flow.now()
    rows = []
    for col in collections:
        for name, value in col.snapshot().items():
            rows.append((space.pack((col.role, name, int(now * 1000))),
                         b"%d" % value))
    for role, counters in (extra or {}).items():
        for name, value in counters.items():
            rows.append((space.pack((role, name, int(now * 1000))),
                         b"%d" % int(value)))

    async def body(tr):
        for k, v in rows:
            tr.set(k, v)
    await run_transaction(db, body, max_retries=max_retries)
    return len(rows)


async def read_series(db, role: str, counter: str,
                      space: Subspace = DEFAULT_SPACE,
                      start: int = None, end: int = None):
    """Samples for one counter: [(ms_timestamp, value)], optionally
    bounded to start <= ms_timestamp < end (tuple-encoded bounds ride
    the ordinary range read, so the cut happens server-side — the
    whole-history fetch was the round-1 shape; a dashboard asking for
    the last minute must not page years of samples)."""
    if start is None and end is None:
        b, e = space.range((role, counter))
    else:
        full_b, full_e = space.range((role, counter))
        b = space.pack((role, counter, int(start))) if start is not None \
            else full_b
        e = space.pack((role, counter, int(end))) if end is not None \
            else full_e
    tr = db.create_transaction()
    rows = await tr.get_range(b, e)
    return [(space.unpack(k)[-1], int(v)) for k, v in rows]


async def metric_logger(db, collections, interval: float = None,
                        space: Subspace = DEFAULT_SPACE,
                        extra_fn=None):
    """Periodic flush actor (ref: runMetrics). `extra_fn`, when given,
    is called each round for the `extra` sample dict (the probe /
    hot-spot series a status assembler exposes)."""
    if interval is None:
        interval = flow.SERVER_KNOBS.metric_logger_interval
    while True:
        await flow.delay(interval)
        await log_counters(db, collections, space,
                           extra=extra_fn() if extra_fn else None)


# -- the \xff\x02/metrics/ history series (ISSUE 17) ----------------------
# Written by the CC's MetricHistoryRecorder (server/metric_history.py)
# in delta-encoded chunk rows; read back here by anything with a
# database handle — the soak's restart-safe verdict, incident bundles,
# dashboards.

async def read_history(db, signal: str, start_ms: int = None,
                       end_ms: int = None, limit: int = 100_000):
    """One signal's persisted samples: [(ts_ms, int_value)], optionally
    bounded to start_ms <= ts < end_ms. Chunks are self-contained, so
    the row range is cut at chunk granularity and samples filtered —
    a chunk straddling the window still contributes its inside part."""
    from ..server.systemkeys import (decode_metric_chunk,
                                     metric_history_signal_prefix)
    prefix = metric_history_signal_prefix(signal)

    async def body(tr):
        tr.set_option("access_system_keys")
        return await tr.get_range(prefix, prefix + b"\xff", limit=limit)

    rows = await run_transaction(db, body)
    out = []
    for _k, v in rows:
        samples = decode_metric_chunk(v)
        if samples is None:
            continue
        for ts, val in samples:
            if start_ms is not None and ts < start_ms:
                continue
            if end_ms is not None and ts >= end_ms:
                continue
            out.append((ts, val))
    return out


async def list_history_signals(db, limit: int = 100_000):
    """Every signal with at least one persisted chunk, sorted."""
    from ..server.systemkeys import (METRIC_HISTORY_END,
                                     METRIC_HISTORY_PREFIX,
                                     parse_metric_history_key)

    async def body(tr):
        tr.set_option("access_system_keys")
        return await tr.get_range(METRIC_HISTORY_PREFIX,
                                  METRIC_HISTORY_END, limit=limit)

    rows = await run_transaction(db, body)
    signals = set()
    for k, _v in rows:
        parsed = parse_metric_history_key(k)
        if parsed is not None:
            signals.add(parsed[1])
    return sorted(signals)


async def trim_history(db, cutoff_ms: int, max_retries: int = 100,
                       scan_limit: int = 10_000) -> int:
    """Trim every signal's series to the retention window: one bounded
    scan to discover the live signals, then one clear_range per signal
    up to its cutoff chunk (the clientlog-janitor shape; chunks are
    keyed by their FIRST sample, so a straddling chunk survives whole)."""
    from ..server.systemkeys import (METRIC_HISTORY_END,
                                     METRIC_HISTORY_PREFIX,
                                     metric_history_cutoff_key,
                                     metric_history_signal_prefix,
                                     parse_metric_history_key)

    async def body(tr):
        tr.set_option("access_system_keys")
        rows = await tr.get_range(METRIC_HISTORY_PREFIX,
                                  METRIC_HISTORY_END, limit=scan_limit)
        doomed = 0
        signals = set()
        for k, _v in rows:
            parsed = parse_metric_history_key(k)
            if parsed is None:
                continue
            signals.add(parsed[1])
            if parsed[2] < cutoff_ms:
                doomed += 1
        for signal in signals:
            tr.clear_range(metric_history_signal_prefix(signal),
                           metric_history_cutoff_key(signal, cutoff_ms))
        return doomed

    return await run_transaction(db, body, max_retries=max_retries)


async def trim_series(db, cutoff_ms: int, space: Subspace = DEFAULT_SPACE,
                      max_retries: int = 100,
                      scan_limit: int = 10_000) -> int:
    """Trim the LEGACY tuple-space counter series (log_counters above)
    to the same retention window: keys order as (role, counter, ts), so
    old rows interleave per pair — one bounded scan discovers the live
    (role, counter) pairs, then one clear_range per pair trims its tail."""
    b, e = space.range(())

    async def body(tr):
        rows = await tr.get_range(b, e, limit=scan_limit)
        doomed = 0
        pairs = set()
        for k, _v in rows:
            try:
                role, counter, ts = space.unpack(k)
            except Exception:  # noqa: BLE001 — foreign rows are skipped
                continue
            pairs.add((role, counter))
            if ts < cutoff_ms:
                doomed += 1
        for role, counter in pairs:
            pb, _pe = space.range((role, counter))
            tr.clear_range(pb, space.pack((role, counter, cutoff_ms)))
        return doomed

    return await run_transaction(db, body, max_retries=max_retries)


class MetricsJanitor:
    """ONE retention janitor for every longitudinal keyspace (the
    ISSUE 17 satellite: trimming was ad hoc per series): the
    \\xff\\x02/metrics/ history and the legacy tuple-space counter
    series share METRIC_RETENTION_SECONDS; the TimeKeeper map keeps
    its own TIMEKEEPER_RETENTION (operators want version translation
    to reach further back than dense samples). Lifecycle mirrors
    ClientLogJanitor."""

    def __init__(self, cluster, retention: float = None,
                 interval: float = None, space: Subspace = DEFAULT_SPACE):
        self.cluster = cluster
        self.db = cluster.client("metrics-janitor")
        self.retention = retention
        self.interval = interval
        self.space = space
        self.rows_trimmed = 0
        self.rounds = 0
        self._task = None

    def start(self) -> None:
        from ..flow import TaskPriority
        self._task = flow.spawn(self._run(), TaskPriority.LOW_PRIORITY,
                                name="metricsJanitor")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        from ..flow import TaskPriority
        from ..server.timekeeper import trim_timekeeper
        while True:
            await flow.delay(
                self.interval if self.interval is not None
                else flow.SERVER_KNOBS.metric_janitor_interval,
                TaskPriority.LOW_PRIORITY)
            retention = (self.retention if self.retention is not None
                         else flow.SERVER_KNOBS.metric_retention_seconds)
            cutoff_ms = int((flow.now() - retention) * 1000)
            try:
                trimmed = await trim_history(self.db, cutoff_ms)
                trimmed += await trim_series(self.db, cutoff_ms,
                                             self.space)
                trimmed += await trim_timekeeper(
                    self.db,
                    flow.now() - flow.SERVER_KNOBS.timekeeper_retention)
                if trimmed:
                    flow.TraceEvent("MetricsTrimmed").detail(
                        Rows=trimmed, CutoffMs=cutoff_ms).log()
                self.rows_trimmed += trimmed
                self.rounds += 1
            except flow.FdbError as e:
                if e.name == "operation_cancelled":
                    raise
                # a trim round losing to a recovery waits for the next
