"""MetricLogger: persist role counters into the database itself.

Reference: fdbclient/MetricLogger.actor.cpp + flow/TDMetric — counter
samples written into a system-ish keyspace so the database stores its
own time series. Here: one snapshot per role per flush under a tuple
subspace keyed (role, counter, sim_time)."""

from __future__ import annotations

from .. import flow
from ..client import run_transaction
from .subspace import Subspace

DEFAULT_SPACE = Subspace(("\x02metrics",))


async def log_counters(db, collections, space: Subspace = DEFAULT_SPACE,
                       max_retries: int = 100) -> int:
    """Write one timestamped sample per counter; returns rows written."""
    now = flow.now()
    rows = []
    for col in collections:
        for name, value in col.snapshot().items():
            rows.append((space.pack((col.role, name, int(now * 1000))),
                         b"%d" % value))

    async def body(tr):
        for k, v in rows:
            tr.set(k, v)
    await run_transaction(db, body, max_retries=max_retries)
    return len(rows)


async def read_series(db, role: str, counter: str,
                      space: Subspace = DEFAULT_SPACE):
    """All samples for one counter: [(ms_timestamp, value)]."""
    b, e = space.range((role, counter))
    tr = db.create_transaction()
    rows = await tr.get_range(b, e)
    return [(space.unpack(k)[-1], int(v)) for k, v in rows]


async def metric_logger(db, collections, interval: float = None,
                        space: Subspace = DEFAULT_SPACE):
    """Periodic flush actor (ref: runMetrics)."""
    if interval is None:
        interval = flow.SERVER_KNOBS.metric_logger_interval
    while True:
        await flow.delay(interval)
        await log_counters(db, collections, space)
