"""Backup / restore: snapshot the database to a file and bring it back.

Reference: fdbclient/FileBackupAgent.actor.cpp + design/backup.md — a
backup is a consistent range snapshot (here: one paged read version,
exactly the consistency the reference's snapshot phase provides per
range file) written as length-prefixed kv records behind a versioned
header; restore clears the target range and writes the records back in
batches. The reference's continuous mutation log (for point-in-time
restore) rides the same container format and is future work; this
covers the fdbbackup/fdbrestore snapshot path.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

MAGIC = b"FDBTPUBK"
FORMAT_VERSION = 1
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

PAGE = 1000          # rows per read page
RESTORE_BATCH = 500  # rows per restore transaction


async def backup(db, begin: bytes = b"", end: bytes = b"\xff",
                 max_attempts: int = 50):
    """Snapshot [begin, end) at a single read version; returns
    (blob, version, row_count). A scan that outlives the MVCC window
    (or hits any retryable failure) restarts with a fresh read version
    — the snapshot is consistent at whichever version completes."""
    from ..client import RETRYABLE
    from .. import flow

    last = None
    for _attempt in range(max_attempts):
        tr = db.create_transaction()
        rows: List[Tuple[bytes, bytes]] = []
        cursor = begin
        try:
            while True:
                page = await tr.get_range(cursor, end, limit=PAGE,
                                          snapshot=True)
                rows.extend(page)
                if len(page) < PAGE:
                    break
                cursor = page[-1][0] + b"\x00"
            version = await tr.get_read_version()
            break
        except flow.FdbError as e:
            if e.name not in RETRYABLE:
                raise
            last = e
            await tr.on_error(e)
    else:
        raise last
    out = [MAGIC, bytes([FORMAT_VERSION]), _U64.pack(version),
           _U32.pack(len(begin)), begin, _U32.pack(len(end)), end,
           _U64.pack(len(rows))]
    for k, v in rows:
        out.append(_U32.pack(len(k)))
        out.append(k)
        out.append(_U32.pack(len(v)))
        out.append(v)
    return b"".join(out), version, len(rows)


def backup_to_file(blob: bytes, path: str) -> None:
    with open(path, "wb") as f:
        f.write(blob)


def read_backup(path_or_blob) -> Tuple[bytes, bytes, int,
                                       List[Tuple[bytes, bytes]]]:
    """Parse a backup; returns (begin, end, version, rows)."""
    if isinstance(path_or_blob, (bytes, bytearray)):
        b = bytes(path_or_blob)
    else:
        with open(path_or_blob, "rb") as f:
            b = f.read()
    if b[:8] != MAGIC or b[8] != FORMAT_VERSION:
        raise ValueError("not a backup file (bad magic/version)")
    off = 9
    (version,) = _U64.unpack_from(b, off)
    off += 8
    (lb,) = _U32.unpack_from(b, off)
    off += 4
    begin = b[off:off + lb]
    off += lb
    (le,) = _U32.unpack_from(b, off)
    off += 4
    end = b[off:off + le]
    off += le
    (n,) = _U64.unpack_from(b, off)
    off += 8
    rows = []
    for _ in range(n):
        (lk,) = _U32.unpack_from(b, off)
        off += 4
        k = b[off:off + lk]
        off += lk
        (lv,) = _U32.unpack_from(b, off)
        off += 4
        v = b[off:off + lv]
        off += lv
        rows.append((k, v))
    return begin, end, version, rows


async def restore(db, path_or_blob, max_retries: int = 200) -> int:
    """Clear the backed-up range and write the snapshot back in
    batches (ref: the restore apply loop). Returns rows restored."""
    from ..client import run_transaction

    begin, end, _version, rows = read_backup(path_or_blob)

    async def clear_body(tr):
        tr.clear_range(begin, end)
    await run_transaction(db, clear_body, max_retries=max_retries)

    for i in range(0, len(rows), RESTORE_BATCH):
        batch = rows[i:i + RESTORE_BATCH]

        async def body(tr, batch=batch):
            for k, v in batch:
                tr.set(k, v)
        await run_transaction(db, body, max_retries=max_retries)
    return len(rows)
