"""PubSub layer: feeds, inboxes, fan-out message delivery.

Reference: layers/pubsub (the in-tree Python recipe) and
fdbserver/pubsub.actor.cpp — feeds post messages; inboxes subscribe to
feeds; a read drains each subscribed feed from the inbox's last-seen
watermark. Everything is ordinary transactions over the tuple layer,
so delivery inherits the database's ACID guarantees: a post is either
visible to every subscriber or none.

Layout (all under one Subspace):
  ("feed", feed_id, seq)        -> message bytes
  ("feedmeta", feed_id)         -> next seq (little-endian, atomic ADD)
  ("sub", inbox_id, feed_id)    -> last-read seq (versionless watermark)
"""

from __future__ import annotations

from typing import List, Tuple

from .subspace import Subspace


class PubSub:
    def __init__(self, subspace: Subspace = None):
        self.ss = subspace if subspace is not None else Subspace(("pubsub",))

    # -- feeds -----------------------------------------------------------
    async def post(self, tr, feed: str, message: bytes) -> None:
        """Append a message to the feed. The sequencer read carries a
        CONFLICT range: concurrent posters to the same feed serialize
        through OCC retry, so no post can overwrite another (a
        snapshot read here would silently drop messages — review r3)."""
        meta = self.ss.pack(("feedmeta", feed))
        raw = await tr.get(meta)
        seq = int.from_bytes(raw or b"", "little")
        tr.set(self.ss.pack(("feed", feed, seq)), message)
        tr.set(meta, (seq + 1).to_bytes(8, "little"))

    # -- subscriptions ---------------------------------------------------
    async def subscribe(self, tr, inbox: str, feed: str) -> None:
        """New subscribers start at the feed's current tail — they see
        messages posted after the subscription (the recipe's choice)."""
        raw = await tr.get(self.ss.pack(("feedmeta", feed)))
        tr.set(self.ss.pack(("sub", inbox, feed)), raw or b"")

    def unsubscribe(self, tr, inbox: str, feed: str) -> None:
        tr.clear(self.ss.pack(("sub", inbox, feed)))

    async def read_inbox(self, tr, inbox: str,
                         limit: int = 100) -> List[Tuple[str, bytes]]:
        """Drain un-read messages across every subscribed feed, oldest
        first per feed, advancing the watermarks."""
        b, e = self.ss.range(("sub", inbox))
        subs = await tr.get_range(b, e)
        out: List[Tuple[str, bytes]] = []
        for sk, sv in subs:
            feed = self.ss.unpack(sk)[2]
            mark = int.from_bytes(sv or b"", "little")
            fb = self.ss.pack(("feed", feed, mark))
            _b2, fe = self.ss.range(("feed", feed))
            msgs = await tr.get_range(fb, fe, limit=limit - len(out))
            last = mark
            for mk, mv in msgs:
                seq = self.ss.unpack(mk)[2]
                out.append((feed, mv))
                last = seq + 1
            if last != mark:
                tr.set(sk, last.to_bytes(8, "little"))
            if len(out) >= limit:
                break
        return out
