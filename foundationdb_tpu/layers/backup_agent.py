"""Continuous backup: a mutation-log tail + snapshot = point-in-time
restore.

Reference: fdbclient/FileBackupAgent.actor.cpp + design/backup.md — a
backup is a range snapshot PLUS a continuous mutation log; restore
applies the snapshot then replays the log to the target version. The
log here comes from a dedicated backup tag the proxies add to every
mutation while a backup is active (ref: the backup mutation-log tags):
one stream preserves exact intra-version mutation order, and the agent
is registered in the TLogs' expected-replica sets so records it has
not yet persisted are never popped away beneath it.

Protocol: enable the tag FIRST, then take the snapshot — every
mutation after the snapshot version is guaranteed present in the tail,
and restore discards log records at or below it.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from .. import flow
from ..flow import TaskPriority
from ..server.types import MutationRef, TLogPeekRequest, TLogPopRequest
from . import backup as snapshot_backup

LOG_MAGIC = b"FDBTPUML"
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

AGENT_NAME = "backup-agent"


class BackupAgent:
    """Drives one continuous backup of a SimCluster (operator-side
    tool, like the CLI: it holds the cluster handle the way fdbbackup
    holds a cluster file)."""

    def __init__(self, cluster, db,
                 backup_range: Tuple[bytes, bytes] = (b"", b"\xff")):
        self.cluster = cluster
        self.db = db
        # what this backup covers (ref: backupRanges — the default is
        # the whole user keyspace). The tail CLIPS the stream to it, so
        # \xff rows — notably the \xff\x02/backup/ control rows the
        # driver itself writes — never enter the mutation log: a
        # restore must not replay the tool's own state machine into
        # the live control subspace
        self.backup_range = backup_range
        self.base_blob: Optional[bytes] = None
        self.base_version = 0
        self.log_records: List[Tuple[int, Tuple[MutationRef, ...]]] = []
        self._tail_task = None
        self._tailed_to = 0
        self._stop = False
        self._replica_rr = 0
        # identity token for container-held incremental upload state
        self._upload_token = object()

    # -- lifecycle -------------------------------------------------------
    async def _tagging_recovery(self, active: bool) -> None:
        """Flip the backup tag THROUGH an epoch recovery: the next
        epoch's proxies are recruited with the flag and its TLogs with
        the agent in BACKUP_TAG's replica set — nothing pokes live
        roles, so the change also works over a real deployment (ref:
        backup tagging as part of the log system configuration; same
        shape as attaching a region)."""
        cc = self.cluster.cc
        cc.backup_active = active
        cc.backup_agent = self if active else None
        cc._config_dirty = True
        # wait for a SETTLED epoch that advertises the flag — not
        # merely the next epoch: a recovery already past recruitment
        # when the flag flipped publishes the stale value, and the
        # level-triggered config-dirty recovery after it publishes the
        # corrected one (start: a silent log hole otherwise; stop: the
        # tag would pin records forever)
        while True:
            info = cc.dbinfo.get()
            if info.backup_active == active and \
                    info.recovery_state == "fully_recovered":
                return
            await flow.first_of(
                cc.dbinfo.on_change(),
                flow.delay(flow.SERVER_KNOBS.backup_nudge_interval,
                           TaskPriority.DEFAULT_ENDPOINT))

    async def start(self) -> int:
        """Enable the tag via recovery, start tailing at the new
        epoch's recovery version (everything before it is untagged but
        provably below the snapshot), then snapshot; returns the
        snapshot (base) version."""
        cc = self.cluster.cc
        await self._tagging_recovery(True)
        # every commit of the new epoch carries the tag; the snapshot's
        # GRV is above the recovery version, so each untagged (older)
        # transaction is inside the snapshot and each later one is in
        # the tail
        start_v = cc.dbinfo.get().recovery_version
        self._tail_task = flow.spawn(self._tail(start_v),
                                     TaskPriority.DEFAULT_ENDPOINT,
                                     name="backupAgent.tail")
        blob, version, _n = await snapshot_backup.backup(self.db)
        self.base_blob = blob
        self.base_version = version
        return version

    async def stop(self) -> None:
        self._stop = True
        await self._tagging_recovery(False)
        if self._tail_task is not None:
            await flow.catch_errors(self._tail_task)

    # -- the tail (modeled on the storage pull loop) ---------------------
    async def _tail(self, start_version: int) -> None:
        from ..server.proxy import BACKUP_TAG
        version = start_version
        while not self._stop:
            info = self.cluster.cc.dbinfo.get()
            src = self._pick_source(info, version + 1)
            if src is None:
                await flow.delay(
                    flow.SERVER_KNOBS.backup_source_retry_delay,
                    TaskPriority.DEFAULT_ENDPOINT)
                continue
            gen, refs = src
            try:
                reply = await flow.timeout_error(refs.peeks.get_reply(
                    TLogPeekRequest(version + 1, BACKUP_TAG),
                    self.db.process),
                    flow.SERVER_KNOBS.backup_peek_timeout)
            except flow.FdbError:
                self._replica_rr += 1   # rotate off a dead replica
                await flow.delay(flow.SERVER_KNOBS.backup_tail_idle_delay,
                                 TaskPriority.DEFAULT_ENDPOINT)
                continue
            cap = gen.end_version if gen.end_version >= 0 else None
            # never record beyond what is known replicated cluster-wide:
            # a single tlog's durable tail can roll back in a recovery,
            # and the log must only ever contain versions a consistent
            # database state actually had (the storage pull applies the
            # same cap to durability)
            safe = reply.known_committed
            if cap is not None:
                safe = max(safe, cap)   # a locked gen's end IS final
            before = version
            for v, mutations in reply.entries:
                if v <= version:
                    continue
                if cap is not None and v > cap:
                    break
                if v > safe:
                    break
                kept = self._clip(mutations)
                if kept:
                    self.log_records.append((v, kept))
                version = v
            adv = min(reply.committed_version, safe)
            if cap is not None:
                adv = min(adv, cap)
            version = max(version, adv)
            self._tailed_to = version
            if version > before:
                refs.pops.send(TLogPopRequest(version, BACKUP_TAG,
                                              AGENT_NAME), self.db.process)
            elif cap is None:
                # no progress on the open generation: known_committed
                # only advances with fresh commits — nudge one through
                await self._nudge_commit()
                await flow.delay(flow.SERVER_KNOBS.backup_tail_idle_delay,
                                 TaskPriority.DEFAULT_ENDPOINT)

    def _clip(self, mutations) -> Tuple[MutationRef, ...]:
        """Clip a version's mutations to the backup range (ref: the
        backup's backupRanges bounding what the mutation log keeps)."""
        lo, hi = self.backup_range
        from ..server.types import CLEAR_RANGE
        out = []
        for m in mutations:
            if m.type == CLEAR_RANGE:
                b, e = max(m.param1, lo), min(m.param2, hi)
                if b < e:
                    out.append(m if (b, e) == (m.param1, m.param2)
                               else MutationRef(CLEAR_RANGE, b, e))
            elif lo <= m.param1 < hi:
                out.append(m)
        return tuple(out)

    def _pick_source(self, info, needed: int):
        from ..server.dbinfo import pick_log_source
        return pick_log_source(info, needed, self._replica_rr)

    async def _nudge_commit(self) -> None:
        from ..server.types import CommitRequest
        info = self.cluster.cc.dbinfo.get()
        if info.proxies:
            await flow.catch_errors(flow.timeout_error(
                info.proxies[0].commits.get_reply(
                    CommitRequest(0, (), (), ()), self.db.process), 1.0))

    async def _wait_until(self, pred, max_wait: float) -> None:
        """Poll with commit nudges: the tail/apply frontiers only
        advance through known_committed, which needs fresh commits on
        an idle cluster."""
        deadline = flow.now() + max_wait
        while not pred():
            if flow.now() > deadline:
                raise flow.error("timed_out")
            await self._nudge_commit()
            await flow.delay(flow.SERVER_KNOBS.backup_nudge_interval,
                             TaskPriority.DEFAULT_ENDPOINT)

    async def wait_tailed_to(self, version: int, max_wait: float = 30.0):
        await self._wait_until(lambda: self._tailed_to >= version, max_wait)

    # -- container -------------------------------------------------------
    def save_to(self, container, chunk_records: int = None) -> dict:
        """Write this backup into a container using the reference's
        file layout: one snapshot object + chunked mutation-log objects
        whose names carry their version coverage (ref: BackupContainer
        snapshots/ + logs/ naming). INCREMENTAL per container: the
        snapshot and full chunks upload once; only the growing tail
        chunk re-uploads (overlapping coverage is clipped at restore) —
        so the periodic driver upload is O(new records), not O(whole
        history). Returns the container's describe(). Plain sync object
        IO, like fdbbackup writing to its target."""
        from .backup_container import _records_to_log_blob
        if chunk_records is None:
            chunk_records = int(
                flow.SERVER_KNOBS.backup_log_chunk_records)
        if self.base_blob is None:
            raise ValueError("backup has no snapshot yet (start() first)")
        # incremental state lives ON the container (keyed by this
        # agent): it dies with the container, and a fresh container can
        # never inherit another's consumed-record counters
        st = getattr(container, "_agent_upload_state", None)
        if st is None or st.get("agent") is not self._upload_token:
            # keyed by a per-agent token, NOT the agent itself: a
            # container outliving the agent must not pin the agent's
            # whole mutation-log history in memory
            st = {"agent": self._upload_token, "snap": False, "n": 0,
                  "end": self.base_version}
            container._agent_upload_state = st
        if not st["snap"]:
            container.store_snapshot(self.base_blob, self.base_version)
            st["snap"] = True
        recs = [r for r in self.log_records if r[0] > self.base_version]
        i = st["n"]
        # complete chunks: upload once and consume
        while len(recs) - i >= chunk_records:
            chunk = recs[i:i + chunk_records]
            i += chunk_records
            end = chunk[-1][0]
            container.store_log(
                _records_to_log_blob(chunk, self.base_version),
                st["end"], end)
            st["n"], st["end"] = i, end
        # the partial tail: re-upload from the last consumed boundary
        # with coverage out to the tail frontier (versions with no
        # backup-tagged payload are still certified mutation-free)
        tail = recs[i:]
        tail_end = max([r[0] for r in tail] + [self._tailed_to])
        if tail_end > st["end"]:
            container.store_log(
                _records_to_log_blob(tail, self.base_version),
                st["end"], tail_end)
        return container.describe()

    def write_log(self) -> bytes:
        return encode_log(self.log_records, self.base_version)


def encode_log(records, base_version: int) -> bytes:
    """The mutation-log wire format (one encoder, one decoder —
    read_log below): MAGIC, base version, then (version, mutations)
    records."""
    out = [LOG_MAGIC, _U64.pack(base_version), _U64.pack(len(records))]
    for v, mutations in records:
        out.append(_U64.pack(v))
        out.append(_U32.pack(len(mutations)))
        for m in mutations:
            out.append(bytes([m.type]))
            out.append(_U32.pack(len(m.param1)))
            out.append(m.param1)
            out.append(_U32.pack(len(m.param2)))
            out.append(m.param2)
    return b"".join(out)


def read_log(blob: bytes):
    if blob[:8] != LOG_MAGIC:
        raise ValueError("not a mutation log")
    (base_version,) = _U64.unpack_from(blob, 8)
    (n,) = _U64.unpack_from(blob, 16)
    off = 24
    records = []
    for _ in range(n):
        (v,) = _U64.unpack_from(blob, off)
        off += 8
        (nm,) = _U32.unpack_from(blob, off)
        off += 4
        ms = []
        for _ in range(nm):
            t = blob[off]
            off += 1
            (l1,) = _U32.unpack_from(blob, off)
            p1 = bytes(blob[off + 4:off + 4 + l1])
            off += 4 + l1
            (l2,) = _U32.unpack_from(blob, off)
            p2 = bytes(blob[off + 4:off + 4 + l2])
            off += 4 + l2
            ms.append(MutationRef(t, p1, p2))
        records.append((v, tuple(ms)))
    return base_version, records


def _replay_mutations(tr, mutations) -> None:
    """Replay one logged mutation batch into a transaction — the single
    apply switch shared by restore and DR (a replayable type added here
    serves both paths). System-key mutations (the \\xff\\x02 stored
    subspace rides the backup tag like everything else) need the
    option, exactly as the reference's restore does."""
    from ..server.types import (ATOMIC_OPS, CLEAR_RANGE, INERT_OPS,
                                SET_VALUE)
    tr.set_option("access_system_keys")
    for m in mutations:
        if m.type == SET_VALUE:
            tr.set(m.param1, m.param2)
        elif m.type == CLEAR_RANGE:
            tr.clear_range(m.param1, m.param2)
        elif m.type in ATOMIC_OPS:
            tr.atomic_op(m.param1, m.param2, m.type)
        elif m.type in INERT_OPS:
            pass  # debug markers/no-ops ride the log but mutate nothing
        else:
            raise ValueError(f"unreplayable mutation {m.type}")


async def restore_to_version(db, snapshot_blob: bytes, log_blob: bytes,
                             target_version: int,
                             max_retries: int = 300) -> int:
    """Point-in-time restore: the snapshot state plus every logged
    mutation in (base_version, target_version], applied in exact
    commit order (ref: the restore apply loop replaying log files)."""
    from ..client import run_transaction

    base_version, records = read_log(log_blob)
    if target_version < base_version:
        raise ValueError("target predates the snapshot")
    await snapshot_backup.restore(db, snapshot_blob,
                                  max_retries=max_retries)
    applied = 0
    batch: List[MutationRef] = []
    for v, mutations in records:
        if v <= base_version or v > target_version:
            continue
        batch.extend(mutations)
    marker_space = b"\x02restore-mark/"
    for i in range(0, len(batch), 200):
        chunk = batch[i:i + 200]
        marker = marker_space + b"%012d" % i

        async def body(tr, chunk=chunk, marker=marker):
            # chunk marker: atomic ops are NOT idempotent, so a retry
            # after commit_unknown_result must detect an applied chunk
            # instead of re-running it (the reference's idempotency
            # pattern for restore apply)
            if await tr.get(marker) is not None:
                return
            _replay_mutations(tr, chunk)
            tr.set(marker, b"1")
        await run_transaction(db, body, max_retries=max_retries)
        applied += len(chunk)

    async def clear_markers(tr):
        tr.clear_range(marker_space, marker_space + b"\xff")
    await run_transaction(db, clear_markers, max_retries=max_retries)
    return applied


class DrAgent(BackupAgent):
    """Continuous replication to a DESTINATION database (ref:
    fdbclient/DatabaseBackupAgent.actor.cpp — DR is the same mutation
    stream applied to another cluster instead of files). The
    destination converges to each source version in commit order;
    chunk markers make the apply exactly-once across retries."""

    MARKER_SPACE = b"\x02dr-mark/"

    def __init__(self, cluster, db, dest_db):
        super().__init__(cluster, db)
        self.dest_db = dest_db
        self.applied_version = 0
        self._apply_task = None
        self._applied_idx = 0
        self._apply_error: Optional[BaseException] = None

    async def start(self) -> int:
        """Snapshot into the destination, then stream the tail."""
        base = await super().start()
        await snapshot_backup.restore(self.dest_db, self.base_blob)
        self.applied_version = base
        self._apply_task = flow.spawn(self._apply_loop(),
                                      TaskPriority.DEFAULT_ENDPOINT,
                                      name="drAgent.apply")
        return base

    async def stop(self) -> None:
        await super().stop()
        if self._apply_task is not None:
            await flow.catch_errors(self._apply_task)
        if self._apply_error is not None:
            raise self._apply_error
        # the idempotency markers served their purpose: leave the
        # destination byte-identical to the source's replicated range
        from ..client import run_transaction

        async def clear_markers(tr):
            tr.clear_range(self.MARKER_SPACE, self.MARKER_SPACE + b"\xff")
        await run_transaction(self.dest_db, clear_markers, max_retries=300)

    async def wait_applied_to(self, version: int,
                              max_wait: float = 60.0) -> None:
        def pred():
            if self._apply_error is not None:
                raise self._apply_error
            return self.applied_version >= version
        await self._wait_until(pred, max_wait)

    async def _apply_loop(self) -> None:
        try:
            await self._apply_records()
        except flow.ActorCancelled:
            raise
        except BaseException as e:  # noqa: BLE001 — surfaced to waiters
            self._apply_error = e

    async def _apply_records(self) -> None:
        from ..client import run_transaction
        while not (self._stop and
                   self._applied_idx >= len(self.log_records)):
            if self._applied_idx >= len(self.log_records):
                # drained: everything at or below the tail frontier is
                # applied — a version with no backup-tagged record
                # (empty nudge commits) must still become waitable
                self.applied_version = max(self.applied_version,
                                           self._tailed_to)
                await flow.delay(flow.SERVER_KNOBS.backup_agent_poll_delay,
                                 TaskPriority.DEFAULT_ENDPOINT)
                continue
            i = self._applied_idx
            v, mutations = self.log_records[i]
            self._applied_idx += 1
            if v <= self.base_version:
                self.applied_version = max(self.applied_version, v)
                continue
            marker = self.MARKER_SPACE + b"%012d" % i

            async def body(tr, mutations=mutations, marker=marker):
                if await tr.get(marker) is not None:
                    return
                _replay_mutations(tr, mutations)
                tr.set(marker, b"1")
            await run_transaction(self.dest_db, body, max_retries=300)
            self.applied_version = max(self.applied_version, v)
