"""Subspace: a tuple-prefixed partition of the keyspace.

Reference: fdbclient/Subspace.cpp — a fixed key prefix + the tuple
layer: `subspace.pack(t)` prepends the prefix, `unpack` strips it,
`range()` covers everything under the subspace. Directory-style
composition comes from nesting subspaces.
"""

from __future__ import annotations

from typing import Tuple

from ..flow import error
from . import tuple_layer


class Subspace:
    def __init__(self, prefix_tuple: Tuple = (), raw_prefix: bytes = b""):
        self._prefix = raw_prefix + tuple_layer.pack(prefix_tuple)

    @property
    def key(self) -> bytes:
        return self._prefix

    def pack(self, t: Tuple = ()) -> bytes:
        return self._prefix + tuple_layer.pack(t)

    def unpack(self, key: bytes) -> Tuple:
        if not key.startswith(self._prefix):
            raise error("key_outside_legal_range")
        return tuple_layer.unpack(key[len(self._prefix):])

    def contains(self, key: bytes) -> bool:
        return key.startswith(self._prefix)

    def range(self, t: Tuple = ()) -> Tuple[bytes, bytes]:
        p = self._prefix + tuple_layer.pack(t)
        return p + b"\x00", p + b"\xff"

    def subspace(self, t: Tuple) -> "Subspace":
        return Subspace((), self.pack(t))

    def __getitem__(self, item) -> "Subspace":
        return self.subspace((item,))
