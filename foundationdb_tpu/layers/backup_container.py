"""Backup containers: the abstract backup target + a blob-store target.

Ref: fdbclient/BackupContainer.actor.cpp (the container file layout —
snapshot files named by version, mutation-log files named by version
range, plus a describable manifest), fdbclient/BlobStore.actor.cpp (the
S3-compatible object client) and fdbclient/HTTP.actor.cpp (its HTTP
layer). The reference's backup URL scheme (`file://...`,
`blobstore://host:port/...`) maps here to container classes behind one
interface:

  MemoryContainer      in-process dict (tests, DR staging)
  DirectoryContainer   real files in a directory (`file://`)
  BlobStoreContainer   HTTP object PUT/GET/DELETE/LIST against a real
                       socket server (`blobstore://`) — the in-repo
                       BlobStoreServer provides the S3-ish endpoint the
                       way the reference expects an external store

Object layout inside a container (ref: BackupContainer's
snapshots/logs/ directory split):

  snapshots/snapshot,<version>        one range-snapshot blob
  logs/log,<begin>,<end>              one mutation-log chunk
  properties/...                      small metadata objects
"""

from __future__ import annotations

import hashlib
import hmac
import json
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import quote, unquote

from . import backup as snapshot_backup
from . import backup_agent as agent_mod

# Shared IThreadPool for blocking blob IO on WALL-CLOCK schedulers
# (ref: the eio pool behind AsyncFileEIO — the reference never runs
# blocking network IO on the Net2 loop). One pool per scheduler: the
# reactor actor dies with its loop, so a new run loop lazily gets a
# fresh pool. The deterministic simulator never uses it — pool threads
# would break determinism, and the in-sim blob server answers fast.
_blob_pool = None
_blob_pool_sched = None


def _offload(fn, *args):
    """A flow Future running fn on the shared blob pool, or None when
    the caller should just run it inline (no scheduler, or a virtual
    one)."""
    from ..flow.scheduler import _tls
    s = _tls.current
    if s is None or s.virtual:
        return None
    global _blob_pool, _blob_pool_sched
    if _blob_pool is None or _blob_pool_sched is not s:
        from ..flow.threadpool import ThreadPool
        if _blob_pool is not None:
            # a NEW run loop replaced the one this pool's reactor lived
            # on: stop its worker threads and error its outstanding
            # futures instead of leaking both per scheduler generation
            try:
                _blob_pool.close()
            except Exception:  # noqa: BLE001 — old loop already gone
                pass
        _blob_pool = ThreadPool(n_threads=2, name="blobio")
        _blob_pool.start()
        _blob_pool_sched = s
    return _blob_pool.run(fn, *args)


class BackupContainer:
    """Object-store surface every backup target implements (ref:
    IBackupContainer)."""

    async def arun(self, fn, *args):
        """Run a blocking container operation from a flow actor without
        stalling the loop (ADVICE r5: blob retry backoff blocked the
        whole scheduler): wall-clock schedulers ship the call — wire
        attempts AND backoff sleeps — to the blob IThreadPool; the
        deterministic simulator calls inline (its retry backoff skips
        the wall sleep instead, see BlobStoreContainer._retry_backoff).
        Pool-run exceptions surface as io_error (the original rides the
        ThreadPoolTaskError trace)."""
        fut = _offload(fn, *args)
        if fut is None:
            return fn(*args)
        return await fut

    def put_object(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def get_object(self, name: str) -> Optional[bytes]:
        raise NotImplementedError

    def list_objects(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def delete_object(self, name: str) -> None:
        raise NotImplementedError

    # -- the backup file layout (shared by every target) ----------------
    def store_snapshot(self, blob: bytes, version: int) -> str:
        name = f"snapshots/snapshot,{version:020d}"
        self.put_object(name, blob)
        return name

    def store_log(self, blob: bytes, begin: int, end: int) -> str:
        name = f"logs/log,{begin:020d},{end:020d}"
        self.put_object(name, blob)
        return name

    def describe(self) -> dict:
        """Manifest view (ref: BackupContainer describeBackup):
        snapshot versions + contiguous log coverage + restorability."""
        snaps = sorted(int(n.rsplit(",", 1)[1])
                       for n in self.list_objects("snapshots/"))
        logs = sorted(tuple(map(int, n.split(",")[1:]))
                      for n in self.list_objects("logs/"))
        max_restorable = None
        if snaps:
            max_restorable = snaps[-1]
            cursor = snaps[-1]
            for b, e in logs:
                # a chunk named (b, e] certifies versions strictly
                # above b only — contiguity requires b <= cursor
                if b <= cursor and e > cursor:
                    cursor = e
            max_restorable = cursor
        return {"snapshot_versions": snaps, "log_ranges": logs,
                "max_restorable_version": max_restorable}

    def latest_restorable(self, to_version: Optional[int] = None
                          ) -> Tuple[bytes, list, int]:
        """The snapshot blob + ordered log records needed to restore to
        `to_version` (default: the newest restorable point). Raises
        ValueError when the container cannot reach that version."""
        d = self.describe()
        snaps = d["snapshot_versions"]
        if not snaps:
            raise ValueError("container holds no snapshot")
        target = to_version if to_version is not None \
            else d["max_restorable_version"]
        base = None
        for v in snaps:
            if v <= target:
                base = v
        if base is None:
            raise ValueError(
                f"no snapshot at or below target version {target}")
        blob = self.get_object(f"snapshots/snapshot,{base:020d}")
        records: list = []
        covered = base
        for b, e in sorted(tuple(map(int, n.split(",")[1:]))
                           for n in self.list_objects("logs/")):
            if e <= covered or b > target:
                continue
            if b > covered and covered < target:
                # a hole below the target makes it unreachable
                break
            chunk = self.get_object(f"logs/log,{b:020d},{e:020d}")
            _bv, recs = agent_mod.read_log(chunk)
            # clip to (covered, target]: overlapping chunks (e.g. two
            # save_to() calls) must not replay a record twice
            records.extend((v, ms) for v, ms in recs
                           if covered < v <= target and v > base)
            covered = max(covered, e)
        if covered < target:
            raise ValueError(
                f"log coverage ends at {covered}, target {target}")
        return blob, records, target


class MemoryContainer(BackupContainer):
    def __init__(self):
        self._objects: Dict[str, bytes] = {}

    def put_object(self, name: str, data: bytes) -> None:
        self._objects[name] = bytes(data)

    def get_object(self, name: str) -> Optional[bytes]:
        return self._objects.get(name)

    def list_objects(self, prefix: str = "") -> List[str]:
        return sorted(n for n in self._objects if n.startswith(prefix))

    def delete_object(self, name: str) -> None:
        self._objects.pop(name, None)


class DirectoryContainer(BackupContainer):
    """`file://` target: objects are real files under a directory."""

    def __init__(self, root: str):
        import os
        self._root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        import os
        # object names map to REAL subdirectories; non-canonical names
        # (empty/./.. segments) are rejected rather than normalized so
        # distinct names can never collide on disk
        parts = name.split("/")
        if not parts or any(p in ("", ".", "..") for p in parts):
            raise ValueError(f"non-canonical object name: {name!r}")
        return os.path.join(self._root, *parts)

    def put_object(self, name: str, data: bytes) -> None:
        import os
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get_object(self, name: str) -> Optional[bytes]:
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def list_objects(self, prefix: str = "") -> List[str]:
        import os
        out = []
        for dirpath, _dirs, files in os.walk(self._root):
            rel = os.path.relpath(dirpath, self._root)
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                name = fn if rel == "." else f"{rel}/{fn}".replace(
                    os.sep, "/")
                if name.startswith(prefix):
                    out.append(name)
        return sorted(out)

    def delete_object(self, name: str) -> None:
        import os
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass


# ---------------------------------------------------------------------
# blobstore:// — HTTP object store over real sockets
# ---------------------------------------------------------------------

class _BlobHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    store: Dict[str, bytes] = {}
    lock = threading.Lock()
    #: access-key -> secret; empty dict = unauthenticated server
    secrets: Dict[str, str] = {}
    uploads: Dict[str, Dict[int, bytes]] = {}
    upload_names: Dict[str, str] = {}
    completed_uploads: Dict[str, str] = {}   # uploadId -> object name

    def log_message(self, *a):   # no stderr noise in tests
        pass

    def _split(self) -> Tuple[str, Dict[str, str]]:
        from urllib.parse import parse_qsl
        path, _, query = self.path.partition("?")
        return (unquote(path.lstrip("/")),
                dict(parse_qsl(query, keep_blank_values=True)))

    def _authorized(self, verb: str) -> bool:
        """HMAC request auth (ref: BlobStore.actor.cpp setAuthHeaders —
        S3 V2 shape: sign (verb, date, resource) with the account
        secret; a date outside the replay window is rejected even with
        a valid signature)."""
        if not self.secrets:
            return True
        auth = self.headers.get("Authorization", "")
        date = self.headers.get("X-FDBTPU-Date", "")
        if not auth.startswith("FDBTPU ") or ":" not in auth[7:]:
            return False
        key, _, sig = auth[7:].partition(":")
        secret = self.secrets.get(key)
        if secret is None:
            return False
        try:
            then = float(date)
        except ValueError:
            return False
        from ..flow import SERVER_KNOBS
        if abs(time.time() - then) > SERVER_KNOBS.blobstore_auth_window:
            return False
        want = _sign(secret, verb, date, self.path)
        return hmac.compare_digest(sig, want)

    def _deny(self) -> None:
        # drain the request body first: HTTP/1.1 keep-alive parses the
        # unread body as the next request line otherwise
        length = int(self.headers.get("Content-Length", 0))
        if length:
            self.rfile.read(length)
        self.send_response(403)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _ok(self, body: bytes = b"", status: int = 200,
            ctype: str = "application/octet-stream") -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def do_PUT(self):
        if not self._authorized("PUT"):
            return self._deny()
        name, q = self._split()
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        if "uploadId" in q and "partNumber" in q:
            # one part of a multipart upload (ref: S3 UploadPart)
            with self.lock:
                parts = self.uploads.get(q["uploadId"])
                if parts is None or self.upload_names.get(
                        q["uploadId"]) != name:
                    return self._ok(status=404)
                parts[int(q["partNumber"])] = data
            return self._ok()
        with self.lock:
            self.store[name] = data
        self._ok()

    def do_POST(self):
        if not self._authorized("POST"):
            return self._deny()
        name, q = self._split()
        length = int(self.headers.get("Content-Length", 0))
        self.rfile.read(length)
        if "uploads" in q:
            # initiate multipart (ref: S3 CreateMultipartUpload)
            uid = uuid.uuid4().hex
            with self.lock:
                self.uploads[uid] = {}
                self.upload_names[uid] = name
                # bounded in-flight uploads, oldest evicted (ADVICE r5:
                # a client dying between initiate and abort/complete —
                # including a failed abort-on-exception — leaked its
                # parts forever; mirror the completed_uploads cap. An
                # evicted-but-live upload's later part PUTs get 404 and
                # the client's retry budget surfaces the failure.)
                while len(self.uploads) > 256:
                    old = next(iter(self.uploads))
                    self.uploads.pop(old, None)
                    self.upload_names.pop(old, None)
            return self._ok(json.dumps({"uploadId": uid}).encode(),
                            ctype="application/json")
        if "uploadId" in q:
            # complete: assemble parts in part-number order; the object
            # appears atomically only now. IDEMPOTENT on retry: a
            # client whose first complete succeeded but whose response
            # was lost must get 200, not 404 (ref:
            # CompleteMultipartUpload semantics the retry layer assumes)
            with self.lock:
                owner = self.upload_names.get(q["uploadId"])
                if owner is not None and owner != name:
                    return self._ok(status=404)   # wrong object name
                parts = self.uploads.pop(q["uploadId"], None)
                self.upload_names.pop(q["uploadId"], None)
                if parts is None:
                    if self.completed_uploads.get(q["uploadId"]) == name:
                        return self._ok()
                    return self._ok(status=404)
                self.store[name] = b"".join(
                    parts[i] for i in sorted(parts))
                self.completed_uploads[q["uploadId"]] = name
                # retry memory, bounded: only recent completions need
                # the idempotent answer
                while len(self.completed_uploads) > 256:
                    self.completed_uploads.pop(
                        next(iter(self.completed_uploads)))
            return self._ok()
        self._ok(status=400)

    def do_GET(self):
        if not self._authorized("GET"):
            return self._deny()
        name, q = self._split()
        if "list" in q:
            prefix = q["list"]
            with self.lock:
                names = sorted(n for n in self.store
                               if n.startswith(prefix))
            return self._ok(json.dumps(names).encode(),
                            ctype="application/json")
        with self.lock:
            data = self.store.get(name)
        if data is None:
            return self._ok(status=404)
        self._ok(data)

    def do_DELETE(self):
        if not self._authorized("DELETE"):
            return self._deny()
        name, q = self._split()
        with self.lock:
            if "uploadId" in q:     # abort multipart (name must match)
                if self.upload_names.get(q["uploadId"]) == name:
                    self.uploads.pop(q["uploadId"], None)
                    self.upload_names.pop(q["uploadId"], None)
            else:
                self.store.pop(name, None)
        self._ok()


def _sign(secret: str, verb: str, date: str, resource: str) -> str:
    msg = "\n".join((verb, date, resource)).encode()
    return hmac.new(secret.encode(), msg, hashlib.sha256).hexdigest()


class BlobStoreServer:
    """An S3-shaped object server on a real socket (the endpoint the
    reference's BlobStore client talks to): per-request HMAC auth,
    multipart uploads assembled atomically at completion, prefix
    listing. Each instance has an isolated object namespace."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 secrets: Optional[Dict[str, str]] = None):
        handler = type("Handler", (_BlobHandler,),
                       {"store": {}, "lock": threading.Lock(),
                        "secrets": dict(secrets or {}),
                        "uploads": {}, "upload_names": {},
                        "completed_uploads": {}})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)


class BlobStoreContainer(BackupContainer):
    """HTTP client side (ref: BlobStore.actor.cpp doRequest over
    HTTP.actor.cpp): every request retries transient failures
    (connection errors, 5xx) with exponential backoff under a bounded
    try budget; requests are HMAC-signed when credentials are given;
    large objects upload in parts, each part retried independently,
    and the object appears only at completion."""

    def __init__(self, host: str, port: int, timeout: float = None,
                 key: str = "", secret: str = ""):
        from ..flow import SERVER_KNOBS
        if timeout is None:
            timeout = SERVER_KNOBS.blobstore_request_timeout
        self.host, self.port, self.timeout = host, port, timeout
        self.key, self.secret = key, secret

    def _conn(self):
        import http.client
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    def _headers(self, verb: str, path: str) -> Dict[str, str]:
        if not self.key:
            return {}
        date = repr(time.time())
        return {"X-FDBTPU-Date": date,
                "Authorization": "FDBTPU %s:%s" % (
                    self.key, _sign(self.secret, verb, date, path))}

    @staticmethod
    def _retry_backoff(seconds: float) -> None:
        """Backoff between wire attempts. On ANY flow scheduler's
        thread a time.sleep stalls the whole run loop (ADVICE r5: up to
        ~4s of cumulative scheduler stall per down endpoint — and on a
        virtual scheduler the sleep does not even advance simulated
        time), so the retry proceeds immediately there: each attempt
        stays bounded by the connection timeout, so a down endpoint
        costs tries x timeout, never an added backoff stall. Off the
        loop — tools, and pure container IO shipped to the blob
        IThreadPool via BackupContainer.arun — the backoff really
        waits."""
        from ..flow.scheduler import _tls
        if _tls.current is not None:
            return
        time.sleep(seconds)

    def _request(self, verb: str, path: str, body: bytes = b""):
        """One logical request = up to BLOBSTORE_REQUEST_TRIES wire
        attempts; connection failures and 5xx retry with exponential
        backoff, 4xx and 404 do not (they are answers, not weather)."""
        from ..flow import SERVER_KNOBS
        tries = int(SERVER_KNOBS.blobstore_request_tries)
        backoff = SERVER_KNOBS.blobstore_backoff_min
        last = None
        for attempt in range(tries):
            c = self._conn()
            try:
                c.request(verb, path, body=body,
                          headers=self._headers(verb, path))
                r = c.getresponse()
                data = r.read()
                if r.status >= 500:
                    last = IOError(f"{verb} {path}: HTTP {r.status}")
                else:
                    return r.status, data
            except OSError as e:
                last = e
            finally:
                c.close()
            if attempt + 1 < tries:
                self._retry_backoff(backoff)
                backoff = min(backoff * 2,
                              SERVER_KNOBS.blobstore_backoff_max)
        raise IOError(f"{verb} {path}: retries exhausted ({last})")

    def put_object(self, name: str, data: bytes) -> None:
        from ..flow import SERVER_KNOBS
        path = "/" + quote(name, safe="/,")
        if len(data) > SERVER_KNOBS.blobstore_multipart_threshold:
            return self._put_multipart(name, path, data)
        status, _ = self._request("PUT", path, data)
        if status != 200:
            raise IOError(f"PUT {name}: HTTP {status}")

    def _put_multipart(self, name: str, path: str, data: bytes) -> None:
        from ..flow import SERVER_KNOBS
        part_bytes = int(SERVER_KNOBS.blobstore_multipart_part_bytes)
        status, body = self._request("POST", path + "?uploads")
        if status != 200:
            raise IOError(f"POST {name}?uploads: HTTP {status}")
        uid = json.loads(body)["uploadId"]
        try:
            for i in range(0, len(data), part_bytes):
                status, _ = self._request(
                    "PUT", "%s?partNumber=%d&uploadId=%s"
                    % (path, i // part_bytes, uid),
                    data[i:i + part_bytes])
                if status != 200:
                    raise IOError(f"PUT {name} part: HTTP {status}")
            status, _ = self._request("POST",
                                      "%s?uploadId=%s" % (path, uid))
            if status != 200:
                raise IOError(f"complete {name}: HTTP {status}")
        except BaseException:
            try:
                self._request("DELETE", "%s?uploadId=%s" % (path, uid))
            except IOError:
                pass   # orphaned upload: server-side garbage, not data
            raise

    def get_object(self, name: str) -> Optional[bytes]:
        status, data = self._request("GET", "/" + quote(name, safe="/,"))
        if status == 404:
            return None
        if status != 200:
            raise IOError(f"GET {name}: HTTP {status}")
        return data

    def list_objects(self, prefix: str = "") -> List[str]:
        status, data = self._request("GET",
                                     "/?list=" + quote(prefix, safe=""))
        if status != 200:
            raise IOError(f"LIST {prefix}: HTTP {status}")
        return json.loads(data)

    def delete_object(self, name: str) -> None:
        status, _ = self._request("DELETE", "/" + quote(name, safe="/,"))
        if status != 200:
            raise IOError(f"DELETE {name}: HTTP {status}")


def open_container(url: str) -> BackupContainer:
    """Backup-URL scheme (ref: the reference's backup URLs):
    `file:///path`, `blobstore://host:port`, `memory:`."""
    if url.startswith("file://"):
        return DirectoryContainer(url[len("file://"):])
    if url.startswith("blobstore://"):
        rest = url[len("blobstore://"):].split("/", 1)[0]
        key = secret = ""
        if "@" in rest:
            creds, rest = rest.rsplit("@", 1)
            key, _, secret = creds.partition(":")
        host, port = rest.rsplit(":", 1)
        return BlobStoreContainer(host, int(port), key=key, secret=secret)
    if url == "memory:":
        return MemoryContainer()
    raise ValueError(f"unknown backup container url: {url}")


async def restore_from_container(db, container: BackupContainer,
                                 to_version: Optional[int] = None) -> int:
    """Restore the database from a container: newest snapshot at or
    below the target, then replay its logs (ref: fdbrestore driving
    FileBackupAgent restore from a container). Returns the version the
    database was restored to."""
    blob, records, target = await container.arun(
        container.latest_restorable, to_version)
    log_blob = _records_to_log_blob(records, 0)
    await agent_mod.restore_to_version(db, blob, log_blob, target)
    return target


def _records_to_log_blob(records, base_version: int) -> bytes:
    """Container chunks use THE mutation-log encoder (one format, one
    encoder — backup_agent.encode_log)."""
    return agent_mod.encode_log(records, base_version)
