"""Backup containers: the abstract backup target + a blob-store target.

Ref: fdbclient/BackupContainer.actor.cpp (the container file layout —
snapshot files named by version, mutation-log files named by version
range, plus a describable manifest), fdbclient/BlobStore.actor.cpp (the
S3-compatible object client) and fdbclient/HTTP.actor.cpp (its HTTP
layer). The reference's backup URL scheme (`file://...`,
`blobstore://host:port/...`) maps here to container classes behind one
interface:

  MemoryContainer      in-process dict (tests, DR staging)
  DirectoryContainer   real files in a directory (`file://`)
  BlobStoreContainer   HTTP object PUT/GET/DELETE/LIST against a real
                       socket server (`blobstore://`) — the in-repo
                       BlobStoreServer provides the S3-ish endpoint the
                       way the reference expects an external store

Object layout inside a container (ref: BackupContainer's
snapshots/logs/ directory split):

  snapshots/snapshot,<version>        one range-snapshot blob
  logs/log,<begin>,<end>              one mutation-log chunk
  properties/...                      small metadata objects
"""

from __future__ import annotations

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import quote, unquote

from . import backup as snapshot_backup
from . import backup_agent as agent_mod


class BackupContainer:
    """Object-store surface every backup target implements (ref:
    IBackupContainer)."""

    def put_object(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def get_object(self, name: str) -> Optional[bytes]:
        raise NotImplementedError

    def list_objects(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def delete_object(self, name: str) -> None:
        raise NotImplementedError

    # -- the backup file layout (shared by every target) ----------------
    def store_snapshot(self, blob: bytes, version: int) -> str:
        name = f"snapshots/snapshot,{version:020d}"
        self.put_object(name, blob)
        return name

    def store_log(self, blob: bytes, begin: int, end: int) -> str:
        name = f"logs/log,{begin:020d},{end:020d}"
        self.put_object(name, blob)
        return name

    def describe(self) -> dict:
        """Manifest view (ref: BackupContainer describeBackup):
        snapshot versions + contiguous log coverage + restorability."""
        snaps = sorted(int(n.rsplit(",", 1)[1])
                       for n in self.list_objects("snapshots/"))
        logs = sorted(tuple(map(int, n.split(",")[1:]))
                      for n in self.list_objects("logs/"))
        max_restorable = None
        if snaps:
            max_restorable = snaps[-1]
            cursor = snaps[-1]
            for b, e in logs:
                # a chunk named (b, e] certifies versions strictly
                # above b only — contiguity requires b <= cursor
                if b <= cursor and e > cursor:
                    cursor = e
            max_restorable = cursor
        return {"snapshot_versions": snaps, "log_ranges": logs,
                "max_restorable_version": max_restorable}

    def latest_restorable(self, to_version: Optional[int] = None
                          ) -> Tuple[bytes, list, int]:
        """The snapshot blob + ordered log records needed to restore to
        `to_version` (default: the newest restorable point). Raises
        ValueError when the container cannot reach that version."""
        d = self.describe()
        snaps = d["snapshot_versions"]
        if not snaps:
            raise ValueError("container holds no snapshot")
        target = to_version if to_version is not None \
            else d["max_restorable_version"]
        base = None
        for v in snaps:
            if v <= target:
                base = v
        if base is None:
            raise ValueError(
                f"no snapshot at or below target version {target}")
        blob = self.get_object(f"snapshots/snapshot,{base:020d}")
        records: list = []
        covered = base
        for b, e in sorted(tuple(map(int, n.split(",")[1:]))
                           for n in self.list_objects("logs/")):
            if e <= covered or b > target:
                continue
            if b > covered and covered < target:
                # a hole below the target makes it unreachable
                break
            chunk = self.get_object(f"logs/log,{b:020d},{e:020d}")
            _bv, recs = agent_mod.read_log(chunk)
            # clip to (covered, target]: overlapping chunks (e.g. two
            # save_to() calls) must not replay a record twice
            records.extend((v, ms) for v, ms in recs
                           if covered < v <= target and v > base)
            covered = max(covered, e)
        if covered < target:
            raise ValueError(
                f"log coverage ends at {covered}, target {target}")
        return blob, records, target


class MemoryContainer(BackupContainer):
    def __init__(self):
        self._objects: Dict[str, bytes] = {}

    def put_object(self, name: str, data: bytes) -> None:
        self._objects[name] = bytes(data)

    def get_object(self, name: str) -> Optional[bytes]:
        return self._objects.get(name)

    def list_objects(self, prefix: str = "") -> List[str]:
        return sorted(n for n in self._objects if n.startswith(prefix))

    def delete_object(self, name: str) -> None:
        self._objects.pop(name, None)


class DirectoryContainer(BackupContainer):
    """`file://` target: objects are real files under a directory."""

    def __init__(self, root: str):
        import os
        self._root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        import os
        # object names map to REAL subdirectories; non-canonical names
        # (empty/./.. segments) are rejected rather than normalized so
        # distinct names can never collide on disk
        parts = name.split("/")
        if not parts or any(p in ("", ".", "..") for p in parts):
            raise ValueError(f"non-canonical object name: {name!r}")
        return os.path.join(self._root, *parts)

    def put_object(self, name: str, data: bytes) -> None:
        import os
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get_object(self, name: str) -> Optional[bytes]:
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def list_objects(self, prefix: str = "") -> List[str]:
        import os
        out = []
        for dirpath, _dirs, files in os.walk(self._root):
            rel = os.path.relpath(dirpath, self._root)
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                name = fn if rel == "." else f"{rel}/{fn}".replace(
                    os.sep, "/")
                if name.startswith(prefix):
                    out.append(name)
        return sorted(out)

    def delete_object(self, name: str) -> None:
        import os
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass


# ---------------------------------------------------------------------
# blobstore:// — HTTP object store over real sockets
# ---------------------------------------------------------------------

class _BlobHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    store: Dict[str, bytes] = {}
    lock = threading.Lock()

    def log_message(self, *a):   # no stderr noise in tests
        pass

    def _name(self) -> str:
        return unquote(self.path.lstrip("/"))

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        with self.lock:
            self.store[self._name()] = data
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        name = self._name()
        if name.startswith("?list="):
            prefix = unquote(name[len("?list="):])
            with self.lock:
                names = sorted(n for n in self.store
                               if n.startswith(prefix))
            body = json.dumps(names).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        with self.lock:
            data = self.store.get(name)
        if data is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_DELETE(self):
        with self.lock:
            self.store.pop(self._name(), None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class BlobStoreServer:
    """A minimal S3-shaped object server on a real socket (the endpoint
    the reference's BlobStore client would talk to). Each instance has
    an isolated object namespace."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        handler = type("Handler", (_BlobHandler,),
                       {"store": {}, "lock": threading.Lock()})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)


class BlobStoreContainer(BackupContainer):
    """HTTP client side (ref: BlobStore.actor.cpp doRequest over
    HTTP.actor.cpp — here stdlib http.client over the same wire
    shapes: PUT/GET/DELETE an object, GET ?list= for a prefix)."""

    def __init__(self, host: str, port: int, timeout: float = None):
        if timeout is None:
            from ..flow import SERVER_KNOBS
            timeout = SERVER_KNOBS.blobstore_request_timeout
        self.host, self.port, self.timeout = host, port, timeout

    def _conn(self):
        import http.client
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    def put_object(self, name: str, data: bytes) -> None:
        c = self._conn()
        try:
            c.request("PUT", "/" + quote(name, safe="/,"), body=data)
            r = c.getresponse()
            r.read()
            if r.status != 200:
                raise IOError(f"PUT {name}: HTTP {r.status}")
        finally:
            c.close()

    def get_object(self, name: str) -> Optional[bytes]:
        c = self._conn()
        try:
            c.request("GET", "/" + quote(name, safe="/,"))
            r = c.getresponse()
            data = r.read()
            if r.status == 404:
                return None
            if r.status != 200:
                raise IOError(f"GET {name}: HTTP {r.status}")
            return data
        finally:
            c.close()

    def list_objects(self, prefix: str = "") -> List[str]:
        c = self._conn()
        try:
            c.request("GET", "/?list=" + quote(prefix, safe=""))
            r = c.getresponse()
            data = r.read()
            if r.status != 200:
                raise IOError(f"LIST {prefix}: HTTP {r.status}")
            return json.loads(data)
        finally:
            c.close()

    def delete_object(self, name: str) -> None:
        c = self._conn()
        try:
            c.request("DELETE", "/" + quote(name, safe="/,"))
            r = c.getresponse()
            r.read()
            if r.status != 200:
                raise IOError(f"DELETE {name}: HTTP {r.status}")
        finally:
            c.close()


def open_container(url: str) -> BackupContainer:
    """Backup-URL scheme (ref: the reference's backup URLs):
    `file:///path`, `blobstore://host:port`, `memory:`."""
    if url.startswith("file://"):
        return DirectoryContainer(url[len("file://"):])
    if url.startswith("blobstore://"):
        hostport = url[len("blobstore://"):].split("/", 1)[0]
        host, port = hostport.rsplit(":", 1)
        return BlobStoreContainer(host, int(port))
    if url == "memory:":
        return MemoryContainer()
    raise ValueError(f"unknown backup container url: {url}")


async def restore_from_container(db, container: BackupContainer,
                                 to_version: Optional[int] = None) -> int:
    """Restore the database from a container: newest snapshot at or
    below the target, then replay its logs (ref: fdbrestore driving
    FileBackupAgent restore from a container). Returns the version the
    database was restored to."""
    blob, records, target = container.latest_restorable(to_version)
    log_blob = _records_to_log_blob(records, 0)
    await agent_mod.restore_to_version(db, blob, log_blob, target)
    return target


def _records_to_log_blob(records, base_version: int) -> bytes:
    """Container chunks use THE mutation-log encoder (one format, one
    encoder — backup_agent.encode_log)."""
    return agent_mod.encode_log(records, base_version)
