"""TaskBucket: a persistent task queue stored in the database.

Reference: fdbclient/TaskBucket.actor.cpp — the backup system's
execution framework: tasks are key-space entries claimed by workers
with leases; a crashed worker's lease expires and the task becomes
available again; `is_empty`/`check_active` drive agents. Re-designed to
this framework's async client: add/claim/extend/finish as transactions
on a Subspace, with random claim keys for contention spread.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .. import flow
from . import tuple_layer
from .subspace import Subspace




class Task:
    __slots__ = ("key", "params", "lease_until")

    def __init__(self, key: bytes, params: Dict[str, bytes],
                 lease_until: float):
        self.key = key
        self.params = params
        self.lease_until = lease_until


class TaskBucket:
    def __init__(self, subspace: Subspace, lease: float = None):
        if lease is None:
            lease = flow.SERVER_KNOBS.taskbucket_lease_seconds
        self._available = subspace.subspace(("avail",))
        self._claimed = subspace.subspace(("claimed",))
        self._lease = lease

    async def add(self, tr, params: Dict[str, bytes]) -> bytes:
        """Enqueue a task; returns its id."""
        tid = flow.g_random.random_bytes(12)
        tr.set(self._available.pack((tid,)), _encode_params(params))
        return tid

    async def claim_one(self, tr) -> Optional[Task]:
        """Claim an available task (or reclaim one whose lease
        expired). The claim is transactional: two workers claiming the
        same task conflict at commit and one retries onto another."""
        b, e = self._available.range()
        rows = await tr.get_range(b, e, limit=8)
        for k, v in rows:
            (tid,) = self._available.unpack(k)
            lease_until = flow.now() + self._lease
            tr.clear(k)
            tr.set(self._claimed.pack((tid,)),
                   tuple_layer.pack((lease_until,)) + v)
            return Task(self._claimed.pack((tid,)), _decode_params(v),
                        lease_until)
        # reclaim expired leases (ref: requeuing timed-out tasks)
        b, e = self._claimed.range()
        now = flow.now()
        for k, v in await tr.get_range(b, e, limit=8):
            lease_until, off = tuple_layer._decode_one(v, 0, False)
            if lease_until < now:
                params_blob = v[off:]
                tr.set(k, tuple_layer.pack((now + self._lease,))
                       + params_blob)
                return Task(k, _decode_params(params_blob),
                            now + self._lease)
        return None

    async def extend(self, tr, task: Task) -> None:
        raw = await tr.get(task.key)
        if raw is None:
            raise flow.error("operation_failed")
        _lease, off = tuple_layer._decode_one(raw, 0, False)
        task.lease_until = flow.now() + self._lease
        tr.set(task.key, tuple_layer.pack((task.lease_until,)) + raw[off:])

    async def finish(self, tr, task: Task) -> None:
        tr.clear(task.key)

    async def is_empty(self, tr) -> bool:
        for space in (self._available, self._claimed):
            b, e = space.range()
            if await tr.get_range(b, e, limit=1):
                return False
        return True


def _encode_params(params: Dict[str, bytes]) -> bytes:
    return tuple_layer.pack(tuple(x for kv in sorted(params.items())
                                  for x in kv))


def _decode_params(blob: bytes) -> Dict[str, bytes]:
    flat = tuple_layer.unpack(blob)
    return {flat[i]: flat[i + 1] for i in range(0, len(flat), 2)}
