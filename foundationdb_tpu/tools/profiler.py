"""Transaction-profiling analyzer: read the sampled ClientLogEvent
records back out of the database and report where the time went.

Reference: contrib/transaction_profiling_analyzer.py — the tool that
scans \\xff\\x02/fdbClientInfo/client_latency/, reassembles each
record's chunk run, decodes the client's event stream, and prints the
top offenders. Same shape here: `scan_records` pages the keyspace with
ordinary range reads (chunked records reassemble across page
boundaries; a record missing chunks is SKIPPED and counted, never a
crash), `analyze` folds the event streams into top-N slowest
transactions, per-operation latency histograms, and the hottest
read/written keys, and `format_report` renders the operator view the
cli's `profile analyze` prints.
"""

from __future__ import annotations

import sys
from typing import List, NamedTuple, Optional, Tuple

from ..client.profiling import (CommitEvent, ErrorEvent, GetEvent,
                                GetRangeEvent, GetVersionEvent,
                                decode_events)
from ..flow.latency import LatencyBands
from ..rpc.wire import WireError
from ..server.systemkeys import (CLIENT_LATENCY_END,
                                 CLIENT_LATENCY_PREFIX,
                                 CLIENT_LATENCY_VERSION,
                                 parse_client_latency_key)

SCAN_PAGE_ROWS = 512


class TxnRecord(NamedTuple):
    """One reassembled profile record."""
    start_ts: float           # seconds (sim clock)
    rec_id: str
    events: Tuple[tuple, ...]


def _finish_group(records: List[TxnRecord], stats: dict, meta,
                  chunks: dict) -> None:
    """Close out one (start_ts, rec_id) chunk run: reassemble when
    complete, otherwise count the skip."""
    if meta is None:
        return
    start_us, rec_id, num = meta
    if len(chunks) != num or set(chunks) != set(range(1, num + 1)):
        stats["skipped_missing_chunks"] += 1
        return
    blob = b"".join(chunks[i] for i in range(1, num + 1))
    try:
        events = decode_events(blob)
    except (WireError, IndexError, ValueError):
        stats["skipped_undecodable"] += 1
        return
    records.append(TxnRecord(start_us / 1e6, rec_id, events))


async def scan_records(tr, limit_rows: int = 200_000,
                       page_rows: int = SCAN_PAGE_ROWS):
    """-> (records, stats) from one transaction's view of the profiling
    keyspace. `tr` must already be readable for system keys (the
    callers set read_system_keys). Chunk runs are contiguous by key
    order, so reassembly is a single pass with carry across pages — a
    record whose chunks straddle a page boundary reassembles exactly
    like one that doesn't (`page_rows` is a parameter so the tests can
    force the straddle)."""
    stats = {"chunks_seen": 0, "records": 0,
             "skipped_missing_chunks": 0, "skipped_undecodable": 0,
             "skipped_foreign_version": 0}
    records: List[TxnRecord] = []
    meta = None          # (start_us, rec_id, num_chunks) of the open run
    chunks: dict = {}
    begin = CLIENT_LATENCY_PREFIX
    scanned = 0
    while scanned < limit_rows:
        page = await tr.get_range(begin, CLIENT_LATENCY_END,
                                  limit=page_rows, snapshot=True)
        for k, v in page:
            scanned += 1
            parsed = parse_client_latency_key(k)
            if parsed is None:
                continue
            version, start_us, rec_id, chunk, num = parsed
            if version != CLIENT_LATENCY_VERSION:
                stats["skipped_foreign_version"] += 1
                continue
            stats["chunks_seen"] += 1
            if meta != (start_us, rec_id, num):
                _finish_group(records, stats, meta, chunks)
                meta, chunks = (start_us, rec_id, num), {}
            chunks[chunk] = v
        if len(page) < page_rows:
            break
        begin = page[-1][0] + b"\x00"
    _finish_group(records, stats, meta, chunks)
    stats["records"] = len(records)
    return records, stats


# -- analysis ------------------------------------------------------------

_OP_NAMES = {GetVersionEvent: "grv", GetEvent: "get",
             GetRangeEvent: "get_range", CommitEvent: "commit"}


def _txn_latency(rec: TxnRecord) -> float:
    """A transaction's cost: the sum of its operation latencies (the
    events carry per-op latency, not wall extent — retries interleave
    with user code the client can't see)."""
    return sum(getattr(e, "latency", 0.0) for e in rec.events)


def analyze(records: List[TxnRecord], top_n: int = 10) -> dict:
    """Fold decoded records into the operator report: outcome counts,
    top-N slowest transactions, per-op latency histograms, and the
    hottest read/written keys."""
    per_op = {name: LatencyBands(name) for name in _OP_NAMES.values()}
    read_keys: dict = {}
    written_keys: dict = {}
    committed = conflicted = errored = 0
    scored = []
    for rec in records:
        verdicts = [e.verdict for e in rec.events
                    if isinstance(e, CommitEvent)]
        if "conflicted" in verdicts:
            conflicted += 1
        if "committed" in verdicts:
            committed += 1
        if any(isinstance(e, ErrorEvent) for e in rec.events):
            errored += 1
        scored.append((_txn_latency(rec), rec))
        for e in rec.events:
            op = _OP_NAMES.get(type(e))
            if op is not None:
                per_op[op].record(e.latency)
            if isinstance(e, GetEvent):
                read_keys[e.key] = read_keys.get(e.key, 0) + 1
            elif isinstance(e, GetRangeEvent):
                read_keys[e.begin] = read_keys.get(e.begin, 0) + 1
            elif isinstance(e, CommitEvent):
                for b, _e2 in e.write_ranges:
                    written_keys[b] = written_keys.get(b, 0) + 1
    scored.sort(key=lambda p: (-p[0], p[1].rec_id))

    def _top(d: dict) -> list:
        return sorted(d.items(), key=lambda kv: (-kv[1], kv[0]))[:top_n]

    return {
        "records": len(records),
        "committed": committed,
        "conflicted": conflicted,
        "errored": errored,
        "slowest": [{
            "rec_id": rec.rec_id, "start_ts": round(rec.start_ts, 6),
            "latency": round(score, 6), "events": len(rec.events),
            "verdict": next((e.verdict for e in rec.events
                             if isinstance(e, CommitEvent)), "none"),
        } for score, rec in scored[:top_n]],
        "per_op": {name: bands.snapshot()
                   for name, bands in per_op.items() if bands.total},
        "hottest_keys": [{"key": k.hex(), "reads": n}
                         for k, n in _top(read_keys)],
        "hottest_written": [{"key": k.hex(), "writes": n}
                            for k, n in _top(written_keys)],
    }


def format_report(analysis: dict, stats: Optional[dict] = None) -> str:
    lines = [f"Transaction profile: {analysis['records']} records "
             f"({analysis['committed']} committed, "
             f"{analysis['conflicted']} conflicted, "
             f"{analysis['errored']} errored)"]
    if stats:
        lines.append(
            f"  chunks={stats['chunks_seen']} "
            f"skipped_missing={stats['skipped_missing_chunks']} "
            f"skipped_undecodable={stats['skipped_undecodable']}")
    if analysis["slowest"]:
        lines.append("Slowest transactions:")
        for row in analysis["slowest"]:
            lines.append(
                f"  {row['latency']:<10g} {row['verdict']:<10} "
                f"events={row['events']:<4} id={row['rec_id']}")
    if analysis["per_op"]:
        lines.append("Per-op latency:")
        for op, snap in sorted(analysis["per_op"].items()):
            lines.append(
                f"  {op:<10} n={snap['total']:<6} "
                f"sum={snap['sum_seconds']:<10g} "
                f"max={snap['max_seconds']:<10g}")
    if analysis["hottest_keys"]:
        lines.append("Hottest read keys:")
        for row in analysis["hottest_keys"]:
            lines.append(f"  {row['reads']:>6}x  {row['key']}")
    if analysis["hottest_written"]:
        lines.append("Hottest written keys:")
        for row in analysis["hottest_written"]:
            lines.append(f"  {row['writes']:>6}x  {row['key']}")
    return "\n".join(lines)


async def profile_analysis(db, top_n: int = 10):
    """One-shot scan + analyze over a Database handle -> (analysis,
    stats). The scan runs in a read-only, UNSAMPLED system-keys
    transaction — the analyzer must not profile its own scan."""
    from ..client.profiling import run_unsampled

    async def body(tr):
        tr.set_option("read_system_keys")
        return await scan_records(tr)

    records, stats = await run_unsampled(db, body)
    return analyze(records, top_n=top_n), stats


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    connect = None
    top_n = 10
    while argv:
        a = argv.pop(0)
        if a == "--connect":
            connect = argv.pop(0)
        elif a == "--top":
            top_n = int(argv.pop(0))
    if connect is None:
        print("usage: profiler --connect host:port [--top N]",
              file=sys.stderr)
        return 2
    from ..client.remote import RemoteCluster
    host, _, port = connect.partition(":")
    remote = RemoteCluster(host or "127.0.0.1", int(port))
    try:
        analysis, stats = remote.call(
            profile_analysis(remote.db, top_n=top_n))
        print(format_report(analysis, stats))
        return 0
    finally:
        remote.close()


if __name__ == "__main__":
    sys.exit(main())
