"""simprof: the sim-performance attribution tool (ROADMAP item 6 —
"profile the run loop before refactoring it").

Runs a NAMED storm with the SIM_TASK_STATS plane armed (per-task
run-loop accounting, per-TaskPriority-band rollup, per-message-type
network accounting, sampled coroutine stacks) and emits:

  - a text report (who burns the wall clock: task table, priority
    bands, message types, wall-vs-sim budget),
  - a JSON report (the machine-readable version, for CI artifacts),
  - optionally a flamegraph-ready `.folded` collapsed-stack file
    (`--folded out.folded` -> flamegraph.pl / speedscope).

`--compare SIMPERF_r01.json` checks the run against a committed
baseline and exits non-zero when a storm's wall time regressed past
the tolerance — the regression gate every sim-scale PR runs against.
Wall baselines are machine-dependent, so the gate is a RATIO
(default: fail at > 2x the recorded wall seconds); the deterministic
columns (tasks_run, messages_sent) are reported as drift, never
failed, because code changes move them legitimately.

    python -m foundationdb_tpu.tools.simprof --storm open_loop
    python -m foundationdb_tpu.tools.simprof --storms open_loop,overload
    python -m foundationdb_tpu.tools.simprof --all --compare SIMPERF_r02.json
    python -m foundationdb_tpu.tools.simprof --all --write-baseline SIMPERF_r03.json
    python -m foundationdb_tpu.tools.simprof --storm overload_million
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

JSON_REPORT_PATH = "/tmp/_simprof_report.json"
TEXT_REPORT_PATH = "/tmp/_simprof_report.txt"

#: the named storm set. `baseline: True` rows form the rNN baseline
#: set (the acceptance floor is >= 3 named storms). `clients`,
#: `multiplex` and `horizon` parameterize the overload-family storms
#: (overridable from the command line with --clients / --multiplex /
#: --horizon, so any cell — including the 10^6-client one — is
#: reproducible from the same entry point CI uses).
STORMS = {
    "open_loop": {"baseline": True, "seed": 6262,
                  "help": "seeded Zipfian open-loop burst (QoS storm)"},
    "contention": {"baseline": True, "seed": 8383,
                   "help": "hot-key read-modify-write contention storm"},
    "overload": {"baseline": True, "seed": 9393,
                 "help": "10^4-client open-loop overload storm"},
    "overload_million": {"baseline": True, "seed": 11311,
                         "clients": 1_000_000, "multiplex": 600,
                         "horizon": 10.0,
                         "help": "10^6-distinct-client overload storm, "
                                 "10x horizon, multiplexed arrivals"},
    "chaos_partition": {"baseline": False, "seed": 101,
                        "help": "partition_minority ChaosStorm "
                                "(traffic + faults + heal + verify)"},
}


def _arm(cluster) -> None:
    """Arm the whole plane on a freshly built cluster (SimCluster
    re-initializes knobs in __init__, so the knob is set afterwards
    and the scheduler/network are armed directly)."""
    from .. import flow
    flow.SERVER_KNOBS.set("sim_task_stats", 1)
    cluster.sched.start_task_stats()
    cluster.net.arm_message_stats()
    cluster.sched.start_profiler(sample_every=16)


def run_storm(name: str, seed: Optional[int] = None,
              duration: float = 3.0, clients: Optional[int] = None,
              horizon: Optional[float] = None,
              multiplex: Optional[int] = None) -> dict:
    """One named storm under the armed plane -> the simprof report
    dict (storm stats incl. sim_perf, the FULL task/message tables,
    and the sampled collapsed stacks). `clients`/`horizon`/`multiplex`
    override the overload-family population size, duration multiplier
    and clients-per-arrival block (defaults come from the STORMS row),
    so any population/horizon cell is one command line. NOTE: the
    `overload` cell keeps ISSUE 10's tightened (collapse-shape)
    ratekeeper knobs; the committed 10^6 baseline is the HEALTHY
    `overload_million` cell — reproduce it by NAME (overrides apply to
    it too):

        python -m foundationdb_tpu.tools.simprof --storm overload_million
    """
    from .. import flow
    from ..server import SimCluster
    from ..server.workloads import (ChaosStorm, ContentionStorm,
                                    OpenLoopStorm, OverloadStorm)
    if name not in STORMS:
        raise ValueError(f"unknown storm {name!r}; known: "
                         f"{sorted(STORMS)}")
    cfg = STORMS[name]
    if seed is None:
        seed = cfg["seed"]
    if clients is None:
        clients = cfg.get("clients", 10_000)
    if multiplex is None:
        multiplex = cfg.get("multiplex", 1)
    if horizon is None:
        horizon = cfg.get("horizon", 1.0)

    if name == "chaos_partition":
        cluster = SimCluster(seed=seed, durable=True, n_workers=6)
        _arm(cluster)
        dbs = [cluster.client(f"sp{i}") for i in range(3)]
        storm = ChaosStorm(cluster, dbs, flow.g_random,
                           "partition_minority", duration=duration + 2.0)

        async def main():
            rep = await storm.run()
            return {k: rep[k] for k in ("storm", "recovery_seconds",
                                        "sim_perf")}
    else:
        overload_like = name.startswith("overload")
        cluster = SimCluster(seed=seed, durable=True,
                             n_proxies=2 if overload_like else 1)
        _arm(cluster)
        if name == "overload":
            # the 10^4 cell keeps the tightened ratekeeper (the
            # collapse-shape storm ISSUE 10 measured); the 10^6 cell
            # runs a HEALTHY cluster — its question is simulator
            # scale (can nightly afford a million clients at a 10x
            # horizon), not admission-control physics
            flow.SERVER_KNOBS.set("rk_target_storage_queue_bytes", 4000)
            flow.SERVER_KNOBS.set("rk_spring_storage_queue_bytes", 1000)
        dbs = [cluster.client(f"sp{i}") for i in range(6)]
        if name == "open_loop":
            storm = OpenLoopStorm(dbs, flow.g_random, duration=duration,
                                  rate=80.0, burst_rate=500.0,
                                  burst_start=1.0, burst_len=1.0,
                                  max_inflight=256)
        elif name == "contention":
            storm = ContentionStorm(dbs, flow.g_random,
                                    duration=duration, rate=120.0)
        else:
            storm = OverloadStorm(dbs, flow.g_random,
                                  duration=duration * horizon,
                                  fair_rate=60.0, abusive_rate=240.0,
                                  n_clients=clients,
                                  clients_per_arrival=multiplex)

        async def main():
            return {"storm": await storm.run()}

    try:
        out = cluster.run(main(), timeout_time=900)
        stats = out["storm"]
        sim_perf = out.get("sim_perf") or stats["sim_perf"]
        samples = cluster.sched.stop_profiler()
        folded = cluster.sched.profile_folded()
        report = {
            "storm": name,
            "seed": seed,
            "sim_perf": sim_perf,
            "stats": {k: v for k, v in stats.items()
                      if k not in ("sim_perf",)},
            "task_stats": cluster.sched.task_stats_report(),
            "message_stats": cluster.net.message_stats_report(),
            "profile_top": samples[:20],
            "folded": folded,
        }
        if "recovery_seconds" in out:
            report["recovery_seconds"] = out["recovery_seconds"]
        return report
    finally:
        from .. import flow as _flow
        _flow.reset_server_knobs(randomize=False)
        cluster.shutdown()


def baseline_row(report: dict) -> dict:
    """The comparable slice of one storm report (what the committed
    SIMPERF_rNN.json keeps per storm)."""
    sp = report["sim_perf"]
    return {
        "seed": report["seed"],
        "sim_seconds": sp["sim_seconds"],
        "wall_seconds": sp["wall_seconds"],
        "sim_per_wall": sp["sim_per_wall"],
        "tasks_run": sp["tasks_run"],
        "tasks_per_wall_sec": sp["tasks_per_wall_sec"],
        "messages_sent": sp.get("messages_sent"),
    }


def compare_reports(current: dict, baseline: dict,
                    tolerance: float = 2.0) -> tuple:
    """-> (regressions, lines). `current` and `baseline` both map
    storm name -> baseline_row-shaped dict. A storm regresses when its
    wall_seconds exceed tolerance x the baseline's; deterministic
    drift (tasks_run, messages_sent) is reported, never failed."""
    regressions: List[str] = []
    lines: List[str] = []
    for name in sorted(baseline):
        base = baseline[name]
        cur = current.get(name)
        if cur is None:
            lines.append(f"  {name:<16} (not run this round)")
            continue
        if cur.get("seed") != base.get("seed"):
            # a different seed is a different workload shape: gating
            # its wall time against this baseline would report seed
            # mismatch as "regression" — say so and skip instead
            lines.append(
                f"  {name:<16} seed {cur.get('seed')} != baseline "
                f"seed {base.get('seed')} — not comparable, skipped")
            continue
        wall, bwall = cur["wall_seconds"], base["wall_seconds"]
        ratio = wall / max(bwall, 1e-9)
        verdict = "ok"
        if ratio > tolerance:
            verdict = "REGRESSED"
            regressions.append(
                f"{name}: wall {wall:.3f}s vs baseline {bwall:.3f}s "
                f"({ratio:.2f}x > {tolerance:.2f}x tolerance)")
        lines.append(
            f"  {name:<16} wall {wall:>8.3f}s vs {bwall:>8.3f}s "
            f"({ratio:>5.2f}x)  sim/wall {cur['sim_per_wall']:>7.2f} "
            f"vs {base['sim_per_wall']:>7.2f}  "
            f"tasks {cur['tasks_run']} vs {base['tasks_run']}  "
            f"[{verdict}]")
    return regressions, lines


def format_report(report: dict, top_k: int = 10) -> str:
    """One storm report as the operator-facing text block."""
    sp = report["sim_perf"]
    lines = [
        f"== simprof: {report['storm']} (seed {report['seed']}) ==",
        f"sim {sp['sim_seconds']}s in wall {sp['wall_seconds']}s "
        f"(sim/wall {sp['sim_per_wall']}x) — {sp['tasks_run']} steps, "
        f"{sp['tasks_per_wall_sec']}/wall-sec",
    ]
    ts = report.get("task_stats") or {}
    if ts.get("tasks"):
        lines.append("task families by busy time:")
        for r in ts["tasks"][:top_k]:
            lines.append(f"  {r['task']:<32} steps={r['steps']:<9}"
                         f" busy={r['busy_us'] / 1e6:<9.4f}s"
                         f" max={r['max_us']:.0f}us")
        if ts.get("dropped_names"):
            lines.append(f"  ({ts['dropped_names']} folds in '(other)': "
                         f"table bound hit)")
    if ts.get("bands"):
        lines.append("priority bands: " + "  ".join(
            f"{b['band']}={b['busy_us'] / 1e6:.4f}s"
            for b in ts["bands"][:top_k]))
    ms = report.get("message_stats") or {}
    if ms.get("types"):
        lines.append("message types:")
        for r in ms["types"][:top_k]:
            lines.append(f"  {r['type']:<32} {r['count']}")
        lines.append(f"  total sent={ms.get('messages_sent')} "
                     f"timers_now={ms.get('timers_now')}")
    prof = report.get("profile_top") or ()
    if prof:
        lines.append("sampled stacks (top):")
        for e in prof[:5]:
            lines.append(f"  {e['samples']:>5}  {e['task']}  {e['stack']}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    storms: List[str] = []
    seed = None
    duration = 3.0
    compare_path = None
    write_baseline = None
    tolerance = None     # None = baseline file's (or 2.0)
    json_path = JSON_REPORT_PATH
    text_path = TEXT_REPORT_PATH
    folded_path = None
    clients = None
    horizon = None
    multiplex = None
    while argv:
        a = argv.pop(0)
        if a == "--storm":
            storms.append(argv.pop(0))
        elif a == "--storms":
            # comma-separated filter, e.g. --storms open_loop,overload
            storms.extend(s for s in argv.pop(0).split(",") if s)
        elif a == "--all":
            storms = [n for n, s in STORMS.items() if s["baseline"]]
        elif a == "--seed":
            seed = int(argv.pop(0))
        elif a == "--duration":
            duration = float(argv.pop(0))
        elif a == "--clients":
            clients = int(argv.pop(0))
        elif a == "--horizon":
            horizon = float(argv.pop(0))
        elif a == "--multiplex":
            multiplex = int(argv.pop(0))
        elif a == "--compare":
            compare_path = argv.pop(0)
        elif a == "--write-baseline":
            write_baseline = argv.pop(0)
        elif a == "--tolerance":
            tolerance = float(argv.pop(0))
        elif a == "--json":
            json_path = argv.pop(0)
        elif a == "--report":
            text_path = argv.pop(0)
        elif a == "--folded":
            folded_path = argv.pop(0)
        elif a in ("-h", "--help"):
            print(__doc__)
            print("storms:")
            for n, s in STORMS.items():
                print(f"  {n:<16} {s['help']}"
                      + ("  [baseline set]" if s["baseline"] else ""))
            return 0
        else:
            print(f"unknown argument {a!r} (try --help)",
                  file=sys.stderr)
            return 2
    if not storms:
        storms = [n for n, s in STORMS.items() if s["baseline"]]

    unknown = [n for n in storms if n not in STORMS]
    if unknown:
        print(f"unknown storms {unknown} (known: {sorted(STORMS)})",
              file=sys.stderr)
        return 2

    reports = {}
    blocks = []
    for name in storms:
        rep = run_storm(name, seed=seed, duration=duration,
                        clients=clients, horizon=horizon,
                        multiplex=multiplex)
        reports[name] = rep
        block = format_report(rep)
        blocks.append(block)
        print(block)

    with open(json_path, "w") as fh:
        json.dump({n: {k: v for k, v in r.items() if k != "folded"}
                   for n, r in reports.items()},
                  fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    with open(text_path, "w") as fh:
        fh.write("\n\n".join(blocks) + "\n")
    if folded_path:
        with open(folded_path, "w") as fh:
            fh.write("\n".join(r["folded"] for r in reports.values()
                               if r.get("folded")) + "\n")
    print(f"\nreports: {text_path} {json_path}"
          + (f" {folded_path}" if folded_path else ""))

    if write_baseline:
        import os.path
        import re
        # SIMPERF_rNN.json names the round (the documented convention)
        m = re.search(r"[_-](r\d+)", os.path.basename(write_baseline))
        doc = {"round": m.group(1) if m else "r01",
               "tolerance": tolerance if tolerance is not None else 2.0,
               "note": "simprof wall-time baselines; compare is a "
                       "ratio gate (machine-dependent absolute walls)",
               "storms": {n: baseline_row(r)
                          for n, r in reports.items()}}
        with open(write_baseline, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"baseline written: {write_baseline}")

    if compare_path:
        with open(compare_path) as fh:
            base = json.load(fh)
        # explicit --tolerance overrides the file's; otherwise the
        # baseline's recorded tolerance (default 2.0) gates
        tol = (tolerance if tolerance is not None
               else float(base.get("tolerance", 2.0)))
        regressions, lines = compare_reports(
            {n: baseline_row(r) for n, r in reports.items()},
            base["storms"], tolerance=tol)
        print(f"\ncompare vs {compare_path} "
              f"(round {base.get('round', '?')}, tol {tol:.2f}x):")
        print("\n".join(lines))
        if regressions:
            print("\nWALL-TIME REGRESSIONS:", file=sys.stderr)
            for r in regressions:
                print(f"  {r}", file=sys.stderr)
            return 1
        print("no wall-time regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
