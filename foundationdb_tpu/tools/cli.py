"""fdbcli analogue: an interactive shell over the client API.

Reference: fdbcli/fdbcli.actor.cpp — the command table (:435-475) with
get/set/clear/clearrange/getrange/status/writemode, byte-string
arguments with \\xNN escapes, and transactional semantics per command
(each command runs its own retried transaction). The shell drives a
SimCluster's deterministic loop per command; `python -m
foundationdb_tpu.tools.cli --exec "set a b; get a"` scripts it.
"""

from __future__ import annotations

import json
import shlex
import sys
from typing import List, Optional

from .. import flow
from ..client import run_transaction
from ..server import SimCluster

HELP = """\
Commands (ref: fdbcli):
  get <key>                  read a key
  set <key> <value>          write a key
  clear <key>                remove a key
  clearrange <begin> <end>   remove a key range
  getrange <begin> <end> [limit]   read a range
  getkey <sel> <key> [offset]      resolve a key selector
                             (sel: lt | le | gt | ge)
  status [json|details]      cluster status (details: per-stage
                             latency bands, percentiles, kernel
                             profile, conflict hot spots, latency
                             probe, health messages)
  metrics                    counter time series (latest + rates)
  top                        hottest conflict ranges + role rates
                             (the conflict-attribution view; with
                             SIM_TASK_STATS armed, also the run-loop
                             task table and network message types)
  qos                        saturation telemetry: ratekeeper budget +
                             limiting reason, per-role queue/lag/rate
                             signals, tag & priority traffic
  heat                       storage heat: per-server read/write
                             bandwidth + shard bytes, read-hot
                             sub-ranges, busiest read tag per server
  slo                        SLO engine verdict: per-rule ok/BREACH,
                             burn rates, recorder + TimeKeeper write
                             accounting (needs METRIC_HISTORY armed)
  path                       commit critical-path decomposition: the
                             dominant latency station, per-station
                             seconds with queue-vs-service splits,
                             and per-process resource telemetry
                             (needs CRITICAL_PATH armed)
  flightrec [dump [dir]]     flight-recorder status, or dump the
                             recent-trace-event ring to a directory
                             (in-process recorder)

  throttle on <tag> <tps> [prio] [secs]   manually throttle a tag
                             (prio: default | batch; secs: how long
                             the row lives, default 3600)
  throttle off <tag>         clear a tag's throttle row
  throttle list              the live \\xff\\x02/throttledTags/ rows
  configure <k>=<v> ...      change the cluster shape (proxies,
                             resolvers, logs, conflict_backend)
  exclude <worker>           bar a worker from hosting roles
  include <worker>           re-admit an excluded worker
  writemode <on|off>         allow mutations (default on)
  backup start <url>         submit a backup to a container URL
  backup status|wait|abort   drive/inspect it (needs a BackupDriver)
  restore <url> [version]    restore from a container URL
  coordinators <n>           move the coordination state to n fresh
                             coordinators (in-sim cli)
  consistencycheck           full-replica byte sweep (in-sim cli)
  profile on [rate]          run-loop sampler + sampled transaction
                             logging at [rate] (in-sim cli)
  profile off                stop both profilers (in-sim cli)
  profile analyze [top]      analyze persisted transaction profiles
                             (slowest txns, per-op latency, hot keys)
  help                       this text
  exit                       leave
Keys/values support \\xNN escapes and quoting."""


def _unescape(tok: str) -> bytes:
    out = bytearray()
    i = 0
    while i < len(tok):
        ch = tok[i]
        if ch == "\\" and i + 3 < len(tok) and tok[i + 1] == "x":
            out.append(int(tok[i + 2:i + 4], 16))
            i += 4
        else:
            out.extend(ch.encode())
            i += 1
    return bytes(out)


def _printable(b: bytes) -> str:
    return "".join(chr(c) if 32 <= c < 127 and c != 92 else f"\\x{c:02x}"
                   for c in b)


def _band_line(who: str, kind: str, b: dict) -> str:
    """One latency-surface row: totals, reservoir percentiles, and the
    fraction under a mid/wide band (the numbers an operator scans for
    'where does a commit's time go')."""
    total = b.get("total", 0)
    bands = b.get("bands", {})

    def frac(th):
        return f"{bands[th] / total:.0%}" if total and th in bands else "-"
    return (f"  {who:<26} {kind:<8} n={total:<7}"
            f" p50={b.get('p50', 0):<9g} p90={b.get('p90', 0):<9g}"
            f" p99={b.get('p99', 0):<9g} max={b.get('max_seconds', 0):<9g}"
            f" <=5ms:{frac('<=0.005s'):<5} <=100ms:{frac('<=0.1s')}")


def _render_details(cl: dict) -> str:
    """`status details`: the per-stage latency + kernel-profile view
    (ref: fdbcli `status details` folding LatencyBands and role
    metrics)."""
    lines = [f"Epoch {cl['epoch']} — {cl['recovery_state']}",
             "Latency (seconds):"]
    for p in cl.get("proxies", ()):
        for kind in ("grv", "commit"):
            lines.append(_band_line(p["name"], kind,
                                    p["latency_bands"][kind]))
    for r in cl.get("resolvers", ()):
        lines.append(_band_line(r["name"], "resolve",
                                r["latency_bands"]["resolve"]))
    for lg in cl.get("logs", ()):
        if "latency_bands" in lg:
            lines.append(_band_line(lg["store"], "logfsync",
                                    lg["latency_bands"]["commit"]))
    seen_reps: set = set()
    for s in cl.get("storages", ()):
        for rep in s["replicas"]:
            # the storages list is per SHARD; one server hosting many
            # shards carries the same snapshot in each — render each
            # server once
            if "latency_bands" in rep and rep["name"] not in seen_reps:
                seen_reps.add(rep["name"])
                lines.append(_band_line(rep["name"], "read",
                                        rep["latency_bands"]["read"]))
    kern = [(r["name"], r["kernel"]) for r in cl.get("resolvers", ())
            if r.get("kernel")]
    if kern:
        lines.append("Resolver kernels:")
        for name, k in kern:
            occ = ", ".join(f"{d}={v if v is not None else '-'}"
                            for d, v in k.get("occupancy", {}).items())
            h2d = k.get("h2d") or {}
            pb = h2d.get("per_batch")
            h2d_s = (f" h2d={pb:g}/batch"
                     f" ({h2d.get('transfers', 0)}x,"
                     f" {h2d.get('bytes', 0)}B,"
                     f" staging={h2d.get('staging_allocs', 0)})"
                     if pb is not None else "")
            lines.append(
                f"  {name:<26} backend={k['backend']} "
                f"platform={k['platform']} batches={k['batches']} "
                f"rows={k['state_rows']}/{k['capacity']} occ[{occ}]"
                f"{h2d_s}")
    pipes = [(r["name"], r["pipeline"]) for r in cl.get("resolvers", ())
             if r.get("pipeline")]
    if pipes:
        lines.append("Resolve pipeline:")
        for name, p in pipes:
            lat = p.get("latency", {})
            sub = lat.get("submit", {})
            dr = lat.get("drain", {})
            occ = p.get("occupancy")
            lines.append(
                f"  {name:<26} depth={p['depth']} "
                f"in_flight={p['in_flight']}/{p['peak_in_flight']}peak "
                f"submits={p['submits']} drains={p['drains']} "
                f"forced={p['forced_drains']} "
                f"occ={occ if occ is not None else '-'} "
                f"submit_p50={sub.get('p50', 0):g}s "
                f"drain_p50={dr.get('p50', 0):g}s")
    lines.extend(_balance_lines(cl))
    fos = [(r["name"], r["failover"]) for r in cl.get("resolvers", ())
           if r.get("failover")]
    if fos:
        lines.append("Backend failover:")
        for name, fo in fos:
            sh = fo.get("shadow", {})
            lines.append(
                f"  {name:<26} active={fo['active_backend']} "
                f"{'primary' if fo.get('on_primary') else 'FALLBACK'} "
                f"ckpts={fo.get('checkpoints', 0)} "
                f"log={fo.get('replay_log', 0)} "
                f"faults={fo.get('device_faults', 0)} "
                f"failovers={fo.get('failovers', 0)} "
                f"replayed={fo.get('replayed_batches', 0)} "
                f"reattach={fo.get('reattaches', 0)} "
                f"shadow={sh.get('sampled', 0)}/{sh.get('mismatches', 0)}mm")
    adm = cl.get("admission_control") or {}
    if adm.get("grv_admission_enabled") or \
            adm.get("tag_throttling_enabled") or \
            any((p.get("admission") or {}).get("rejected")
                for p in cl.get("proxies", ())):
        # enforced admission posture: who admitted/shed how much per
        # class, and which tags are throttled (server/admission.py)
        lines.append("Admission control:")
        for p in cl.get("proxies", ()):
            a = p.get("admission") or {}
            ad = a.get("admitted") or {}
            q = a.get("queued") or {}
            lines.append(
                f"  {p['name']:<26} "
                f"admitted imm={ad.get('immediate', 0)} "
                f"def={ad.get('default', 0)} batch={ad.get('batch', 0)} "
                f"queued={sum(q.values())} "
                f"rejected={a.get('rejected', 0)} "
                f"timed_out={a.get('timed_out', 0)} "
                f"tag_delayed={a.get('throttle_delayed', 0)} "
                f"rounds={a.get('confirm_rounds', 0)}")
        for r in adm.get("throttled_tags", ()):
            lines.append(
                f"  throttled tag {r['tag']}: tps={r['tps']:g} "
                f"prio<={r['priority']} "
                f"{'auto' if r.get('auto') else 'manual'} "
                f"expires@{r['expiry']:g} queued={r.get('queued', 0)}")
        auto = adm.get("auto_throttler") or {}
        client = adm.get("client") or {}
        lines.append(
            f"  auto throttler: written={auto.get('auto_throttles', 0)} "
            f"cleared={auto.get('auto_cleared', 0)}  "
            f"client backoffs={client.get('backoffs', 0)} "
            f"({client.get('backoff_ms', 0)}ms)")
    cs = cl.get("conflict_scheduling") or {}
    scheds = [(p["name"], p.get("scheduler") or {})
              for p in cl.get("proxies", ())]
    if cs.get("scheduling_enabled") or any(
            s.get("deferrals") for _n, s in scheds):
        # conflict prediction at admission: who deferred how much, and
        # what the predictors currently know (server/scheduler.py)
        lines.append("Conflict scheduler:")
        for name, s in scheds:
            lines.append(
                f"  {name:<26} deferrals={s.get('deferrals', 0)} "
                f"released={s.get('released', 0)} "
                f"overflow={s.get('overflow', 0)} "
                f"held={s.get('deferred_now', 0)} "
                f"queues={s.get('queue_ranges', 0)} "
                f"hot_rows={s.get('hot_rows', 0)}")
        client = cs.get("client") or {}
        lines.append(
            f"  client windows: early_aborts="
            f"{client.get('early_aborts', 0)} "
            f"checks={client.get('checks', 0)} "
            f"cached={client.get('windows_cached', 0)}")
    reps = [(p["name"], p.get("repair") or {})
            for p in cl.get("proxies", ())]
    if cs.get("repair_enabled") or any(
            r.get("attempts") for _n, r in reps):
        # server-side transaction repair: the abort tax converted
        # (server/repair.py)
        lines.append("Transaction repair:")
        for name, r in reps:
            lines.append(
                f"  {name:<26} attempts={r.get('attempts', 0)} "
                f"repaired={r.get('committed', 0)} "
                f"reconflicted={r.get('conflicted', 0)} "
                f"fallbacks={r.get('fallbacks', 0)} "
                f"reread_rows={r.get('reread_rows', 0)} "
                f"in_flight={r.get('in_flight', 0)}")
    if cl.get("kernels"):
        lines.append("Kernel compile/execute (process-wide):")
        for kn, v in sorted(cl["kernels"].items()):
            lines.append(f"  {kn} = {v}")
    qos = cl.get("qos") or {}
    if qos.get("transactions_per_second_limit") is not None:
        # throttle posture without reaching for the exporter: the
        # current budget, WHY it is what it is, and the smoothed
        # inputs behind the decision (ref: fdbcli `status details`
        # performance-limited-by section)
        inputs = qos.get("inputs") or {}
        lines.append("Ratekeeper:")
        lines.append(
            f"  tps_limit={qos['transactions_per_second_limit']:g} "
            f"batch_tps_limit="
            f"{(qos.get('batch_transactions_per_second_limit') or 0):g} "
            f"limited_by={qos.get('limiting_reason', 'none')}")
        if inputs:
            lines.append("  inputs: " + "  ".join(
                f"{k}={v}" for k, v in sorted(inputs.items())))
    heat = cl.get("storage_heat") or {}
    if heat.get("ranges") or heat.get("busiest_read_tags"):
        # the heat plane only earns a details section once it flagged
        # something (the full per-server view lives under `heat`)
        lines.append("Storage heat (read-hot sub-ranges):")
        for row in heat.get("ranges", ()):
            lines.append(
                f"  [{row['begin']}, {row['end']})  {row['server']:<20} "
                f"density={row['density']:g} read={row['read_bps']:g}B/s")
        for row in heat.get("busiest_read_tags", ()):
            lines.append(f"  busiest tag {row['tag']} @ {row['server']}: "
                         f"busyness={row['busyness']:g}")
    chaos = cl.get("chaos") or {}
    if chaos.get("injected") or chaos.get("scenarios"):
        # the chaos plane only earns a section once something fired
        lines.append("Chaos (injected faults):")
        inj = "  ".join(f"{k}={v}"
                        for k, v in sorted(chaos["injected"].items()))
        lines.append(f"  {inj if inj else '(none)'}")
        for sc, n in sorted((chaos.get("scenarios") or {}).items()):
            lines.append(f"  scenario {sc}: {n} run(s)")
        lines.append(
            f"  events={chaos.get('events', 0)} "
            f"dropped_msgs={chaos.get('messages_dropped', 0)} "
            f"dup_msgs={chaos.get('messages_duplicated', 0)}")
    rl = cl.get("run_loop", {})
    if rl:
        ratio = rl.get("sim_per_busy")
        lines.append(f"Run loop: tasks={rl.get('tasks_run')} "
                     f"busy={rl.get('busy_seconds')}s "
                     f"sim={rl.get('sim_seconds')}s"
                     + (f" sim/busy={ratio}x" if ratio else ""))
        for t in rl.get("slow_tasks", ()):
            lines.append(f"  slow: {t['seconds']:<8} {t['task']}"
                         + (f"  @ {t['stack']}"
                            if t.get("stack") else ""))
    lines.extend(_sim_perf_lines(cl))
    lines.append("Latency probe:")
    probe = cl.get("latency_probe") or {}
    scalars = {k: v for k, v in probe.items() if k != "bands"}
    if scalars:
        lines.append("  " + "  ".join(
            f"{k}={v}" for k, v in sorted(scalars.items())))
    else:
        lines.append("  (no probe round yet)")
    for stage, snap in sorted((probe.get("bands") or {}).items()):
        lines.append(_band_line("cluster-probe", stage, snap))
    lines.extend(_hot_spot_and_message_lines(cl))
    return "\n".join(lines)


def _sim_perf_lines(cl: dict) -> List[str]:
    """The SIM_TASK_STATS attribution view (run-loop task table +
    priority bands + network message types) — shared by `status
    details` and `top`; empty while the plane is off."""
    lines: List[str] = []
    ts = (cl.get("run_loop") or {}).get("task_stats") or {}
    if ts.get("tasks"):
        lines.append("Run-loop attribution (SIM_TASK_STATS):")
        for r in ts["tasks"]:
            lines.append(
                f"  {r['task']:<30} steps={r['steps']:<9}"
                f" busy={r['busy_us'] / 1e6:<9.3f}s"
                f" max={r['max_us']:.0f}us")
        bands = "  ".join(f"{b['band']}={b['busy_us'] / 1e6:.3f}s"
                          for b in ts.get("bands", ()))
        if bands:
            lines.append(f"  priority bands: {bands}")
        if ts.get("dropped_names"):
            lines.append(f"  (table bound hit: {ts['dropped_names']} "
                         f"folds in '(other)')")
    net = cl.get("network") or {}
    if net.get("types"):
        lines.append("Network messages (by request type):")
        for r in net["types"]:
            lines.append(f"  {r['type']:<30} {r['count']}")
        lines.append(
            f"  sent={net.get('messages_sent')} "
            f"dropped={net.get('messages_dropped')} "
            f"timers_now={net.get('timers_now')} "
            f"ready_now={net.get('ready_now')}")
    return lines


def _balance_lines(cl: dict) -> List[str]:
    """The resolver split/merge view (ISSUE 15) — per-resolver owned
    ranges + state rows, the balance loop's event counters, and the
    last split key — shared by `status details` and `top` so skew is
    visible before and after the balancer acts."""
    bal = cl.get("resolver_balance") or {}
    resolvers = cl.get("resolvers") or ()
    if not bal and not any(r.get("splits") for r in resolvers):
        return []
    armed = "armed" if bal.get("enabled") else "off"
    lines = [f"Resolver balance ({armed}): "
             f"splits={bal.get('splits', 0)} "
             f"merges={bal.get('merges', 0)} "
             f"releases={bal.get('releases', 0)} "
             f"handoff_timeouts={bal.get('handoff_timeouts', 0)}"]
    last = bal.get("last_split")
    if last:
        lines.append(f"  last split [{last.get('begin')}, "
                     f"{last.get('end') or 'ff..'}) "
                     f"resolver {last.get('from')} -> {last.get('to')} "
                     f"(work moved {last.get('work_moved')})")
    for r in resolvers:
        sp = r.get("splits") or {}
        if sp:
            lines.append(
                f"  {r['name']}: owned_ranges="
                f"{sp.get('owned_ranges', '-')} "
                f"state_rows={sp.get('state_rows', 0)} "
                f"checkpoints={sp.get('checkpoints_served', 0)} "
                f"installs={sp.get('installs', 0)}")
    return lines


def _hot_spot_and_message_lines(cl: dict) -> List[str]:
    """The conflict-hot-spot table + health messages — shared by
    `status details` and `top`."""
    lines = ["Conflict hot spots (decaying score):"]
    hot = cl.get("conflict_hot_spots") or ()
    for row in hot:
        lines.append(f"  [{row['begin']}, {row['end']})  "
                     f"score={row['score']:<10g} total={row['total']}")
    if not hot:
        lines.append("  (none attributed)")
    for m in cl.get("messages", ()):
        lines.append(f"Message [{m.get('severity')}] {m.get('name')}: "
                     f"{m.get('description')}")
    return lines


def _tail_rate(series: dict) -> str:
    tail = series.get("tail") or []
    if series.get("gauge"):
        return "(gauge)"
    if len(tail) >= 2 and tail[-1][0] > tail[0][0] and \
            tail[-1][1] >= tail[0][1]:
        return f"{(tail[-1][1] - tail[0][1]) / (tail[-1][0] - tail[0][0]):.2f}"
    return ""


def _render_top(cl: dict) -> str:
    """`top`: the conflict-attribution view — hottest key ranges first
    (what an operator looks at when high_conflict_rate fires), then the
    busiest role counters by sampled rate."""
    lines = _hot_spot_and_message_lines(cl)
    lines.extend(_balance_lines(cl))
    watch = ("transactions_committed", "transactions_conflicted",
             "transactions_started", "batches_resolved",
             "transactions_resolved", "conflict_ranges_attributed",
             "commits", "get_queries")
    rows = []
    for name, s in sorted((cl.get("metrics") or {}).items()):
        rn, _, cn = name.partition("/")
        if cn not in watch:
            continue
        rate = _tail_rate(s)
        if not rate or rate == "(gauge)":
            continue
        rows.append((float(rate), rn, cn))
    rows.sort(reverse=True)
    if rows:
        lines.append("Busiest counters (rate/s over the sampled tail):")
        for rate, rn, cn in rows[:12]:
            lines.append(f"  {rate:>10.2f}/s  {rn}/{cn}")
    # the run-loop/network attribution tables (when SIM_TASK_STATS is
    # armed) — `top` is exactly where "what burns the wall clock" goes
    lines.extend(_sim_perf_lines(cl))
    return "\n".join(lines)


def _render_qos(cl: dict) -> str:
    """`qos`: the saturation-telemetry view — the ratekeeper's budget
    and limiting reason, every role's smoothed queue/lag/rate signals,
    and the tag/priority traffic accounting (what an operator reads
    when the cluster feels slow and they want to know WHICH role is
    saturated before the throttle even engages)."""
    qos = cl.get("qos") or {}
    lines = [
        f"Ratekeeper: tps_limit="
        f"{qos.get('transactions_per_second_limit')} "
        f"batch_tps_limit="
        f"{qos.get('batch_transactions_per_second_limit')} "
        f"limited_by={qos.get('limiting_reason', 'none')}"]
    inputs = qos.get("inputs") or {}
    if inputs:
        lines.append("Decision inputs:")
        for k, v in sorted(inputs.items()):
            lines.append(f"  {k:<36} {v}")
    roles = qos.get("roles") or {}
    for kind in ("storage", "tlog", "proxy", "resolver"):
        if kind not in roles:
            continue
        lines.append(f"{kind.capitalize()} signals:")
        for rname, signals in sorted(roles[kind].items()):
            sig = "  ".join(f"{k}={v}" for k, v in sorted(signals.items())
                            if k != "sampled_at")
            lines.append(f"  {rname:<26} {sig}")
    if not roles:
        lines.append("(no QoS samples yet — is QOS_SAMPLE_INTERVAL 0?)")
    tags = qos.get("tags") or ()
    lines.append("Tag traffic (decaying busyness):")
    for row in tags:
        lines.append(
            f"  {row['tag']:<20} busyness={row['busyness']:<10g} "
            f"started={row['started']} committed={row['committed']} "
            f"conflicted={row['conflicted']}")
    if not tags:
        lines.append("  (no tagged transactions)")
    prios = qos.get("priorities") or {}
    if prios:
        lines.append("Priority classes:")
        for prio in ("immediate", "default", "batch"):
            c = prios.get(prio)
            if c is None:
                continue
            lines.append(
                f"  {prio:<10} started={c['started']} "
                f"committed={c['committed']} "
                f"conflicted={c['conflicted']}")
    return "\n".join(lines)


def _render_heat(cl: dict) -> str:
    """`heat`: the storage heat view (ISSUE 13) — per-server sampled
    bytes + read/write bandwidth, the cluster's read-hot sub-ranges
    (decaying top-K), and the busiest read tag per server (what an
    operator reads to answer 'which shard would DD split, and which
    tenant is hammering it')."""
    heat = cl.get("storage_heat") or {}
    armed = heat.get("tracking_enabled")
    lines = [f"Storage heat (STORAGE_HEAT_TRACKING="
             f"{'on' if armed else 'off'}):"]
    seen: set = set()
    lines.append("Per-server meters:")
    for s in cl.get("storages", ()):
        for rep in s.get("replicas", ()):
            if rep["name"] in seen or "sampled_bytes" not in rep:
                continue
            seen.add(rep["name"])
            lines.append(
                f"  {rep['name']:<26} bytes={rep['sampled_bytes']:<8} "
                f"write={rep.get('write_bytes_per_sec', 0):<9g}B/s "
                f"read={rep.get('read_bytes_per_sec', 0):<9g}B/s "
                f"ops={rep.get('read_ops_per_sec', 0):g}/s")
    if not seen:
        lines.append("  (no storage replicas reporting)")
    ranges = heat.get("ranges") or ()
    lines.append("Read-hot sub-ranges (decaying):")
    for row in ranges:
        lines.append(
            f"  [{row['begin']}, {row['end']})  {row['server']:<20} "
            f"density={row['density']:<8g} read={row['read_bps']:g}B/s "
            f"seen={row.get('sightings', 0)}x")
    if not ranges:
        lines.append("  (none flagged)" if armed
                     else "  (plane off — arm STORAGE_HEAT_TRACKING)")
    tags = heat.get("busiest_read_tags") or ()
    lines.append("Busiest read tag per server:")
    for row in tags:
        lines.append(f"  {row['server']:<26} tag={row['tag']} "
                     f"busyness={row['busyness']:g}")
    if not tags:
        lines.append("  (no tagged reads)")
    return "\n".join(lines)


def _render_metrics(cl: dict) -> str:
    """`metrics`: the TDMetric-style counter series — latest value plus
    a rate computed over the fine-grained tail."""
    lines = ["metric                                            "
             "latest      rate/s"]
    for name, s in sorted(cl.get("metrics", {}).items()):
        latest = s.get("latest")
        # same semantics as the *Metrics rollup: gauges are levels
        # (no derivative), and a negative delta is a role restart
        # (re-baseline), not a rate
        rate = _tail_rate(s)
        val = latest[1] if latest else "-"
        lines.append(f"{name:<48}  {val:<10}  {rate}")
    return "\n".join(lines)


def _render_slo(cl: dict) -> str:
    """`slo`: the longitudinal-observability verdict (ISSUE 17) — the
    online SLO engine's per-rule state, the recorder/TimeKeeper write
    accounting, and how many ok->breach transitions the run has seen
    (what an operator reads to answer 'is the cluster meeting its
    objectives, and if not which rule broke first')."""
    slo = cl.get("slo") or {}
    if not slo.get("enabled"):
        return ("SLO engine off — arm METRIC_HISTORY to start the "
                "TimeKeeper, the metric-history recorder, and the "
                "burn-rate rules")
    lines = [f"SLO: {slo.get('state', '?')} "
             f"(breaches this run: {slo.get('breaches', 0)})"]
    for r in slo.get("rules", ()):
        val = r.get("value")
        thr = r.get("threshold")
        extra = ""
        if r.get("kind") == "burn_rate" and \
                r.get("slow_value") is not None:
            extra = (f"  slow={r['slow_value']:g}"
                     f"/{r.get('slow_threshold', 0):g}")
        lines.append(
            f"  {'ok    ' if r.get('ok') else 'BREACH'} "
            f"{r.get('name', '?'):<22} {r.get('kind', ''):<10} "
            f"value={val if val is not None else '-':<10} "
            f"threshold={thr if thr is not None else '-'}{extra}")
    rec = slo.get("recorder") or {}
    lines.append(
        f"  recorder: {rec.get('signals', 0)} signals, "
        f"{rec.get('samples', 0)} samples taken, "
        f"{rec.get('rows_written', 0)} chunk rows flushed; "
        f"timekeeper rows: {slo.get('timekeeper_rows', 0)}")
    return "\n".join(lines)


def _render_path(cl: dict) -> str:
    """`path`: the latency-forensics view (ISSUE 18) — which pipeline
    station commits spend their time in, the queue-vs-service split
    where the serving role keeps one, the telescoping-sum residual
    bound, and per-process resource telemetry. Every read is .get:
    a federated doc from an older worker simply shows dashes."""
    cp = cl.get("critical_path") or {}
    if not cp.get("enabled"):
        return ("critical-path decomposition off — arm CRITICAL_PATH "
                "to decompose every commit into per-station segments "
                "(batcher, version, resolve, fsync, reply)")
    lines = [
        f"Critical path: dominant now = {cp.get('dominant_now') or '-'}"
        f"  ({cp.get('samples', 0)} commits decomposed; max residual "
        f"{cp.get('max_residual_seconds', 0):g}s, tolerance "
        f"{cp.get('tolerance', 0):g})",
        f"  {'station':<16} {'seconds':>9} {'dominant':>9} "
        f"{'decayed':>9}"]
    dom = cp.get("dominant") or {}
    secs = cp.get("station_seconds") or {}
    decayed = {r.get("station"): r.get("score", 0.0)
               for r in cp.get("top") or ()}
    from ..server.critical_path import STATIONS
    for s in STATIONS:
        lines.append(f"  {s:<16} {secs.get(s, 0.0):>9g} "
                     f"{dom.get(s, 0):>9} {decayed.get(s, 0.0):>9g}")
    splits = cp.get("splits") or {}
    for station, split in sorted(splits.items()):
        w = (split.get("wait") or {}).get("sum_seconds", 0.0)
        sv = (split.get("service") or {}).get("sum_seconds", 0.0)
        lines.append(f"  {station}: queue {w:g}s vs service {sv:g}s "
                     f"(serving-role split)")
    pm = cl.get("process_metrics") or {}
    if pm.get("enabled"):
        share = pm.get("role_cpu_share") or {}
        if share:
            lines.append("  host cpu share: " + "  ".join(
                f"{r}={v:.0%}" for r, v in share.items()))
        host = pm.get("host") or {}
        if host:
            lines.append(
                f"  host process: cpu={host.get('cpu_seconds', 0):g}s "
                f"rss={host.get('rss_bytes', 0)} "
                f"fds={host.get('open_fds', 0)} "
                f"lag={host.get('loop_lag_ms', 0):g}ms")
    for pname, p in sorted((cl.get("processes") or {}).items()):
        s = p.get("process_metrics") or {}
        if not s:
            lines.append(f"  {pname}: (no process metrics)")
            continue
        lines.append(
            f"  {pname}: cpu={s.get('cpu_seconds', 0):g}s "
            f"rss={s.get('rss_bytes', 0)} fds={s.get('open_fds', 0)} "
            f"lag={s.get('loop_lag_ms', 0):g}ms "
            f"up={p.get('up', 1)}")
    return "\n".join(lines)


class Cli:
    def __init__(self, db, runner, cluster=None):
        """`db` is any Database-shaped handle (in-sim or remote);
        `runner` executes a client coroutine to completion — the sim
        loop locally, RemoteCluster.call over TCP. `cluster` (in-sim
        only) enables the operator commands that need cluster-level
        access: coordinators, consistencycheck, profile."""
        self.db = db
        self._runner = runner
        self.cluster = cluster
        self.writemode = True
        self._coord_changes = 0   # deterministic unique names

    @classmethod
    def for_cluster(cls, cluster: SimCluster) -> "Cli":
        return cls(cluster.client("fdbcli"),
                   lambda coro: cluster.run(coro, timeout_time=600),
                   cluster=cluster)

    @classmethod
    def for_remote(cls, remote) -> "Cli":
        return cls(remote.db, remote.call)

    def _move_may_have_landed(self, new_refs) -> bool:
        """True when the coordinators change may have committed even
        though the client RPC errored/timed out — in that case the new
        quorum must NOT be reaped (the old set redirects to it
        forever). First FENCES the mover: a quorum read on the old
        coordinators raises their read generations, so an in-flight
        tombstone write that has not applied anywhere yet can never
        commit (the mover does not retry conflicts) — making "not
        landed" a stable fact rather than a point-in-time observation.
        Then scans the old quorum for a MovedValue tombstone or a
        forward pointing at the new set."""
        from ..server.coordination import CoordinatedState, MovedValue
        n = len(new_refs)
        old = self.cluster.coordinators[:-n]
        old_refs = [self.cluster._coord_refs(c) for c in old]
        proc = self.cluster.net.new_process(
            f"cli-fence{self._coord_changes}",
            machine=f"cli-fence{self._coord_changes}")

        async def fence():
            cs = CoordinatedState([(r[0], r[1]) for r in old_refs], proc)
            await cs.read()

        try:
            self._run(fence())
        except Exception:
            return True   # fence unproven: keep the new quorum alive

        new_names = {r[0].endpoint.process.name for r in new_refs}

        def _points_at_new(refs) -> bool:
            return any(r[0].endpoint.process.name in new_names
                       for r in refs)

        for coord in old:
            if coord._forward is not None and _points_at_new(coord._forward):
                return True
            for value, _wgen, _rgen in coord._reg.values():
                if isinstance(value, MovedValue) and \
                        _points_at_new(value.coordinators):
                    return True
        return False

    def execute(self, line: str) -> str:
        """Run one command line; returns the printed output."""
        try:
            lex = shlex.shlex(line, posix=True)
            lex.whitespace_split = True
            lex.escape = ""          # backslashes belong to \xNN escapes
            lex.commenters = ""      # '#' is key/value data, not comments
            toks = list(lex)
        except ValueError as e:
            return f"ERROR: {e}"
        if not toks:
            return ""
        cmd, args = toks[0].lower(), [_unescape(t) for t in toks[1:]]
        try:
            return self._dispatch(cmd, args, toks[1:])
        except Exception as e:  # noqa: BLE001 — shell surfaces, not dies
            return f"ERROR: {getattr(e, 'name', None) or e}"

    def _run(self, coro):
        return self._runner(coro)

    def _dispatch(self, cmd: str, args: List[bytes],
                  raw: List[str]) -> str:
        if cmd == "help":
            return HELP
        if cmd == "exit":
            raise SystemExit(0)
        if cmd == "writemode":
            if not raw or raw[0] not in ("on", "off"):
                return "ERROR: writemode requires `on' or `off'"
            self.writemode = raw[0] == "on"
            return ""
        if cmd == "metrics":
            async def mt():
                return await self.db.get_status()
            return _render_metrics(self._run(mt())["cluster"])
        if cmd == "top":
            async def tp():
                return await self.db.get_status()
            return _render_top(self._run(tp())["cluster"])
        if cmd == "qos":
            async def qs():
                return await self.db.get_status()
            return _render_qos(self._run(qs())["cluster"])
        if cmd == "heat":
            async def ht():
                return await self.db.get_status()
            return _render_heat(self._run(ht())["cluster"])
        if cmd == "slo":
            async def sl():
                return await self.db.get_status()
            return _render_slo(self._run(sl())["cluster"])
        if cmd == "path":
            async def pt():
                return await self.db.get_status()
            return _render_path(self._run(pt())["cluster"])
        if cmd == "flightrec":
            from ..flow import g_flightrec as fr
            if raw and raw[0] == "dump":
                directory = raw[1] if len(raw) > 1 else None
                path = fr.dump(directory=directory, reason="cli")
                if path is None:
                    return ("ERROR: nothing to dump (ring empty, or "
                            "no directory given/armed)")
                return f"dumped {len(fr.snapshot())} events to {path}"
            st = fr.status()
            return (f"flight recorder: "
                    f"{'armed' if st['armed'] else 'disarmed'}  "
                    f"ring={st['buffered']}/{st['size']} events  "
                    f"noted={st['noted']}  dumps={st['dumps']}")
        if cmd == "status":
            async def st():
                return await self.db.get_status()
            doc = self._run(st())
            if raw and raw[0] == "json":
                return json.dumps(doc, indent=2, sort_keys=True)
            if raw and raw[0] == "details":
                return _render_details(doc["cluster"])
            cl = doc["cluster"]
            lines = [
                f"Epoch {cl['epoch']} — {cl['recovery_state']}",
                f"  coordinators: {cl['coordinators']}"
                f"  workers: {len(cl['workers'])}",
                f"  logs: {len(cl['logs'])}"
                f"  storage shards: {len(cl['storages'])}"
                f"  proxies: {len(cl['proxies'])}",
            ]
            px = cl["proxies"][0]["counters"] if cl["proxies"] else {}
            lines.append(
                f"  transactions committed: "
                f"{px.get('transactions_committed', 0)}"
                f"  conflicts: {px.get('transactions_conflicted', 0)}")
            return "\n".join(lines)
        if cmd == "throttle":
            # (ref: fdbcli `throttle on tag|off|list` — manual rows
            # round-trip through the SAME \xff\x02/throttledTags/ keys
            # the ratekeeper's auto-throttler writes; every proxy
            # enforces whatever is in the table, however it got there)
            from ..server import systemkeys as sk
            from ..server.types import PRIORITY_BATCH, PRIORITY_DEFAULT
            sub = raw[0] if raw else ""
            if sub == "list":
                async def body(tr):
                    tr.set_option("read_system_keys")
                    return await tr.get_range(sk.THROTTLED_TAGS_PREFIX,
                                              sk.THROTTLED_TAGS_END)
                rows = self._run(run_transaction(self.db, body))
                lines = []
                for key, value in rows:
                    tag = sk.parse_throttled_tag_key(key)
                    parsed = sk.parse_tag_throttle_value(value)
                    if tag is None or parsed is None:
                        continue
                    tps, expiry, prio, auto = parsed
                    pname = "batch" if prio == PRIORITY_BATCH else "default"
                    lines.append(
                        f"  {_printable(tag):<20} tps={tps:g} "
                        f"prio<={pname} "
                        f"{'auto' if auto else 'manual'} "
                        f"expires@{expiry:g}")
                return ("Throttled tags:\n" + "\n".join(lines)
                        if lines else "(no throttled tags)")
            if not self.writemode:
                return "ERROR: writemode off"
            if sub == "on":
                if len(args) < 3:
                    return ("usage: throttle on <tag> <tps> "
                            "[default|batch] [secs]")
                tag = args[1]
                try:
                    tps = float(raw[2])
                    secs = float(raw[4]) if len(raw) > 4 else 3600.0
                except ValueError:
                    return ("usage: throttle on <tag> <tps> "
                            "[default|batch] [secs]")
                pname = raw[3] if len(raw) > 3 else "default"
                if pname not in ("default", "batch"):
                    return "ERROR: throttle priority is default or batch"
                prio = (PRIORITY_BATCH if pname == "batch"
                        else PRIORITY_DEFAULT)

                async def body(tr):
                    tr.set_option("access_system_keys")
                    tr.set(sk.throttled_tag_key(tag),
                           sk.encode_tag_throttle_value(
                               tps, flow.now() + secs, prio, auto=False))
                self._run(run_transaction(self.db, body))
                return (f"Throttle set: {_printable(tag)} at {tps:g} "
                        f"tps ({pname} and below) for {secs:g}s")
            if sub == "off":
                if len(args) < 2:
                    return "usage: throttle off <tag>"
                tag = args[1]

                async def body(tr):
                    tr.set_option("access_system_keys")
                    tr.clear(sk.throttled_tag_key(tag))
                self._run(run_transaction(self.db, body))
                return f"Throttle cleared: {_printable(tag)}"
            return "usage: throttle on <tag> <tps> [prio] [secs]" \
                   "|off <tag>|list"
        if cmd == "configure":
            mapping = {"proxies": "n_proxies", "resolvers": "n_resolvers",
                       "logs": "n_logs",
                       "conflict_backend": "conflict_backend"}
            kwargs = {}
            for tok in raw:
                k, _eq, v = tok.partition("=")
                if k not in mapping:
                    return f"ERROR: unknown configuration key `{k}'"
                kwargs[mapping[k]] = v if k == "conflict_backend" else int(v)

            async def body():
                await self.db.configure(**kwargs)
            self._run(body())
            return "Configuration changed"
        if cmd == "coordinators":
            # (ref: fdbcli `coordinators` -> ManagementAPI changeQuorum)
            if self.cluster is None:
                return ("ERROR: coordinators change requires cluster "
                        "access (in-sim cli)")
            if len(raw) != 1 or not raw[0].isdigit() or \
                    int(raw[0]) < 1:
                return "usage: coordinators <n>   (n >= 1)"
            n = int(raw[0])
            self._coord_changes += 1
            new_refs = self.cluster.add_coordinators(
                n, tag=f"cli{self._coord_changes}-")
            try:
                self._run(self.db.change_coordinators(new_refs))
            except Exception:
                # the change failed — but change_coordinators has a 30s
                # timeout that can fire AFTER the move committed (the
                # MovedValue tombstone landed in the old quorum).
                # Reaping the new quorum then bricks the coordinated
                # state: the old set forwards to a dead set. Only reap
                # when no old coordinator shows evidence the move
                # reached the new set (advisor r4).
                if not self._move_may_have_landed(new_refs):
                    for coord in self.cluster.coordinators[-n:]:
                        self.cluster.net.kill(coord.process)
                    del self.cluster.coordinators[-n:]
                raise
            return f"Coordination state moved to {n} new coordinators"
        if cmd == "consistencycheck":
            # (ref: `fdbserver -r consistencycheck` / the post-test
            # sweep, tester.actor.cpp:741). Runs over the client
            # surface, so it works identically in-sim and --connect'ed
            # to a tools.server cluster over TCP; in-sim, the cluster
            # handle enables the stronger quiesce.
            from ..server.consistency import check_consistency
            target = self.cluster if self.cluster is not None else self.db
            stats = self._run(check_consistency(target))
            return (f"Consistency check passed: {stats['shards']} shards,"
                    f" {stats['replicas']} replicas, {stats['rows']} rows"
                    f" at version {stats['version']}")
        if cmd == "profile":
            # (ref: fdbcli `profile client` + ProfilerRequest): `on`
            # arms BOTH profilers — the run-loop sampler and the
            # sampled-transaction logger (PROFILE_SAMPLE_RATE, default
            # 1.0 = every transaction); `analyze` runs the
            # tools/profiler.py analyzer over the persisted records,
            # so it works over a remote connection too
            if raw and raw[0] == "analyze":
                from . import profiler as _profiler
                top = int(raw[1]) if len(raw) > 1 else 10

                async def _analyze():
                    if self.cluster is not None:
                        # records flush in the background at low
                        # priority: give in-flight ones a beat to land
                        # so `profile analyze` right after a workload
                        # sees it (remote analyzers scan whatever has
                        # already committed)
                        await flow.delay(1.0)
                    return await _profiler.profile_analysis(
                        self.db, top_n=top)
                analysis, stats = self._run(_analyze())
                return _profiler.format_report(analysis, stats)
            if self.cluster is None:
                return "ERROR: profile on/off requires cluster access"
            sched = self.cluster.sched
            if raw and raw[0] == "on":
                try:
                    rate = float(raw[1]) if len(raw) > 1 else 1.0
                except ValueError:
                    return "usage: profile on [rate]|off|analyze [top]"
                flow.SERVER_KNOBS.set("profile_sample_rate",
                                      min(max(rate, 0.0), 1.0))
                sched.start_profiler()
                return "Profiler on"
            if raw and raw[0] == "off":
                flow.SERVER_KNOBS.set("profile_sample_rate", 0.0)
                report = sched.stop_profiler()
                lines = [f"{e['samples']:6d}  {e['task']}  {e['stack']}"
                         for e in report[:10]]
                return "Profiler off\n" + "\n".join(lines)
            return "usage: profile on [rate]|off|analyze [top]"
        if cmd == "backup":
            # (ref: fdbcli-adjacent fdbbackup verbs; the tool's row
            # protocol works over any Database, in-sim or remote)
            from . import backup_tool as bt
            sub = raw[0] if raw else ""
            if sub in ("start", "abort") and not self.writemode:
                # start/abort commit control rows — the same mutation
                # guard every write verb honors
                return "ERROR: writemode off"
            if sub == "start":
                if len(raw) < 2:
                    return "usage: backup start <container-url>"
                out = self._run(bt.backup_start(self.db, raw[1]))
                return json.dumps(out)
            if sub == "status":
                return json.dumps(self._run(bt.backup_status(self.db)))
            if sub == "wait":
                v = int(raw[1]) if len(raw) > 1 else None
                return json.dumps(self._run(bt.backup_wait(self.db, v)))
            if sub == "abort":
                return json.dumps(self._run(bt.backup_abort(self.db)))
            return "usage: backup start|status|wait|abort ..."
        if cmd == "restore":
            from . import backup_tool as bt
            if not raw:
                return "usage: restore <container-url> [version]"
            if not self.writemode:
                return "ERROR: writemode off"
            v = int(raw[1]) if len(raw) > 1 else None
            out = self._run(bt.backup_restore(self.db, raw[0], v))
            return json.dumps(out)
        if cmd in ("exclude", "include"):
            async def body():
                await self.db.exclude(raw[0], exclude=cmd == "exclude")
            self._run(body())
            return ("Excluded" if cmd == "exclude" else "Included")
        if cmd == "get":
            async def body(tr):
                return await tr.get(args[0])
            v = self._run(run_transaction(self.db, body))
            return (f"`{_printable(args[0])}' is "
                    f"`{_printable(v)}'" if v is not None else
                    f"`{_printable(args[0])}': not found")
        if cmd == "getrange":
            limit = int(raw[2]) if len(raw) > 2 else 25

            async def body(tr):
                return await tr.get_range(args[0], args[1], limit=limit)
            rows = self._run(run_transaction(self.db, body))
            out = [f"`{_printable(k)}' is `{_printable(v)}'"
                   for k, v in rows]
            return "\n".join(out) if out else "(empty range)"
        if cmd == "getkey":
            from ..server.types import KeySelector
            sel_kind, key = raw[0], args[1]
            offset = int(raw[2]) if len(raw) > 2 else 0
            base = {"lt": KeySelector.last_less_than,
                    "le": KeySelector.last_less_or_equal,
                    "gt": KeySelector.first_greater_than,
                    "ge": KeySelector.first_greater_or_equal}[sel_kind](key)
            sel = base._replace(offset=base.offset + offset)

            async def body(tr):
                return await tr.get_key(sel)
            k = self._run(run_transaction(self.db, body))
            return f"`{_printable(k)}'"
        if cmd not in ("set", "clear", "clearrange"):
            return f"ERROR: unknown command `{cmd}' (try help)"
        if not self.writemode:
            return "ERROR: writemode is off"
        if cmd == "set":
            async def body(tr):
                tr.set(args[0], args[1])
            self._run(run_transaction(self.db, body))
            return "Committed"
        if cmd == "clear":
            async def body(tr):
                tr.clear(args[0])
            self._run(run_transaction(self.db, body))
            return "Committed"
        if cmd == "clearrange":
            async def body(tr):
                tr.clear_range(args[0], args[1])
            self._run(run_transaction(self.db, body))
            return "Committed"
        return f"ERROR: unknown command `{cmd}' (try help)"


def _split_script(script: str) -> List[str]:
    """Split on ';' outside quotes (the shell's own quoting applies
    under --exec too)."""
    parts, cur, quote = [], [], None
    for ch in script:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
            cur.append(ch)
        elif ch == ";":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p for p in parts if p.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from ._tlsargs import TLS_FLAGS, tls_from_args
    script = None
    seed = 0
    connect = None
    cluster_file = None
    tls_args = {}
    while argv:
        a = argv.pop(0)
        if a == "--exec":
            script = argv.pop(0)
        elif a == "--seed":
            seed = int(argv.pop(0))
        elif a == "--connect":
            connect = argv.pop(0)
        elif a in ("--cluster-file", "-C"):
            cluster_file = argv.pop(0)
        elif a in TLS_FLAGS:
            tls_args[TLS_FLAGS[a]] = argv.pop(0)
    try:
        tls = tls_from_args(tls_args)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    from ..client.cluster_file import resolve_connect
    try:
        addr = resolve_connect(connect, cluster_file)
    except (OSError, ValueError) as e:
        what = "--connect" if connect is not None else "cluster file"
        print(f"bad {what}: {e}", file=sys.stderr)
        return 2
    if tls is not None and addr is None:
        print("--tls-* flags require --connect/--cluster-file (local "
              "mode has no network)", file=sys.stderr)
        return 2
    cluster = None
    remote = None
    if addr is not None:
        # remote mode (ref: fdbcli -C cluster-file): speak the wire
        # protocol to a tools.server / TcpGateway in another process
        from ..client.remote import RemoteCluster
        host, port = addr
        remote = RemoteCluster(host or "127.0.0.1", port, tls=tls)
        cli = Cli.for_remote(remote)
    else:
        cluster = SimCluster(seed=seed, durable=True)
        cli = Cli.for_cluster(cluster)
    try:
        if script is not None:
            for line in _split_script(script):
                out = cli.execute(line.strip())
                if out:
                    print(out)
            return 0
        print("fdbtpu-cli (type `help' for commands)")
        while True:
            try:
                line = input("fdb> ")
            except EOFError:
                return 0
            try:
                out = cli.execute(line)
            except SystemExit:
                return 0
            if out:
                print(out)
    finally:
        if remote is not None:
            remote.close()
        if cluster is not None:
            cluster.shutdown()


if __name__ == "__main__":
    sys.exit(main())
