"""fdbtpu-backup / fdbtpu-restore: the operator-facing backup driver.

Reference: fdbbackup/backup.actor.cpp:74 — ONE multiplexed binary
(fdbbackup start/status/wait/abort, fdbrestore) that drives backups by
writing the backup config subspace and polling it; the cluster-side
agents do the actual work. Here the same split: every subcommand
speaks ONLY the client surface — control rows under \\xff\\x02/backup/
(server/systemkeys.py) plus container IO — so the tool works
identically against an in-sim cluster and over TCP
(`--connect host:port` dials a tools.server gateway). The cluster must
run a BackupDriver (tools.server does; SimCluster(backup_driver=True)
in-sim) — without one, `start` commits rows nobody serves, exactly
like fdbbackup with no agents running.

    python -m foundationdb_tpu.tools.backup_tool start -d blobstore://h:p -C host:port
    ... status|wait|abort -C host:port
    ... restore -r blobstore://h:p [--version N] -C host:port
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .. import flow
from ..client import run_transaction
from ..layers.backup_container import (open_container,
                                       restore_from_container)
from ..server.systemkeys import (BACKUP_END, BACKUP_PREFIX,
                                 BACKUP_STATE_ABORT, BACKUP_STATE_ERROR,
                                 BACKUP_STATE_RUNNING,
                                 BACKUP_STATE_STOPPED,
                                 BACKUP_STATE_SUBMITTED)

_ACTIVE = (BACKUP_STATE_SUBMITTED, BACKUP_STATE_RUNNING)


async def _read_rows(db) -> dict:
    from ..layers.backup_driver import read_backup_rows
    return await read_backup_rows(db, max_retries=2000)


async def backup_start(db, url: str) -> dict:
    """Submit a backup: commit dest+state rows; the cluster's driver
    picks them up (ref: fdbbackup start writing the config subspace)."""
    open_container(url)   # fail fast on a bad URL, like the reference
    conflict = []

    async def body(tr):
        tr.set_option("access_system_keys")
        cur = await tr.get(BACKUP_PREFIX + b"state")
        if cur in _ACTIVE:
            conflict.append(cur)
            return
        tr.clear_range(BACKUP_PREFIX, BACKUP_END)
        tr.set(BACKUP_PREFIX + b"dest", url.encode())
        tr.set(BACKUP_PREFIX + b"state", BACKUP_STATE_SUBMITTED)
    await run_transaction(db, body, max_retries=2000)
    if conflict:
        raise RuntimeError(
            f"a backup is already {conflict[0].decode()} — abort it first")
    return {"state": "submitted", "dest": url}


async def backup_status(db) -> dict:
    """Control-row view plus the container's own manifest (ref:
    fdbbackup status / describe)."""
    rows = await _read_rows(db)
    out = {k.decode(): v.decode(errors="replace")
           for k, v in rows.items()}
    dest = rows.get(b"dest")
    if dest:
        try:
            out["container"] = open_container(dest.decode()).describe()
        except (IOError, OSError, ValueError) as e:
            out["container_error"] = repr(e)
    return out


async def backup_wait(db, version: Optional[int] = None,
                      max_wait: float = 120.0) -> dict:
    """Block until the backup is restorable (to `version` if given) —
    ref: fdbbackup wait."""
    deadline = flow.now() + max_wait
    while True:
        rows = await _read_rows(db)
        state = rows.get(b"state", b"")
        if state == BACKUP_STATE_ERROR:
            raise RuntimeError(
                f"backup failed: {rows.get(b'error', b'?').decode()}")
        restorable = int(rows.get(b"restorable_version", b"-1"))
        if state in (BACKUP_STATE_RUNNING, BACKUP_STATE_STOPPED) \
                and restorable >= 0 \
                and (version is None or restorable >= version):
            return {"state": state.decode(),
                    "restorable_version": restorable}
        if flow.now() > deadline:
            raise TimeoutError(
                f"backup not restorable to {version} after {max_wait}s "
                f"(state={state.decode()}, restorable={restorable})")
        await flow.delay(flow.SERVER_KNOBS.backup_tool_poll_delay)


async def backup_abort(db, max_wait: float = 120.0) -> dict:
    """Stop the backup and wait for the driver to finalize the
    container (ref: fdbbackup abort)."""
    async def body(tr):
        tr.set_option("access_system_keys")
        tr.set(BACKUP_PREFIX + b"state", BACKUP_STATE_ABORT)
    await run_transaction(db, body, max_retries=2000)
    deadline = flow.now() + max_wait
    while True:
        rows = await _read_rows(db)
        if rows.get(b"state") == BACKUP_STATE_STOPPED:
            return {"state": "stopped",
                    "restorable_version":
                        int(rows.get(b"restorable_version", b"-1"))}
        if flow.now() > deadline:
            raise TimeoutError("abort did not finalize in time")
        await flow.delay(flow.SERVER_KNOBS.backup_tool_poll_delay)


async def backup_restore(db, url: str,
                         version: Optional[int] = None) -> dict:
    """Restore from a container through ordinary transactions (ref:
    fdbrestore driving the restore from a container URL)."""
    v = await restore_from_container(db, open_container(url), version)
    return {"restored_to_version": v}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdbtpu-backup",
        description="backup/restore driver (ref: fdbbackup/fdbrestore)")
    ap.add_argument("command",
                    choices=["start", "status", "wait", "abort",
                             "restore"])
    ap.add_argument("-d", "--dest", help="container URL (start)")
    ap.add_argument("-r", "--source", help="container URL (restore)")
    ap.add_argument("-C", "--connect", required=True,
                    metavar="HOST:PORT",
                    help="cluster gateway (tools.server)")
    ap.add_argument("--version", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args(argv)

    from ..client.remote import RemoteCluster
    host, port = args.connect.rsplit(":", 1)
    rc = RemoteCluster(host, int(port))
    try:
        db = rc.db
        if args.command == "start":
            if not args.dest:
                ap.error("start requires -d/--dest")
            out = rc.call(backup_start(db, args.dest),
                          timeout=args.timeout)
        elif args.command == "status":
            out = rc.call(backup_status(db), timeout=args.timeout)
        elif args.command == "wait":
            out = rc.call(backup_wait(db, args.version, args.timeout),
                          timeout=args.timeout + 10)
        elif args.command == "abort":
            out = rc.call(backup_abort(db, args.timeout),
                          timeout=args.timeout + 10)
        else:
            if not args.source:
                ap.error("restore requires -r/--source")
            out = rc.call(backup_restore(db, args.source, args.version),
                          timeout=args.timeout)
        print(json.dumps(out))
        return 0
    except (RuntimeError, TimeoutError, ValueError) as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1
    finally:
        rc.close()


if __name__ == "__main__":
    sys.exit(main())
