"""Merge per-process trace files into cross-process commit trees.

Reference: the reference cluster writes one XML/JSON trace file per
process and the commit-debug stations (g_traceBatch) are reassembled
OFFLINE by contrib tooling — no process ever sees the whole picture
live. This is that tool for the run directories the soak harness and
clusterbench workers write (ISSUE 16): each process dumps
role+pid-stamped span lines (flow/trace.py, `Process=` /
`RemoteParent*=` fields) plus client-side `WireHop` events carrying
the four NTP-style timestamps of every traced TCP request/reply pair.

The merge: estimate each process's clock offset from the hop
timestamps (median of ((t1-t0)+(t2-t3))/2 per process pair, chained
from a root process — no trusted wall clock anywhere), stitch spans
into per-debug-id trees across the process boundary via the
RemoteParent links, order the merged timeline skew-tolerantly (tree
order wins over adjusted timestamps when a child's clock says it
started before its parent), and emit a human report (slowest commits
end-to-end with a per-hop breakdown) plus flamegraph-ready folded
stacks (`flamegraph.pl` / speedscope).

    python -m foundationdb_tpu.tools.tracemerge <run_dir> \
        [--top N] [--out report.txt] [--folded stacks.folded] [--json doc.json]
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

#: process name for span lines written before ISSUE 16 (no Process
#: field, no ProcessIdentity header) — single-process sim traces merge
#: under this one identity
LOCAL_PROCESS = "local"


# ---------------------------------------------------------------- loading
def _segment_index(name: str) -> int:
    """Rolled-generation ordinal: `trace.x.jsonl.N` segments are older
    than the bare `trace.x.jsonl` (flow/trace.py rolls aside as .1, .2,
    ... with the bare path always newest), so N orders and the bare
    file sorts last."""
    tail = name.rsplit(".", 1)[-1]
    return int(tail) if tail.isdigit() else (1 << 62)


def trace_file_groups(run_dir: str) -> List[List[str]]:
    """Trace files grouped per base file, each group's rolled segments
    in WRITE order — .1 (oldest), .2, ..., bare (newest). Numeric
    ordering matters: a lexicographic sort reads .10 before .2 and
    would interleave an hours-long worker's spans out of order."""
    groups: Dict[str, List[str]] = {}
    for name in os.listdir(run_dir):
        if not (name.startswith("trace.") and ".jsonl" in name):
            continue
        base = name[:name.index(".jsonl") + len(".jsonl")]
        groups.setdefault(base, []).append(name)
    return [[os.path.join(run_dir, n)
             for n in sorted(groups[base], key=_segment_index)]
            for base in sorted(groups)]


def trace_files(run_dir: str) -> List[str]:
    """Every trace file in the run directory, rolled generations
    included (trace.<role>.<pid>.jsonl and .jsonl.N), in read order."""
    return [p for group in trace_file_groups(run_dir) for p in group]


def load_run(run_dir: str) -> dict:
    """Parse every trace file: span rows, wire-hop rows, and the
    per-process span counts. A broken line is skipped, never fatal — a
    kill -9 mid-write must not hide the rest of the run. Rolled
    segments of one base file are read as ONE stream sharing one
    ProcessIdentity: a pre-fix segment without its own header still
    attributes to its file group, not to the local-process bucket."""
    spans: List[dict] = []
    hops: List[dict] = []
    skipped = 0
    for group in trace_file_groups(run_dir):
        rows = []
        for path in group:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        skipped += 1
        default_proc = LOCAL_PROCESS
        for ev in rows:
            if ev.get("Type") == "ProcessIdentity" and ev.get("ID"):
                default_proc = ev["ID"]
                break
        for ev in rows:
            t = ev.get("Type")
            if t == "Span":
                remote = None
                if ev.get("RemoteParentID") is not None:
                    remote = (ev.get("RemoteParentProcess", ""),
                              ev["RemoteParentID"])
                begin = ev.get("Begin", 0.0) or 0.0
                end = ev.get("End")
                spans.append({
                    "process": ev.get("Process") or default_proc,
                    "span_id": ev.get("SpanID"),
                    "parent_id": ev.get("ParentID"),
                    "remote": remote,
                    "debug_id": str(ev.get("ID", "")),
                    "location": ev.get("Location", ""),
                    "begin": begin,
                    "end": end if end is not None else begin,
                })
            elif t == "WireHop":
                hops.append({
                    "client": ev.get("Client") or default_proc,
                    "server": ev.get("Server", ""),
                    "ids": [str(d) for d in ev.get("DebugIDs", ())],
                    "t0": ev.get("T0"), "t1": ev.get("T1"),
                    "t2": ev.get("T2"), "t3": ev.get("T3"),
                })
    return {"run_dir": run_dir, "spans": spans, "hops": hops,
            "skipped_lines": skipped}


# ---------------------------------------------------------------- offsets
def _median(sorted_vals: List[float]) -> float:
    n = len(sorted_vals)
    mid = n // 2
    if n % 2:
        return sorted_vals[mid]
    return (sorted_vals[mid - 1] + sorted_vals[mid]) / 2.0


def estimate_offsets(hops: List[dict], spans: List[dict] = (),
                     root: Optional[str] = None
                     ) -> Tuple[str, Dict[str, float], dict]:
    """Per-process clock offsets from the hop timestamp quads.

    For one (client, server) pair the NTP local-offset formula
    ((t1-t0)+(t2-t3))/2 estimates `server_clock - client_clock` per
    exchange; the pair's estimate is the MEDIAN over its exchanges (a
    single reactor-poll outlier must not skew the alignment). Offsets
    chain outward from a root process (the busiest hop client by
    default, ties lexicographic), so `t - offsets[process]` maps any
    timestamp into the root's clock. Returns (root, offsets,
    pair_table)."""
    pair_samples: Dict[Tuple[str, str], List[float]] = {}
    for h in hops:
        if not h["server"] or None in (h["t0"], h["t1"], h["t2"],
                                       h["t3"]):
            continue
        off = ((h["t1"] - h["t0"]) + (h["t2"] - h["t3"])) / 2.0
        pair_samples.setdefault((h["client"], h["server"]),
                                []).append(off)
    med = {k: _median(sorted(v)) for k, v in pair_samples.items()}
    procs = sorted({s["process"] for s in spans}
                   | {p for k in med for p in k})
    if not procs:
        procs = [LOCAL_PROCESS]
    if root is None:
        client_weight: Dict[str, int] = {}
        for (c, _sv), v in pair_samples.items():
            client_weight[c] = client_weight.get(c, 0) + len(v)
        root = min(procs, key=lambda p: (-client_weight.get(p, 0), p))
    offsets: Dict[str, float] = {root: 0.0}
    frontier = [root]
    while frontier:
        nxt = []
        for a in frontier:
            for (c, sv) in sorted(med):
                if c == a and sv not in offsets:
                    offsets[sv] = offsets[a] + med[(c, sv)]
                    nxt.append(sv)
                elif sv == a and c not in offsets:
                    offsets[c] = offsets[a] - med[(c, sv)]
                    nxt.append(c)
        frontier = nxt
    for p in procs:
        offsets.setdefault(p, 0.0)   # unreachable: no hop evidence
    pairs = {f"{c}->{sv}": {"offset_s": round(med[(c, sv)], 6),
                            "samples": len(pair_samples[(c, sv)])}
             for (c, sv) in sorted(med)}
    return root, offsets, pairs


# ------------------------------------------------------------------ merge
def merge(run_dir: str, root: Optional[str] = None) -> dict:
    """The merged cross-process picture of one run directory: clock
    offsets, and one span tree per sampled debug id (slowest first,
    every timestamp mapped into the root process's clock)."""
    data = load_run(run_dir)
    spans, hops = data["spans"], data["hops"]
    root, offsets, pairs = estimate_offsets(hops, spans, root=root)

    by_debug: Dict[str, List[dict]] = {}
    for s in spans:
        by_debug.setdefault(s["debug_id"], []).append(s)

    chains = []
    for debug_id in sorted(by_debug):
        group = by_debug[debug_id]
        nodes = {(s["process"], s["span_id"]): s for s in group
                 if s["span_id"] is not None}
        children: Dict[tuple, list] = {}
        roots = []
        for s in group:
            s["begin_adj"] = round(
                s["begin"] - offsets.get(s["process"], 0.0), 6)
            s["end_adj"] = round(
                s["end"] - offsets.get(s["process"], 0.0), 6)
            pkey = s["remote"] if s["remote"] is not None else (
                (s["process"], s["parent_id"])
                if s["parent_id"] is not None else None)
            if pkey is not None and tuple(pkey) in nodes:
                children.setdefault(tuple(pkey), []).append(s)
            else:
                roots.append(s)

        # skew-tolerant ordering: siblings sort by adjusted begin (ties
        # by process/span id), but a child ALWAYS nests under its
        # parent even when residual skew says it began first
        def order_key(s):
            return (s["begin_adj"], s["process"], s["span_id"] or 0)

        rows: List[dict] = []

        def walk(s, depth, visiting):
            key = (s["process"], s["span_id"])
            if key in visiting:    # defensive: a cyclic parent link
                return
            rows.append({"process": s["process"],
                         "location": s["location"],
                         "span_id": s["span_id"],
                         "begin": s["begin_adj"], "end": s["end_adj"],
                         "depth": depth})
            for c in sorted(children.get(key, ()), key=order_key):
                walk(c, depth + 1, visiting | {key})

        for s in sorted(roots, key=order_key):
            walk(s, 0, frozenset())
        if not rows:
            continue
        t_begin = min(r["begin"] for r in rows)
        t_end = max(r["end"] for r in rows)
        procs = sorted({r["process"] for r in rows})
        chains.append({
            "debug_id": debug_id,
            "end_to_end_s": round(t_end - t_begin, 6),
            "begin": t_begin,
            "processes": procs,
            "cross_process": len(procs) > 1,
            "spans": rows,
        })
    chains.sort(key=lambda c: (-c["end_to_end_s"], c["debug_id"]))
    return {
        "run_dir": run_dir,
        "root_process": root,
        "processes": sorted({s["process"] for s in spans}),
        "clock_offsets_s": {p: round(v, 6)
                            for p, v in sorted(offsets.items())},
        "hop_pairs": pairs,
        "wire_hops": len(hops),
        "skipped_lines": data["skipped_lines"],
        "chains": chains,
    }


def cross_process_chains(merged: dict) -> List[dict]:
    """Chains whose span tree crosses at least one process boundary."""
    return [c for c in merged["chains"] if c["cross_process"]]


def full_commit_chains(merged: dict) -> List[dict]:
    """Cross-process chains carrying the complete commit path — a
    client leg, the proxy commitBatch leg, a resolver leg and a tlog
    leg (the SOAK_r01 acceptance shape)."""
    want = ("NativeAPI.commit", "MasterProxyServer.commitBatch",
            "Resolver.resolveBatch", "TLog.tLogCommit")
    out = []
    for c in cross_process_chains(merged):
        locs = {r["location"] for r in c["spans"]}
        if all(w in locs for w in want):
            out.append(c)
    return out


# ------------------------------------------------ critical-path stations
#: cross-process commit stations in path order. Boundary timestamps are
#: read off the merged (clock-rebased) span tree of one full commit
#: chain; consecutive boundaries telescope to the chain's client-side
#: extent, the offline analogue of the live in-process decomposition
#: (server/critical_path.py STATIONS).
PATH_STATIONS = ("client_to_proxy", "proxy_batcher", "resolve",
                 "log_push", "tlog_fsync", "reply")


def path_decomposition(merged: dict, tolerance: float = 0.05) -> dict:
    """Decompose every full commit chain into critical-path station
    segments.

    Boundaries, in path order: client span begin, proxy commitBatch
    begin, first resolver begin, last resolver end, first tlog begin,
    last tlog end, client span end. Residual clock skew can push a
    boundary backwards; boundaries are made monotone (running max) so
    segments are non-negative AND still telescope exactly to the
    client-observed extent — any skew shows up as a zero-width station,
    never a negative one. `residual_s` per chain is the difference
    between the chain's merged end-to-end and the telescoped sum (the
    tree may extend past the client span on either side)."""
    rows: List[dict] = []
    seconds = {s: 0.0 for s in PATH_STATIONS}
    dominant: Dict[str, int] = {}
    max_residual = 0.0
    chains = full_commit_chains(merged)
    for c in chains:
        by_loc: Dict[str, List[dict]] = {}
        for r in c["spans"]:
            by_loc.setdefault(r["location"], []).append(r)
        client = by_loc["NativeAPI.commit"][0]
        proxy = by_loc["MasterProxyServer.commitBatch"][0]
        res = by_loc["Resolver.resolveBatch"]
        tlog = by_loc["TLog.tLogCommit"]
        bounds = (client["begin"], proxy["begin"],
                  min(r["begin"] for r in res),
                  max(r["end"] for r in res),
                  min(r["begin"] for r in tlog),
                  max(r["end"] for r in tlog),
                  client["end"])
        cuts = [bounds[0]]
        for b in bounds[1:]:
            cuts.append(max(cuts[-1], b))
        segments = {s: round(cuts[i + 1] - cuts[i], 6)
                    for i, s in enumerate(PATH_STATIONS)}
        dom = max(PATH_STATIONS, key=lambda s: segments[s])
        residual = c["end_to_end_s"] - (cuts[-1] - cuts[0])
        for s in PATH_STATIONS:
            seconds[s] += segments[s]
        dominant[dom] = dominant.get(dom, 0) + 1
        max_residual = max(max_residual, abs(residual))
        rows.append({"debug_id": c["debug_id"],
                     "end_to_end_s": c["end_to_end_s"],
                     "segments": segments,
                     "dominant": dom,
                     "residual_s": round(residual, 6)})
    return {
        "chains": len(chains),
        "decomposed": len(rows),
        "stations": {s: round(v, 6) for s, v in seconds.items()},
        "dominant": dominant,
        "max_residual_seconds": round(max_residual, 6),
        "tolerance": tolerance,
        "rows": rows,
    }


# ----------------------------------------------------------------- output
def render_report(merged: dict, top: int = 5) -> str:
    lines = [f"tracemerge: {merged['run_dir']}"]
    lines.append("processes: " + (", ".join(merged["processes"])
                                  or "(none)"))
    lines.append(f"root clock: {merged['root_process']} "
                 f"(wire hops: {merged['wire_hops']})")
    for pair, row in merged["hop_pairs"].items():
        lines.append(f"  offset {pair}: {row['offset_s'] * 1e3:+.3f} ms"
                     f" ({row['samples']} samples)")
    for p, off in merged["clock_offsets_s"].items():
        lines.append(f"  clock {p}: {off * 1e3:+.3f} ms vs root")
    chains = merged["chains"]
    cross = sum(1 for c in chains if c["cross_process"])
    full = len(full_commit_chains(merged))
    lines.append(f"chains: {len(chains)} total, {cross} cross-process, "
                 f"{full} full commit paths")
    if full:
        path = path_decomposition(merged)
        doms = ", ".join(f"{s}={n}" for s, n in
                         sorted(path["dominant"].items(),
                                key=lambda kv: -kv[1]))
        lines.append(f"critical path ({path['decomposed']} commits "
                     f"decomposed, max residual "
                     f"{path['max_residual_seconds'] * 1e3:.3f} ms): "
                     f"dominant {doms or '-'}")
        for s in PATH_STATIONS:
            lines.append(f"  {s:<16} {path['stations'][s] * 1e3:9.3f} ms"
                         " total")
    lines.append(f"slowest commits (top {min(top, len(chains))}):")
    for c in chains[:top]:
        lines.append(f"  {c['debug_id']}: "
                     f"{c['end_to_end_s'] * 1e3:.3f} ms end-to-end, "
                     f"processes={','.join(c['processes'])}")
        for r in c["spans"]:
            rel = (r["begin"] - c["begin"]) * 1e3
            dur = (r["end"] - r["begin"]) * 1e3
            lines.append(f"    {'  ' * r['depth']}+{rel:.3f}ms "
                         f"{r['location']} [{r['process']}] "
                         f"{dur:.3f}ms")
    return "\n".join(lines) + "\n"


def render_folded(merged: dict) -> str:
    """Flamegraph-ready folded stacks: one line per span,
    `proc:loc;proc:loc...` from the chain root, value = SELF time in
    integer microseconds (children's time subtracted, clamped at 0)."""
    out = []
    for c in merged["chains"]:
        rows = c["spans"]
        stack: List[str] = []
        for i, r in enumerate(rows):
            del stack[r["depth"]:]
            stack.append(f"{r['process']}:{r['location']}")
            dur = max(0.0, r["end"] - r["begin"])
            # children of THIS span only: stop scanning at the next
            # row at or above our depth
            child = 0.0
            for x in rows[i + 1:]:
                if x["depth"] <= r["depth"]:
                    break
                if x["depth"] == r["depth"] + 1:
                    child += max(0.0, x["end"] - x["begin"])
            self_us = max(0, int(round((dur - child) * 1e6)))
            out.append(f"{';'.join(stack)} {self_us}")
    return "\n".join(out) + ("\n" if out else "")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    run_dir = None
    top = 5
    out_path = folded_path = json_path = None
    while argv:
        a = argv.pop(0)
        if a == "--top":
            top = int(argv.pop(0))
        elif a == "--out":
            out_path = argv.pop(0)
        elif a == "--folded":
            folded_path = argv.pop(0)
        elif a == "--json":
            json_path = argv.pop(0)
        elif a == "--run-dir":
            run_dir = argv.pop(0)
        elif not a.startswith("-") and run_dir is None:
            run_dir = a
        else:
            print(f"unknown argument {a!r}", file=sys.stderr)
            return 2
    if run_dir is None or not os.path.isdir(run_dir):
        print("usage: tracemerge <run_dir> [--top N] [--out f] "
              "[--folded f] [--json f]", file=sys.stderr)
        return 2
    merged = merge(run_dir)
    report = render_report(merged, top=top)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(report)
    if folded_path:
        with open(folded_path, "w") as fh:
            fh.write(render_folded(merged))
    if json_path:
        with open(json_path, "w") as fh:
            json.dump(merged, fh, indent=2, sort_keys=True)
            fh.write("\n")
    print(report, end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
