"""Incident bundles: snapshot an SLO breach window into one directory.

The longitudinal plane's last mile (ISSUE 17): when the SLO engine
trips — or an operator asks — everything needed to diagnose the breach
is collected into a self-contained bundle dir:

    manifest.json   window, version bounds, verdict, content inventory
    series.json     every \\xff\\x02/metrics/ signal's samples in the
                    window (version-aligned via the TimeKeeper map)
    timekeeper.json the version<->wallclock rows covering the window
    status.json     the status document at capture time
    chaos.json      the chaos accounting (what faults were firing)
    traces.txt      the tracemerge report over the run dir's per-
                    process trace files (rolled segments included)
    chains.json     the merged cross-process commit chains

`capture_bundle` is async and needs a database handle (the soak
harness and `cli incident` both have one); `python -m ...incident
<run_dir>` is the offline half — it rebuilds the trace report/chains
from a run directory after the fact, no live cluster required.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

from ..layers import metrics as metrics_layer
from ..server import timekeeper


def _write_json(path: str, doc) -> None:
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, default=str)
        fh.write("\n")


def _trace_docs(run_dir: Optional[str], out_dir: str) -> dict:
    """The tracemerge half: report + commit chains over the run dir's
    trace files (best-effort — a bundle without traces is still a
    bundle)."""
    inventory = {}
    if not run_dir or not os.path.isdir(run_dir):
        return inventory
    try:
        from . import tracemerge
        doc = tracemerge.merge(run_dir)
        _write_json(os.path.join(out_dir, "chains.json"), doc)
        inventory["chains.json"] = len(doc.get("chains", ()))
        report = tracemerge.render_report(doc)
        with open(os.path.join(out_dir, "traces.txt"), "w") as fh:
            fh.write(report)
        inventory["traces.txt"] = True
    except Exception as e:  # noqa: BLE001 — diagnostics stay best-effort
        inventory["trace_error"] = str(e)
    return inventory


async def capture_bundle(db, out_dir: str,
                         window: Tuple[float, float],
                         run_dir: Optional[str] = None,
                         status_doc: Optional[dict] = None,
                         verdict: Optional[dict] = None,
                         reason: str = "operator") -> dict:
    """Snapshot the breach window [t0, t1] (cluster seconds) into
    `out_dir`; returns the manifest."""
    os.makedirs(out_dir, exist_ok=True)
    t0, t1 = window
    t0_ms, t1_ms = int(t0 * 1000), int(t1 * 1000)

    # version alignment: the TimeKeeper map translates the wallclock
    # window into the version interval the commit pipeline spoke
    time_map = await timekeeper.read_time_map(db)
    v0 = timekeeper.version_at_time_from_map(time_map, t0)
    v1 = timekeeper.version_at_time_from_map(time_map, t1)
    _write_json(os.path.join(out_dir, "timekeeper.json"),
                [{"ts": ts, "version": v} for ts, v in time_map
                 if t0 - 60 <= ts <= t1 + 60])

    # every recorded signal's samples inside the window
    series = {}
    for signal in await metrics_layer.list_history_signals(db):
        samples = await metrics_layer.read_history(
            db, signal, start_ms=t0_ms, end_ms=t1_ms + 1)
        if samples:
            series[signal] = samples
    _write_json(os.path.join(out_dir, "series.json"), series)

    if status_doc is not None:
        _write_json(os.path.join(out_dir, "status.json"), status_doc)
        chaos = (status_doc.get("cluster") or {}).get("chaos")
        if chaos is not None:
            _write_json(os.path.join(out_dir, "chaos.json"), chaos)

    inventory = _trace_docs(run_dir, out_dir)

    # flight recorder (ISSUE 18): the capturing process's ring of
    # recent trace events joins the bundle — the seconds leading INTO
    # the breach, finer-grained than the sampled series
    from ..flow import g_flightrec
    rec_path = g_flightrec.dump(directory=out_dir,
                                reason=f"incident:{reason}")
    if rec_path is not None:
        inventory["flightrec"] = os.path.basename(rec_path)

    manifest = {
        "reason": reason,
        "window": {"t0": t0, "t1": t1,
                   "version_at_t0": v0, "version_at_t1": v1},
        "verdict": verdict,
        "signals": sorted(series),
        "samples": sum(len(s) for s in series.values()),
        "timekeeper_rows": len(time_map),
        "contents": sorted(os.listdir(out_dir)) + ["manifest.json"],
        **inventory,
    }
    _write_json(os.path.join(out_dir, "manifest.json"), manifest)
    return manifest


def main(argv=None) -> int:
    """Offline mode: rebuild the trace report/chains for a finished run
    directory (the live-keyspace halves need a database handle — the
    soak harness and `cli incident` capture those)."""
    import argparse
    ap = argparse.ArgumentParser(
        description="Assemble an offline incident bundle from a soak/"
                    "clusterbench run directory's trace files.")
    ap.add_argument("run_dir", help="run directory with trace.*.jsonl")
    ap.add_argument("--out", default=None,
                    help="bundle dir (default <run_dir>/incident)")
    args = ap.parse_args(argv)
    out_dir = args.out or os.path.join(args.run_dir, "incident")
    os.makedirs(out_dir, exist_ok=True)
    inventory = _trace_docs(args.run_dir, out_dir)
    _write_json(os.path.join(out_dir, "manifest.json"),
                {"reason": "offline", "run_dir": args.run_dir,
                 **inventory})
    print(json.dumps({"bundle": out_dir, **inventory}))
    return 0 if "trace_error" not in inventory else 1


if __name__ == "__main__":
    raise SystemExit(main())
