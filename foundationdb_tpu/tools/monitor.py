"""Process supervisor: keep a cluster host process running.

Reference: fdbmonitor/fdbmonitor.cpp:501-790 — a tiny daemon that
spawns fdbserver, restarts it with backoff when it dies, and logs
lifecycle events. `python -m foundationdb_tpu.tools.monitor --port N
--data-dir D [server args...]` does that for tools.server: with a
data directory the restarted process recovers the database, so a
crashing server self-heals end to end.
"""

from __future__ import annotations

import subprocess
import sys
import time
from typing import List, Optional

def _backoff_knobs():
    """-> (initial, maximum, reset_after) restart-backoff seconds."""
    from ..flow import SERVER_KNOBS
    return (SERVER_KNOBS.monitor_backoff_initial,
            SERVER_KNOBS.monitor_backoff_max,
            SERVER_KNOBS.monitor_backoff_reset_after)


def supervise(server_args: List[str], max_restarts: Optional[int] = None,
              announce=print, python: Optional[str] = None) -> int:
    """Run tools.server under supervision; returns only when
    max_restarts is exhausted (None = forever / until SIGINT)."""
    initial, maximum, reset_after = _backoff_knobs()
    backoff = initial
    restarts = 0
    while True:
        cmd = [python or sys.executable, "-m",
               "foundationdb_tpu.tools.server"] + server_args
        started = time.monotonic()
        announce(f"MONITOR starting: {' '.join(cmd)}", flush=True)
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)

        def relay(p=proc):
            # continuously forward + DRAIN child stdout (a full pipe
            # would block the server; fdbmonitor relays the same way).
            # Bound to THIS child: a delayed thread must never read a
            # successor's pipe concurrently with its own relay.
            for line in p.stdout:
                announce(f"MONITOR child: {line.rstrip()}", flush=True)

        import threading
        threading.Thread(target=relay, daemon=True).start()
        try:
            rc = proc.wait()
        except KeyboardInterrupt:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()   # a wedged child must not orphan the port
                proc.wait(timeout=30)
            announce("MONITOR stopped", flush=True)
            return 0
        ran = time.monotonic() - started
        announce(f"MONITOR child exited rc={rc} after {ran:.1f}s",
                 flush=True)
        restarts += 1
        if max_restarts is not None and restarts > max_restarts:
            return 1
        if ran >= reset_after:
            backoff = initial
        time.sleep(backoff)
        backoff = min(backoff * 2, maximum)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    return supervise(argv)


if __name__ == "__main__":
    sys.exit(main())
