"""Shared --tls-cert/--tls-key/--tls-ca handling for the CLI tools."""

from __future__ import annotations

from typing import Dict, Optional

TLS_FLAGS = {"--tls-cert": "certfile", "--tls-key": "keyfile",
             "--tls-ca": "cafile"}


def tls_from_args(tls_args: Dict[str, str]):
    """TlsConfig from collected flag values; None when no flags given.
    Raises ValueError when only some of the three are present."""
    if not tls_args:
        return None
    if set(tls_args) != {"certfile", "keyfile", "cafile"}:
        raise ValueError(
            "--tls-cert, --tls-key, and --tls-ca must all be given")
    from ..rpc.tcp import TlsConfig
    return TlsConfig(**tls_args)
