"""System bench: committed-txn/s across N proxies × M resolvers —
in-process (simulated time) and across OS processes over real TCP.

The kernel benches (bench.py) measure the resolver core; this driver
measures the SYSTEM the roadmap says to optimize (ROADMAP item 2): a
seeded open-loop commit workload driven through the whole pipeline —
GRV, batcher, version authority, resolver fan-out, log push — at every
shape in {1,2,4} proxies × {1,2,4} resolvers.

Two modes, two honest units:

- **in-process** (`--mode inprocess`): one SimCluster per cell on the
  virtual clock; committed-txn/s is SIM-time throughput. Saturation
  comes from the two modeled serial resources: the per-proxy commit
  cadence (one master version round-trip per batch, batch size capped
  by COMMIT_TRANSACTION_BATCH_COUNT_MAX for the bench) and the modeled
  resolver service time (SIM_RESOLVE_COST_PER_TXN — resolution cost is
  the quantity the source paper scales against, arXiv:1804.00947; the
  sim otherwise resolves in zero sim time and the resolver axis would
  be invisible). Adding proxies multiplies batch cadence; adding
  resolvers divides per-resolver service load (contention-light keys
  split evenly across the keyspace shards).

- **across-process** (`--mode tcp`, `--processes N`): the cluster —
  master, resolvers, tlogs, storage — runs wall-clock in THIS process
  behind a TcpGateway serving PEER endpoints (rpc/gateway.py,
  ISSUE 15); N proxy WORKER processes each build a real `Proxy` role
  from the peer-describe document and join the commit pipeline over
  rpc/tcp.py — resolver and tlog traffic crosses real sockets.
  Committed-txn/s is WALL-time throughput, and the workload must
  complete with ZERO divergent verdicts (contention-light disjoint
  keys: every arrival must commit; any conflict/too-old is a
  divergence).

`--matrix` runs both modes over the full grid and writes the
SYSBENCH_rNN.json artifact published in PERF.md.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import List, Optional

from .. import flow
from ..flow import rng as _rng
from ..flow.future import Promise

GRID = (1, 2, 4)
# bench saturation model (see module docstring): commit batches capped
# small so the per-proxy cadence (one master RTT per batch) binds, and
# a modeled resolver service time so the resolver axis is real
BATCH_CAP = 8
RESOLVE_COST = 400e-6          # seconds per txn at the resolver
REPORT_PATH = "/tmp/_clusterbench_report.json"


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(len(sorted_vals) * q))
    return sorted_vals[i]


def _lat_ms(vals: list) -> dict:
    vals = sorted(vals)
    return {"p50_ms": round(_percentile(vals, 0.50) * 1e3, 3),
            "p90_ms": round(_percentile(vals, 0.90) * 1e3, 3),
            "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3)}


async def _drive_commits(grv_send, commit_send, *, seed: int,
                         duration: float, rate: float, key_prefix: bytes,
                         max_inflight: int = 2048,
                         clock=None, sample_every: int = 0,
                         debug_prefix: str = "",
                         live: Optional[dict] = None) -> dict:
    """The shared seeded open-loop commit workload: exponential
    arrivals at `rate` for `duration` seconds (sim or wall — `clock`
    decides what the latency numbers mean), each a GRV + a one-key
    read/write commit on its own UNIQUE key (contention-light by
    construction: any non-committed verdict is a divergence, not
    noise). Keys spread uniformly over the first byte so keyspace-split
    resolvers share the load. Arrivals past `max_inflight` are shed
    and counted, never hidden (the PR 10 attainment discipline).

    `grv_send(req, reply)` / `commit_send(i, req, reply)` inject into
    a proxy's streams — in-process these round-robin the SimCluster's
    proxies; in a TCP worker they feed the worker's own Proxy role.

    With `sample_every` > 0, every Nth commit carries a debug id
    (`debug_prefix` + arrival index) and opens the client
    `NativeAPI.commit` span around its commit leg — the root of the
    cross-process span tree tracemerge reassembles (ISSUE 16). 0 (the
    default) changes nothing: no debug ids, no spans, identical
    requests."""
    from ..server.types import (CommitRequest, GetReadVersionRequest,
                                MutationRef, SET_VALUE)
    if clock is None:
        clock = flow.now
    g = flow.g_random.fork()
    counts = {"offered": 0, "shed": 0, "committed": 0, "conflicted": 0,
              "too_old": 0, "errors": 0}
    grv_lat: List[float] = []
    commit_lat: List[float] = []
    inflight = [0]
    done = flow.Promise()
    if live is not None:
        # expose the in-flight accumulators so a status endpoint can
        # snapshot the workload mid-run (federated status, ISSUE 16)
        live["counts"] = counts
        live["grv_lat"] = grv_lat
        live["commit_lat"] = commit_lat

    async def one(i: int) -> None:
        # the random byte LEADS the key: resolver ownership splits on
        # the first byte, so a uniform lead byte spreads the load
        # across every keyspace shard (the prefix keeps workers'
        # keyspaces disjoint)
        key = (bytes([g.random_int(0, 256)]) + key_prefix
               + b"%08d" % i)
        debug_id = (f"{debug_prefix}{i}"
                    if sample_every > 0 and i % sample_every == 0
                    else None)
        span = None
        try:
            t0 = clock()
            reply = Promise()
            grv_send(GetReadVersionRequest(), reply)
            ver = (await reply.future).version
            grv_lat.append(clock() - t0)
            t1 = clock()
            reply = Promise()
            if debug_id is not None:
                span = flow.g_trace_batch.begin_span(debug_id,
                                                     "NativeAPI.commit")
            commit_send(i, CommitRequest(
                ver, ((key, key + b"\x00"),), ((key, key + b"\x00"),),
                (MutationRef(SET_VALUE, key, b"v"),),
                debug_id=debug_id), reply)
            await reply.future
            commit_lat.append(clock() - t1)
            counts["committed"] += 1
        except flow.FdbError as e:
            if e.name == "operation_cancelled":
                raise
            if e.name == "not_committed":
                counts["conflicted"] += 1
            elif e.name == "transaction_too_old":
                counts["too_old"] += 1
            else:
                counts["errors"] += 1
        finally:
            if span is not None:
                span.finish()
            inflight[0] -= 1
            if counts["offered"] >= total[0] and inflight[0] == 0 \
                    and not done.is_set:
                done.send(None)

    # seeded open-loop schedule: one RNG fork, exponential gaps
    total = [1 << 30]
    start = clock()
    t_end = flow.now() + duration
    i = 0
    while flow.now() < t_end:
        if inflight[0] < max_inflight:
            counts["offered"] += 1
            inflight[0] += 1
            flow.spawn(one(i))
        else:
            counts["shed"] += 1
        i += 1
        gap = g.random_exp(1.0 / rate) if rate > 0 else 0.001
        await flow.delay(gap)
    total[0] = counts["offered"]
    if inflight[0] > 0 and not done.is_set:
        await flow.timeout(done.future, 30.0)
    admitted = counts["offered"]
    counts["attainment"] = round(
        admitted / max(1, admitted + counts["shed"]), 4)
    # throughput over the REAL window (arrivals + drain), not the
    # nominal duration: a saturated cell's stragglers land after
    # t_end, and crediting them against `duration` would overstate
    counts["elapsed"] = round(clock() - start, 3)
    counts["txn_per_s"] = round(
        counts["committed"] / max(1e-9, counts["elapsed"]), 1)
    counts["grv"] = _lat_ms(grv_lat)
    counts["commit"] = _lat_ms(commit_lat)
    return counts


# ---------------------------------------------------------------- in-process
def run_inprocess_cell(n_proxies: int, n_resolvers: int, *, seed: int,
                       duration: float, rate: float,
                       out=lambda *a, **k: None) -> dict:
    """One simulated cell: committed-txn/s in SIM time at this shape."""
    prev_sched = flow.get_scheduler()
    prev_rng = _rng.rng_state()
    cluster = None
    try:
        from ..server import SimCluster
        from ..server import dbinfo as dbi
        from ..server.proxy import Proxy
        cluster = SimCluster(seed=seed, n_proxies=n_proxies,
                             n_resolvers=n_resolvers, n_storage=1,
                             n_logs=1)
        flow.SERVER_KNOBS.set("sim_resolve_cost_per_txn", RESOLVE_COST)
        flow.SERVER_KNOBS.set("commit_transaction_batch_count_max",
                              BATCH_CAP)

        async def main():
            while cluster.cc.dbinfo.get().recovery_state != \
                    dbi.FULLY_RECOVERED:
                await flow.delay(0.05)
            info = cluster.cc.dbinfo.get()
            from ..server.cluster_controller import epoch_roles
            proxies = sorted(
                epoch_roles(cluster.cc.workers, info.epoch, Proxy),
                key=lambda p: p[0])
            objs = [p for _n, p in proxies]

            def grv_send(req, reply):
                grv_send.rr += 1
                objs[grv_send.rr % len(objs)].grvs.stream.send(
                    (req, reply))
            grv_send.rr = 0

            def commit_send(i, req, reply):
                objs[i % len(objs)].commits.stream.send((req, reply))

            return await _drive_commits(
                grv_send, commit_send, seed=seed, duration=duration,
                rate=rate, key_prefix=b"sb/")

        result = cluster.run(main(), timeout_time=3600)
        result.update({"proxies": n_proxies, "resolvers": n_resolvers,
                       "mode": "inprocess", "unit": "sim"})
        out(f"  inprocess {n_proxies}x{n_resolvers}: "
            f"{result['txn_per_s']}/s committed={result['committed']} "
            f"attainment={result['attainment']}")
        return result
    finally:
        if cluster is not None:
            cluster.shutdown()
        # the cell mutated the bench knobs (resolve cost, batch cap):
        # restore defaults so a caller mid-simulation is not left with
        # a 400µs modeled resolver (same discipline as the scheduler/
        # RNG restore; smoke's run_once precedent)
        flow.reset_server_knobs(randomize=False)
        flow.set_scheduler(prev_sched)
        _rng.restore_rng_state(prev_rng)


# ----------------------------------------------------------- role processes
# r02 capacity model for role-per-process cells: each external resolver
# charges ROLE_RESOLVE_COST wall-seconds per txn and each worker proxy
# ROLE_COMMIT_COST, so a cell's modeled capacity is
# min(R / resolve_cost, P / commit_cost) committed/s and the grid's
# scaling is governed by genuinely-overlapping OS processes, not the
# host GIL. Offered load runs ROLE_HEADROOM past capacity with a small
# per-worker inflight window so the drain tail stays bounded.
ROLE_RESOLVE_COST = 10e-3
ROLE_COMMIT_COST = 6.8e-3
ROLE_HEADROOM = 2.5
ROLE_MAX_INFLIGHT = 128


def role_cell_capacity(n_proxies: int, n_resolvers: int,
                       resolve_cost: float = ROLE_RESOLVE_COST,
                       commit_cost: float = ROLE_COMMIT_COST) -> float:
    """Modeled committed-txn/s ceiling of a role-per-process cell."""
    caps = []
    if resolve_cost > 0:
        caps.append(n_resolvers / resolve_cost)
    if commit_cost > 0:
        caps.append(n_proxies / commit_cost)
    return min(caps) if caps else float("inf")


class RoleProcs:
    """Role-per-process supervisor: one OS process per external
    resolver/tlog (tools/rolehost.py --worker), spawned BEFORE the
    cluster host so recruitment finds live control endpoints. kill() /
    respawn() drive the chaos path: a respawn pins the dead host's
    port, so every outstanding TcpRef — the host's recruitment refs and
    the worker proxies' RetryingTcpRefs alike — heals onto the
    recovered process without re-describing."""

    def __init__(self, n_resolvers: int = 0, n_tlogs: int = 0, *,
                 run_dir: str, state_root: str = None, seed: int = 0,
                 backend: str = "python", resolve_cost: float = 0.0,
                 checkpoint_every: float = 1.0, trace: bool = False):
        self.run_dir = run_dir
        self.state_root = state_root
        self.seed = seed
        self.backend = backend
        self.resolve_cost = resolve_cost
        self.checkpoint_every = checkpoint_every
        self.trace = trace
        self.keys = ([("resolver", i) for i in range(n_resolvers)]
                     + [("tlog", i) for i in range(n_tlogs)])
        self.procs: dict = {}
        self.ready: dict = {}
        self.kills = 0

    @property
    def n_resolvers(self) -> int:
        return sum(1 for k, _ in self.keys if k == "resolver")

    @property
    def n_tlogs(self) -> int:
        return sum(1 for k, _ in self.keys if k == "tlog")

    def name(self, kind: str, i: int) -> str:
        return f"ext-{kind}-{i}"

    def _ready_path(self, kind: str, i: int) -> str:
        return os.path.join(self.run_dir,
                            f"ready.{self.name(kind, i)}.json")

    def spawn(self, kind: str, i: int, port: int = 0) -> None:
        name = self.name(kind, i)
        cfg = {"role": kind, "name": name, "index": i, "port": port,
               "host": "127.0.0.1", "run_dir": self.run_dir,
               "seed": self.seed + 7000
               + i + (0 if kind == "resolver" else 100),
               "trace": int(bool(self.trace)),
               "trace_roll_size":
                   int(flow.SERVER_KNOBS.trace_roll_size),
               "checkpoint_every": self.checkpoint_every}
        if kind == "resolver":
            cfg["backend"] = self.backend
            cfg["resolve_cost"] = self.resolve_cost
            if self.state_root:
                cfg["state_dir"] = os.path.join(self.state_root, name)
        try:
            os.unlink(self._ready_path(kind, i))
        except OSError:
            pass
        log = open(os.path.join(self.run_dir,
                                f"rolehost.{name}.log"), "ab")
        try:
            self.procs[(kind, i)] = subprocess.Popen(
                [sys.executable, "-m",
                 "foundationdb_tpu.tools.rolehost",
                 "--worker", json.dumps(cfg)],
                stdout=log, stderr=log)
        finally:
            log.close()     # the child holds its own dup

    def spawn_all(self) -> "RoleProcs":
        for kind, i in self.keys:
            self.spawn(kind, i)
        return self

    def check_ready(self, kind: str, i: int):
        """Non-blocking: the ready doc once the CURRENT incarnation
        (pid match) has written it, else None. Raises if the process
        exited — a role host never exits on its own."""
        p = self.procs[(kind, i)]
        if p.poll() is not None:
            raise RuntimeError(
                f"rolehost {self.name(kind, i)} exited "
                f"rc={p.returncode} (see rolehost log in "
                f"{self.run_dir})")
        try:
            with open(self._ready_path(kind, i)) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return None
        if doc.get("pid") != p.pid:
            return None     # a previous incarnation's ready file
        self.ready[(kind, i)] = doc
        return doc

    def wait_ready(self, which=None, timeout: float = 60.0) \
            -> "RoleProcs":
        """Blocking (pre-scheduler) readiness wait."""
        deadline = time.time() + timeout
        for kind, i in (which or self.keys):
            while self.check_ready(kind, i) is None:
                if time.time() > deadline:
                    raise RuntimeError(
                        f"rolehost {self.name(kind, i)} never became "
                        f"ready")
                time.sleep(0.05)
        return self

    async def wait_ready_async(self, which=None,
                               timeout: float = 60.0) -> None:
        """Scheduler-friendly readiness wait (soak/test kill paths —
        the host loop keeps serving while the role host reboots)."""
        deadline = time.time() + timeout
        for kind, i in (which or self.keys):
            while self.check_ready(kind, i) is None:
                if time.time() > deadline:
                    raise RuntimeError(
                        f"rolehost {self.name(kind, i)} never became "
                        f"ready")
                await flow.delay(0.05)

    def kill(self, kind: str, i: int) -> int:
        """SIGKILL — the chaos primitive. Returns the dead pid."""
        p = self.procs[(kind, i)]
        p.kill()
        p.wait()
        self.kills += 1
        return p.pid

    def respawn(self, kind: str, i: int) -> None:
        """Relaunch on the SAME port (from the dead incarnation's
        ready doc) so existing refs heal; follow with wait_ready[_
        async] before expecting replies."""
        self.spawn(kind, i, port=int(self.ready[(kind, i)]["port"]))

    def external_roles(self):
        from .rolehost import ExternalRoles
        return ExternalRoles(
            [self.ready[("resolver", i)]
             for i in range(self.n_resolvers)],
            [self.ready[("tlog", i)] for i in range(self.n_tlogs)])

    def status_stubs(self) -> list:
        """proc-file-shaped stubs for exporter.fetch_process_docs —
        current incarnations only (self.ready tracks respawns)."""
        return [{"name": d["name"], "role": d["role"],
                 "pid": d["pid"], "host": d["host"], "port": d["port"],
                 "status_token": d["tokens"]["status"]}
                for d in (self.ready.get(k) for k in self.keys) if d]

    def terminate_all(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — escalate, never hang
                p.kill()
                p.wait()


# ------------------------------------------------------------ across-process
def run_tcp_cell(n_proxies: int, n_resolvers: int, *, seed: int,
                 duration: float, rate: float, run_dir: str = None,
                 trace: bool = False, sample_every: int = 32,
                 role_processes: bool = False,
                 resolve_cost: float = 0.0, commit_cost: float = 0.0,
                 batch_cap: int = 0, max_inflight: int = 2048,
                 state_root: str = None,
                 out=lambda *a, **k: None) -> dict:
    """One across-process cell: this process hosts the cluster
    (master/resolvers/tlogs/storage) wall-clock behind a peer-serving
    TcpGateway; `n_proxies` worker OS processes each run a real Proxy
    role over rpc/tcp.py and drive their share of the workload.

    Every cell gets a trace RUN DIRECTORY (`run_dir`, fresh tmpdir by
    default): workers write role+pid-stamped trace files and
    proc.<role>.<pid>.json discovery stubs there. With `trace=True`
    the TRACE_PROPAGATION knob arms in host and workers, sampled
    commits (1-in-`sample_every`) carry debug ids, and
    tools/tracemerge.py reassembles the cross-process span trees from
    the directory afterwards.

    With `role_processes=True` (ISSUE 19) the cell goes FULLY
    role-per-process: every resolver and the tlog run as their own
    rolehost OS processes (spawned before the cluster, recruited by
    the master through `ExternalRoles`), worker proxies connect to
    them DIRECTLY over TCP, `resolve_cost` arms in the resolver
    processes and `commit_cost` in the worker proxies (the r02
    capacity model — see `role_cell_capacity`), and the cell doc gains
    per-OS-process CPU/RSS rows plus the federated `role_cpu_share`
    fold."""
    from ..server.process_metrics import (ProcessMetrics,
                                          federated_role_cpu_share,
                                          role_cpu_share)
    prev_sched = flow.get_scheduler()
    prev_rng = _rng.rng_state()
    cluster = gw = roles = ext = None
    prev_trace_path = flow.g_trace.path
    if run_dir is None:
        import tempfile
        run_dir = tempfile.mkdtemp(prefix="fdbtpu-run-")
    else:
        os.makedirs(run_dir, exist_ok=True)
    try:
        from ..rpc.gateway import TcpGateway
        from ..server import SimCluster
        from ..server import dbinfo as dbi
        if trace:
            # host-side trace file in the shared run dir: the
            # resolver/tlog legs of every sampled commit land here
            flow.reset_trace(os.path.join(
                run_dir, f"trace.cluster-host.{os.getpid()}.jsonl"))
            flow.trace.set_process_identity("cluster-host")
        if role_processes:
            # role hosts first: recruitment needs their control
            # endpoints live before the master's first epoch
            roles = RoleProcs(
                n_resolvers=n_resolvers, n_tlogs=1, run_dir=run_dir,
                state_root=state_root
                or os.path.join(run_dir, "state"),
                seed=seed, resolve_cost=resolve_cost, trace=trace)
            roles.spawn_all().wait_ready()
        cluster = SimCluster(seed=seed, virtual=False, n_proxies=1,
                             n_resolvers=n_resolvers, n_storage=1,
                             n_logs=1)
        if roles is not None:
            # attach point: constructed but not yet ticked — the
            # master's recruitment phase sees it on its first epoch
            ext = roles.external_roles()
            cluster.cc.external_roles = ext
        if trace:
            # AFTER cluster construction: SimCluster re-seeds the knob
            # set, which would silently disarm an earlier set()
            flow.SERVER_KNOBS.set("trace_propagation", 1)
        # host-side CPU attribution for the role_cpu_share fold: the
        # scheduler's per-task busy table + this process's OS counters
        flow.get_scheduler().start_task_stats()
        host_pm = ProcessMetrics(role="cluster-host")
        gw = TcpGateway(cluster.client("benchgw"), cluster=cluster)

        results: list = []
        errors: list = []

        def run_worker(idx: int) -> None:
            cfg = {"host": "127.0.0.1", "port": gw.port,
                   "seed": seed + 1000 * (idx + 1), "index": idx,
                   "duration": duration,
                   "rate": rate / n_proxies,
                   "run_dir": run_dir,
                   "trace": int(bool(trace)),
                   "trace_roll_size":
                       int(flow.SERVER_KNOBS.trace_roll_size),
                   "sample_every": sample_every if trace else 0,
                   "commit_cost": commit_cost,
                   "batch_cap": batch_cap,
                   "max_inflight": max_inflight}
            try:
                p = subprocess.run(
                    [sys.executable, "-m",
                     "foundationdb_tpu.tools.clusterbench",
                     "--worker", json.dumps(cfg)],
                    capture_output=True, text=True,
                    timeout=duration + 120)
                lines = [ln for ln in p.stdout.splitlines() if ln.strip()]
                if p.returncode != 0 or not lines:
                    errors.append(f"worker {idx}: rc={p.returncode} "
                                  f"stderr={p.stderr[-2000:]}")
                    return
                results.append(json.loads(lines[-1]))
            except Exception as e:  # noqa: BLE001 — collected, reported
                errors.append(f"worker {idx}: {e!r}")

        async def main():
            gw.start()
            while cluster.cc.dbinfo.get().recovery_state != \
                    dbi.FULLY_RECOVERED:
                await flow.delay(0.05)
            threads = [threading.Thread(target=run_worker, args=(i,),
                                        daemon=True)
                       for i in range(n_proxies)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            while any(t.is_alive() for t in threads):
                await flow.delay(0.1)
            wall = time.perf_counter() - t0
            return wall

        wall = cluster.run(main(), timeout_time=duration + 300)
        if errors:
            raise RuntimeError("; ".join(errors))
        agg = {"proxies": n_proxies, "resolvers": n_resolvers,
               "mode": "tcp", "unit": "wall",
               "worker_processes": n_proxies,
               "run_dir": run_dir,
               "wall_seconds": round(wall, 2)}
        for c in ("offered", "shed", "committed", "conflicted",
                  "too_old", "errors"):
            agg[c] = sum(r[c] for r in results)
        agg["divergent_verdicts"] = (agg["conflicted"] + agg["too_old"]
                                     + agg["errors"])
        elapsed = max(r.get("elapsed", duration) for r in results) \
            if results else duration
        agg["elapsed"] = round(elapsed, 3)
        agg["txn_per_s"] = round(agg["committed"] / max(1e-9, elapsed), 1)
        agg["attainment"] = round(
            agg["offered"] / max(1, agg["offered"] + agg["shed"]), 4)
        agg["grv"] = results[0]["grv"] if results else {}
        agg["commit"] = results[0]["commit"] if results else {}
        # per-OS-process telemetry + the federated role CPU fold
        # (ISSUE 19): host sim-task share weighted by host CPU, worker
        # proxies' and role hosts' whole CPU under their roles
        host_share = role_cpu_share(
            flow.get_scheduler().task_stats_report().get("tasks"))
        host_sample = host_pm.sample()
        agg["host_proc"] = host_sample
        agg["worker_procs"] = [r["proc"] for r in results
                               if r.get("proc")]
        role_docs: list = []
        if roles is not None:
            from .exporter import fetch_process_docs
            role_docs = fetch_process_docs(
                run_dir, stubs=roles.status_stubs())
            agg["role_processes"] = {"resolvers": roles.n_resolvers,
                                     "tlogs": roles.n_tlogs}
            agg["role_procs"] = [
                {k: d.get(k) for k in
                 ("process", "role", "name", "pid", "up", "uptime_s",
                  "counters", "version", "process_metrics")}
                for d in role_docs]
            cap = role_cell_capacity(n_proxies, n_resolvers,
                                     resolve_cost, commit_cost)
            if cap != float("inf"):
                agg["capacity_model_txn_per_s"] = round(cap, 1)
        agg["role_cpu_share"] = federated_role_cpu_share(
            host_share, host_sample.get("cpu_seconds"),
            [{"role": s.get("role"), "process_metrics": s}
             for s in agg["worker_procs"]] + role_docs)
        out(f"  tcp{'-roleproc' if roles is not None else ''} "
            f"{n_proxies}x{n_resolvers}: {agg['txn_per_s']}/s "
            f"committed={agg['committed']} "
            f"divergent={agg['divergent_verdicts']} "
            f"trace-run-dir={run_dir}")
        return agg
    finally:
        if gw is not None:
            gw.close()
        if ext is not None:
            ext.close()
        if cluster is not None:
            cluster.shutdown()
        if roles is not None:
            roles.terminate_all()
        if trace:
            # host spans flushed into the run dir, then the shared
            # collector goes back exactly where the caller had it
            flow.g_trace_batch.dump()
            flow.reset_trace(prev_trace_path)
            flow.trace.clear_process_identity()
            flow.SERVER_KNOBS.set("trace_propagation", 0)
        flow.set_scheduler(prev_sched)
        _rng.restore_rng_state(prev_rng)


def worker_trace_setup(role: str, cfg: dict) -> None:
    """Per-process TraceCollector hygiene for worker OS processes
    (ISSUE 16 satellite): a role+pid-stamped trace file under the
    shared run directory, the TRACE_PROPAGATION knob armed when the
    driver asked for it, and the trace tail flushed on atexit AND on
    SIGTERM — a worker the soak harness kills must not lose its spans.
    (SIGKILL still loses whatever the OS buffers — the collector is
    line-buffered, so at most the current line.)"""
    import atexit
    import signal
    pid = os.getpid()
    run_dir = cfg.get("run_dir")
    # the HOST collector's roll size governs the workers too (ISSUE 17
    # satellite): the driver ships its trace_roll_size knob in the
    # worker cfg, so an hours-long soak's per-worker trace files rotate
    # into .N segments instead of growing unbounded — set BEFORE
    # reset_trace so the fresh collector sizes against it
    if cfg.get("trace_roll_size"):
        flow.SERVER_KNOBS.set("trace_roll_size",
                              int(cfg["trace_roll_size"]))
    if run_dir:
        flow.reset_trace(os.path.join(run_dir,
                                      f"trace.{role}.{pid}.jsonl"))
        # always-on flight recorder (ISSUE 18): ring of recent trace
        # events, auto-dumped into the shared run dir on SevError so a
        # worker that dies screaming leaves its last moments behind
        flow.g_flightrec.arm(dump_dir=run_dir, name=f"{role}.{pid}")
    flow.trace.set_process_identity(
        role, addr=f"{cfg['host']}:{cfg['port']}")
    if cfg.get("trace"):
        flow.SERVER_KNOBS.set("trace_propagation", 1)

    def _flush_traces() -> None:
        try:
            flow.g_trace_batch.dump()
            flow.g_trace.flush()
        except Exception:  # noqa: BLE001 — never mask process exit
            pass

    def _on_sigterm(signum, _frame) -> None:
        _flush_traces()
        os._exit(128 + signum)

    atexit.register(_flush_traces)
    signal.signal(signal.SIGTERM, _on_sigterm)


def write_proc_file(run_dir: str, role: str, port: int,
                    status_token: int) -> str:
    """The discovery stub federated status readers key on
    (proc.<role>.<pid>.json): where this worker's StatusRequest
    endpoint listens."""
    pid = os.getpid()
    path = os.path.join(run_dir, f"proc.{role}.{pid}.json")
    with open(path, "w") as fh:
        json.dump({"name": f"{role}:{pid}", "role": role, "pid": pid,
                   "host": "127.0.0.1", "port": port,
                   "status_token": status_token}, fh)
        fh.write("\n")
    return path


def run_worker(cfg: dict) -> dict:
    """Proxy-worker entry (one OS process): fetch the peer-describe
    document, build a real Proxy role whose downstream refs are all
    TcpRefs into the cluster host, and drive this worker's share of
    the seeded workload through it. Prints the result JSON as the last
    stdout line."""
    prev_sched = flow.get_scheduler()
    prev_rng = _rng.rng_state()
    transport = None
    try:
        from ..rpc.gateway import DESCRIBE_TOKEN, PEER_DESCRIBE
        from ..rpc.network import SimNetwork
        from ..rpc.tcp import (RetryingTcpRef, TcpRequestStream,
                               TcpTransport)
        from ..server.process_metrics import ProcessMetrics, \
            loop_lag_probe
        from ..server.proxy import Proxy
        flow.set_seed(int(cfg["seed"]))
        s = flow.Scheduler(virtual=False)
        flow.set_scheduler(s)
        role = cfg.get("role", f"proxy-{cfg['index']}")
        worker_trace_setup(role, cfg)
        # bench knob arming shipped from the driver (role-per-process
        # cells model BOTH serial resources: the external resolver's
        # resolve cost and this worker proxy's commit cost)
        if cfg.get("commit_cost"):
            flow.SERVER_KNOBS.set("sim_commit_cost_per_txn",
                                  float(cfg["commit_cost"]))
        if cfg.get("batch_cap"):
            flow.SERVER_KNOBS.set("commit_transaction_batch_count_max",
                                  int(cfg["batch_cap"]))
        net = SimNetwork(s, flow.g_random)
        proc = net.new_process(f"benchproxy-{cfg['index']}",
                               machine=f"benchproxy-{cfg['index']}")
        transport = TcpTransport()
        # federated status (ISSUE 16): every worker serves
        # StatusRequest on its own transport; the proc file tells
        # exporter --federate / the soak driver where
        status_stream = TcpRequestStream(transport)
        if cfg.get("run_dir"):
            write_proc_file(cfg["run_dir"], role, transport.port,
                            status_stream.token)
        host, port = cfg["host"], int(cfg["port"])
        live: dict = {}
        started = time.perf_counter()
        pid = os.getpid()
        metrics = ProcessMetrics(role=role)

        def worker_status() -> dict:
            counts = live.get("counts") or {}
            return {
                "process": f"{role}:{pid}", "role": role, "pid": pid,
                "machine_id": f"benchproxy-{cfg['index']}",
                "uptime_s": round(time.perf_counter() - started, 3),
                "counters": dict(counts),
                "grv": _lat_ms(list(live.get("grv_lat") or [])),
                "commit": _lat_ms(list(live.get("commit_lat") or [])),
                "process_metrics": metrics.sample(),
                "flightrec": flow.g_flightrec.status(),
            }

        async def status_loop():
            while True:
                _req, reply = await status_stream.pop()
                reply.send(worker_status())

        async def main():
            transport.start()
            flow.spawn(status_loop())
            flow.spawn(loop_lag_probe(metrics))
            describe = transport.ref(host, port, DESCRIBE_TOKEN)
            doc = None
            for _ in range(50):
                try:
                    doc = await flow.timeout_error(
                        describe.get_reply(PEER_DESCRIBE), 5.0)
                    break
                except flow.FdbError:
                    await flow.delay(0.2)
            if doc is None:
                raise RuntimeError("peer describe never became ready")

            def tref(token):
                return transport.ref(host, port, token)

            def pref(entry, key):
                # role-per-process entries carry the role host's OWN
                # addr (tools/rolehost.py): connect directly, wrapped
                # in a retrying ref so a role kill -9 + same-port
                # respawn heals through role idempotency. Plain int
                # entries are classic gateway tokens.
                if isinstance(entry, dict) and "addr" in entry:
                    h, p = entry["addr"]
                    return RetryingTcpRef(
                        transport.ref(h, int(p), int(entry[key])))
                return tref(entry[key] if isinstance(entry, dict)
                            else entry)

            proxy = Proxy(
                proc, tref(doc["master"]),
                [pref(r, "resolves") for r in doc["resolvers"]],
                [pref(t, "commits") for t in doc["tlogs"]],
                resolver_splits=tuple(doc["resolver_splits"]),
                storage_splits=tuple(doc["storage_splits"]),
                storage_tags=tuple(doc["storage_tags"]),
                recovery_version=int(doc["recovery_version"]))
            proxy.set_peers([tref(t)
                             for t in doc["proxy_raw_committed"]])
            proxy.start()

            def grv_send(req, reply):
                proxy.grvs.stream.send((req, reply))

            def commit_send(_i, req, reply):
                proxy.commits.stream.send((req, reply))

            # priming commit: this worker may start several wall
            # seconds after recovery (subprocess + import time), when
            # the cluster-wide committed version still dates from the
            # recovery epoch while the master's next assignment tracks
            # the wall clock — a read txn driven off that stale first
            # GRV would resolve outside the MVCC window and surface as
            # a spurious too_old "divergence". One blind write (no
            # read ranges: never too_old by definition) advances the
            # committed version to now before the measured workload.
            from ..server.types import (CommitRequest,
                                        GetReadVersionRequest,
                                        MutationRef, SET_VALUE)
            pk = b"\x00sb-prime/%d" % int(cfg["index"])
            reply = Promise()
            grv_send(GetReadVersionRequest(), reply)
            ver0 = (await reply.future).version
            reply = Promise()
            commit_send(0, CommitRequest(
                ver0, (), ((pk, pk + b"\x00"),),
                (MutationRef(SET_VALUE, pk, b"p"),)), reply)
            await reply.future

            counts = await _drive_commits(
                grv_send, commit_send, seed=int(cfg["seed"]),
                duration=float(cfg["duration"]),
                rate=float(cfg["rate"]),
                key_prefix=b"sb/%d/" % int(cfg["index"]),
                clock=time.perf_counter,
                max_inflight=int(cfg.get("max_inflight", 2048)),
                sample_every=int(cfg.get("sample_every", 0)),
                debug_prefix=f"cb{cfg['index']}-", live=live)
            counts["index"] = cfg["index"]
            # per-OS-process CPU/RSS for the cell artifact: the
            # role_cpu_share fold and the SYSBENCH before/after rows
            counts["proc"] = metrics.sample()
            return counts

        t = s.spawn(main())
        return s.run(until=t, timeout_time=float(cfg["duration"]) + 90)
    finally:
        if transport is not None:
            transport.close()
        # worker spans belong to the run dir — land them before the
        # process (and its trace file handle) goes away
        try:
            flow.g_trace_batch.dump()
            flow.g_trace.flush()
        except Exception:  # noqa: BLE001 — exiting anyway
            pass
        flow.g_flightrec.disarm()
        flow.set_scheduler(prev_sched)
        _rng.restore_rng_state(prev_rng)


# -------------------------------------------------------------------- driver
def run_matrix(modes=("inprocess", "tcp"), grid=GRID, *, seed: int = 0,
               duration: float = 2.0, rate: float = 12000.0,
               tcp_duration: float = 3.0, tcp_rate: float = 6000.0,
               role_processes: bool = False,
               role_resolve_cost: float = ROLE_RESOLVE_COST,
               role_commit_cost: float = ROLE_COMMIT_COST,
               role_headroom: float = ROLE_HEADROOM,
               role_max_inflight: int = ROLE_MAX_INFLIGHT,
               out=print) -> dict:
    cells: dict = {"inprocess": {}, "tcp": {}}
    for p in grid:
        for r in grid:
            if "inprocess" in modes:
                cells["inprocess"][f"{p}x{r}"] = run_inprocess_cell(
                    p, r, seed=seed, duration=duration, rate=rate,
                    out=out)
            if "tcp" in modes:
                if role_processes:
                    # offered load tracks the CELL's modeled capacity
                    # (role_cell_capacity) at a fixed headroom — a flat
                    # grid-wide rate would either starve the big cells
                    # or drown the small ones in drain tail
                    cell_rate = role_headroom * role_cell_capacity(
                        p, r, role_resolve_cost, role_commit_cost)
                else:
                    cell_rate = tcp_rate
                cells["tcp"][f"{p}x{r}"] = run_tcp_cell(
                    p, r, seed=seed, duration=tcp_duration,
                    rate=cell_rate, role_processes=role_processes,
                    resolve_cost=(role_resolve_cost
                                  if role_processes else 0.0),
                    commit_cost=(role_commit_cost
                                 if role_processes else 0.0),
                    max_inflight=(role_max_inflight
                                  if role_processes else 2048),
                    out=out)
    tcp_config = {"duration_wall_s": tcp_duration,
                  "offered_rate": tcp_rate}
    if role_processes:
        tcp_config = {"duration_wall_s": tcp_duration,
                      "role_processes": True,
                      "resolve_cost_per_txn_s": role_resolve_cost,
                      "commit_cost_per_txn_s": role_commit_cost,
                      "offered_headroom": role_headroom,
                      "max_inflight_per_worker": role_max_inflight}
    doc = {
        "metric": "system_committed_txn_per_s",
        "config": {
            "seed": seed, "grid": list(grid),
            "inprocess": {"duration_sim_s": duration,
                          "offered_rate": rate,
                          "batch_cap": BATCH_CAP,
                          "resolve_cost_per_txn_s": RESOLVE_COST},
            "tcp": tcp_config,
        },
        "cells": cells,
    }
    ip = cells.get("inprocess") or {}
    if "1x1" in ip and "4x4" in ip:
        base = ip["1x1"]["txn_per_s"] or 1
        doc["headline"] = {
            "inprocess_4x4_vs_1x1": round(ip["4x4"]["txn_per_s"] / base,
                                          2)}
    tcp = cells.get("tcp") or {}
    if tcp:
        doc.setdefault("headline", {})["tcp_divergent_verdicts"] = sum(
            c["divergent_verdicts"] for c in tcp.values())
    if "1x1" in tcp and "4x4" in tcp:
        base = tcp["1x1"]["txn_per_s"] or 1
        doc.setdefault("headline", {})["tcp_4x4_vs_1x1"] = round(
            tcp["4x4"]["txn_per_s"] / base, 2)
    return doc


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    seed = int(os.environ.get("CLUSTERBENCH_SEED", 0))
    out_path = REPORT_PATH
    mode = None
    processes = None
    proxies = resolvers = None
    duration = None
    rate = None
    matrix = False
    trace = False
    run_dir = None
    role_procs = False
    resolve_cost = commit_cost = None
    max_inflight = None
    while argv:
        a = argv.pop(0)
        if a == "--worker":
            print(json.dumps(run_worker(json.loads(argv.pop(0)))))
            return 0
        if a == "--matrix":
            matrix = True
        elif a == "--mode":
            mode = argv.pop(0)
        elif a == "--processes":
            processes = int(argv.pop(0))
        elif a == "--proxies":
            proxies = int(argv.pop(0))
        elif a == "--resolvers":
            resolvers = int(argv.pop(0))
        elif a == "--duration":
            duration = float(argv.pop(0))
        elif a == "--rate":
            rate = float(argv.pop(0))
        elif a == "--seed":
            seed = int(argv.pop(0))
        elif a == "--out":
            out_path = argv.pop(0)
        elif a == "--trace":
            trace = True
        elif a == "--run-dir":
            run_dir = argv.pop(0)
        elif a == "--role-processes":
            role_procs = True
        elif a == "--resolve-cost":
            resolve_cost = float(argv.pop(0))
        elif a == "--commit-cost":
            commit_cost = float(argv.pop(0))
        elif a == "--max-inflight":
            max_inflight = int(argv.pop(0))
        else:
            print(f"unknown argument {a!r}")
            return 2
    if matrix:
        modes = (mode,) if mode else ("inprocess", "tcp")
        doc = run_matrix(
            modes, seed=seed, role_processes=role_procs,
            duration=duration or 2.0,
            tcp_duration=12.0 if role_procs else 3.0, out=print)
    elif processes is not None:
        # the CI small shape: N proxy worker processes over real TCP
        # (--role-processes puts the resolvers and the tlog in their
        # own OS processes too; costs default to 0 — CI measures the
        # zero-divergence property, not the capacity model)
        doc = {"metric": "system_committed_txn_per_s",
               "cells": {"tcp": {}}}
        cell = run_tcp_cell(processes, resolvers or processes,
                            seed=seed, duration=duration or 3.0,
                            rate=rate or 2000.0, run_dir=run_dir,
                            trace=trace, role_processes=role_procs,
                            resolve_cost=resolve_cost or 0.0,
                            commit_cost=commit_cost or 0.0,
                            max_inflight=max_inflight or 2048,
                            out=print)
        doc["cells"]["tcp"][f"{processes}x{resolvers or processes}"] = \
            cell
        doc["headline"] = {
            "tcp_divergent_verdicts": cell["divergent_verdicts"]}
        if cell["divergent_verdicts"] or cell["committed"] == 0:
            with open(out_path, "w") as fh:
                json.dump(doc, fh, indent=2, sort_keys=True)
            print("FAIL: divergent verdicts or zero commits")
            return 1
    else:
        p, r = proxies or 2, resolvers or 2
        doc = {"metric": "system_committed_txn_per_s",
               "cells": {"inprocess": {
                   f"{p}x{r}": run_inprocess_cell(
                       p, r, seed=seed, duration=duration or 2.0,
                       rate=rate or 12000.0, out=print)}}}
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    # final summary line names the trace run dir when one exists so a
    # human (or CI log grep) can hand it straight to tracemerge
    dirs = sorted({c["run_dir"] for cells in doc["cells"].values()
                   for c in cells.values() if c.get("run_dir")})
    suffix = f" trace-run-dir={dirs[0]}" if dirs else ""
    print(f"report -> {out_path}{suffix}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
