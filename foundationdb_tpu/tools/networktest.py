"""Transport microbenchmark: request/reply throughput and latency.

Reference: fdbserver -r networktestserver / networktest
(fdbserver/networktest.actor.cpp) — a ping server and a client loop
measuring the RPC path in isolation. Here it exercises the real TCP
transport (frames, wire encoding, reader/writer threads) over
loopback: `python -m foundationdb_tpu.tools.networktest [--requests N]
[--parallel P] [--bytes B]`.
"""

from __future__ import annotations

import sys
import time
from typing import List, Optional

from .. import flow
from ..flow import rng
from ..rpc.tcp import TcpRequestStream, TcpTransport


def run_networktest(requests: int = 2000, parallel: int = 16,
                    payload_bytes: int = 64) -> dict:
    if requests <= 0:
        return {"requests": 0, "parallel": 0, "payload_bytes": payload_bytes,
                "requests_per_second": 0.0, "p50_ms": 0.0, "p99_ms": 0.0}
    parallel = max(1, min(parallel, requests))
    # this tool hosts its OWN wall-clock loop and reseeds the ambient
    # RNG; a caller already running a flow loop (a test, a seeded sim)
    # must get both back EXACTLY as they were — restore in the finally
    # below (ISSUE 15 satellite; clusterbench shares the discipline)
    prev_sched = flow.get_scheduler()
    prev_rng = rng.rng_state()
    flow.set_seed(0)
    s = flow.Scheduler(virtual=False)
    flow.set_scheduler(s)
    server = TcpTransport()
    client = TcpTransport()
    try:
        stream = TcpRequestStream(server)
        server.start()
        client.start()
        payload = b"x" * payload_bytes

        async def serve():
            while True:
                req, reply = await stream.pop()
                reply.send(req)

        async def worker(ref, n, lat):
            for _ in range(n):
                t0 = time.perf_counter()
                got = await ref.get_reply(payload)
                lat.append(time.perf_counter() - t0)
                assert got == payload

        async def main():
            flow.spawn(serve())
            ref = client.ref("127.0.0.1", server.port, stream.token)
            await ref.get_reply(b"warmup")
            lat: List[float] = []
            per, extra = divmod(requests, parallel)
            t0 = time.perf_counter()
            await flow.wait_for_all([
                flow.spawn(worker(ref, per + (1 if i < extra else 0), lat))
                for i in range(parallel)])
            wall = time.perf_counter() - t0
            lat.sort()
            return {
                "requests": len(lat),
                "parallel": parallel,
                "payload_bytes": payload_bytes,
                "requests_per_second": round(len(lat) / wall, 1),
                "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
            }

        t = s.spawn(main())
        return s.run(until=t, timeout_time=600)
    finally:
        server.close()
        client.close()
        flow.set_scheduler(prev_sched)
        rng.restore_rng_state(prev_rng)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    kw = {}
    run_dir = None
    while argv:
        a = argv.pop(0)
        if a == "--requests":
            kw["requests"] = int(argv.pop(0))
        elif a == "--parallel":
            kw["parallel"] = int(argv.pop(0))
        elif a == "--bytes":
            kw["payload_bytes"] = int(argv.pop(0))
        elif a == "--run-dir":
            run_dir = argv.pop(0)
    # CLI runs land their trace events in a run directory and name it
    # in the final summary line, same contract as clusterbench
    # (ISSUE 16 satellite) — tracemerge takes the directory as-is
    import json
    import os
    import tempfile
    if run_dir is None:
        run_dir = tempfile.mkdtemp(prefix="fdbtpu-run-")
    else:
        os.makedirs(run_dir, exist_ok=True)
    prev_trace_path = flow.g_trace.path
    flow.reset_trace(os.path.join(
        run_dir, f"trace.networktest.{os.getpid()}.jsonl"))
    flow.trace.set_process_identity("networktest")
    try:
        result = run_networktest(**kw)
    finally:
        flow.g_trace_batch.dump()
        flow.reset_trace(prev_trace_path)
        flow.trace.clear_process_identity()
    result["trace_run_dir"] = run_dir
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
