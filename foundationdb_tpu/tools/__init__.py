"""Tools: operator-facing surfaces (ref: fdbcli/, fdbbackup/)."""
