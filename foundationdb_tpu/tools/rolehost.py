"""Role host: one OS process hosting one externally-recruited cluster
role (resolver or tlog) behind fixed TCP tokens.

The reference runs every role in its own `fdbserver` process (SURVEY
layer 4: worker -> {master, proxy, resolver, tlog, storage}); this is
that shape for the resolver and tlog halves of the commit pipeline
(ROADMAP item 2). The cluster host recruits a role here with an init
RPC over the control token, then every proxy — in-host or a
clusterbench worker process — fans resolves/commits out to this
process over rpc/tcp.py. Token layout is FIXED so a respawned host on
the same port serves the same refs (the reference re-recruits after a
process death; we instead make the endpoint survive it, which is what
lets a kill -9 heal without a whole-cluster recovery):

    control = 1
    resolver: resolves = 2, metrics = 3, handoffs = 4, status = 5
    tlog:     commits = 2, peeks = 3, pops = 4, locks = 5, status = 6

Resolver recovery plane (the PR 5 checkpoint + replay discipline moved
across the process boundary): every accepted resolve/install request
is journaled (length-prefixed rpc/wire frames, flushed before the role
can reply), and a checkpoint actor periodically persists the conflict
state TOGETHER WITH the duplicate-delivery reply cache — without the
cache, a proxy retrying a batch at-or-below the checkpoint version
after a kill -9 would hit the aged-out conflict-everything path and
diverge. On respawn the host restores the checkpoint, replays the
gapless journal prefix above it (modeled service cost disarmed), and
only then opens the pumps; batches lost in flight are re-driven by the
proxies' RetryingTcpRefs and land on the restored reply cache /
version chain idempotently.
"""

from __future__ import annotations

import json
import os
import struct
import sys
import time
from typing import List, Optional

from .. import flow
from ..flow import rng as _rng
from ..flow.future import Promise

CONTROL_TOKEN = 1
RESOLVER_TOKENS = {"resolves": 2, "metrics": 3, "handoffs": 4,
                   "status": 5}
TLOG_TOKENS = {"commits": 2, "peeks": 3, "pops": 4, "locks": 5,
               "status": 6}

_REC_HDR = struct.Struct("<BI")     # tag, payload length
REC_RESOLVE, REC_INSTALL = 0, 1


class _LocalReply:
    """Reply sink for journal replay: verdicts recomputed during replay
    go nowhere (their proxies already have them, or will retry)."""

    __slots__ = ("promise",)

    def __init__(self):
        self.promise = Promise()

    def send(self, value=None) -> None:
        if not self.promise.is_set:
            self.promise.send(value)

    def send_error(self, err) -> None:
        if not self.promise.is_set:
            self.promise.send_error(err)


class ResolverJournal:
    """Segmented on-disk journal + checkpoint for an external resolver.

    Segments rotate at each checkpoint; a rotated segment is deleted
    once the checkpointed version covers every resolve it holds (its
    max recorded version), so any record above the checkpoint version
    survives — the replayable chain is complete by construction."""

    def __init__(self, state_dir: str):
        self.dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self._seg_max: dict[int, int] = {}     # seq -> max resolve version
        self._seq = 0
        self._fh = None

    # -- paths -----------------------------------------------------------
    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"journal.{seq:06d}.bin")

    @property
    def ckpt_path(self) -> str:
        return os.path.join(self.dir, "ckpt.bin")

    @property
    def init_path(self) -> str:
        return os.path.join(self.dir, "init.json")

    def segments(self) -> List[int]:
        seqs = []
        for f in os.listdir(self.dir):
            if f.startswith("journal.") and f.endswith(".bin"):
                seqs.append(int(f.split(".")[1]))
        return sorted(seqs)

    def has_state(self) -> bool:
        return os.path.exists(self.init_path)

    # -- writing ---------------------------------------------------------
    def open_segment(self, seq: Optional[int] = None) -> None:
        if self._fh is not None:
            self._fh.close()
        self._seq = self._seq + 1 if seq is None else seq
        self._fh = open(self._seg_path(self._seq), "ab")
        self._seg_max.setdefault(self._seq, 0)

    def append(self, tag: int, payload: bytes, version: int = 0) -> None:
        self._fh.write(_REC_HDR.pack(tag, len(payload)) + payload)
        self._fh.flush()
        if version > self._seg_max.get(self._seq, 0):
            self._seg_max[self._seq] = version

    def write_init(self, doc: dict) -> None:
        tmp = self.init_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, self.init_path)

    def read_init(self) -> dict:
        with open(self.init_path) as fh:
            return json.load(fh)

    def write_checkpoint(self, doc_bytes: bytes, version: int) -> None:
        tmp = self.ckpt_path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(doc_bytes)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.ckpt_path)
        # rotate, then retire every rotated segment the checkpoint
        # fully covers
        self.open_segment()
        for seq in list(self._seg_max):
            if seq != self._seq and self._seg_max[seq] <= version:
                try:
                    os.unlink(self._seg_path(seq))
                except OSError:
                    pass
                del self._seg_max[seq]

    # -- reading ---------------------------------------------------------
    def read_checkpoint(self) -> Optional[bytes]:
        try:
            with open(self.ckpt_path, "rb") as fh:
                return fh.read()
        except OSError:
            return None

    def read_records(self) -> list:
        """All surviving (tag, payload) records in write order; a torn
        tail record (the kill landed mid-write) is dropped."""
        from ..rpc import wire
        out = []
        for seq in self.segments():
            with open(self._seg_path(seq), "rb") as fh:
                buf = fh.read()
            off = 0
            while off + _REC_HDR.size <= len(buf):
                tag, ln = _REC_HDR.unpack_from(buf, off)
                off += _REC_HDR.size
                if off + ln > len(buf):
                    break
                try:
                    out.append((tag, wire.from_bytes(buf[off:off + ln],
                                                     None)))
                except wire.WireError:
                    break
                off += ln
            # rebuild the rotation bookkeeping for this boot
            self._seg_max[seq] = max(
                [r.version for t, r in out if t == REC_RESOLVE] or [0])
        return out


# ----------------------------------------------------------------- worker
def run_rolehost(cfg: dict) -> int:
    """Role-host process entry. cfg: role (resolver|tlog), name, index,
    port (0 first boot, pinned on respawn), run_dir, state_dir
    (resolver persistence), seed, backend, resolve_cost,
    checkpoint_every, trace, trace_roll_size, host."""
    prev_sched = flow.get_scheduler()
    prev_rng = _rng.rng_state()
    transport = None
    try:
        from ..rpc.network import SimNetwork
        from ..rpc.tcp import TcpRequestStream, TcpTransport
        from ..server.process_metrics import ProcessMetrics, \
            loop_lag_probe
        from .clusterbench import worker_trace_setup, write_proc_file

        role_kind = cfg["role"]
        name = cfg["name"]
        flow.set_seed(int(cfg.get("seed", 0)))
        s = flow.Scheduler(virtual=False)
        flow.set_scheduler(s)
        transport = TcpTransport(port=int(cfg.get("port", 0)))
        cfg = dict(cfg, port=transport.port)
        worker_trace_setup(name, cfg)
        net = SimNetwork(s, flow.g_random)
        proc = net.new_process(name, machine=name)
        metrics = ProcessMetrics(role=name)

        control = TcpRequestStream(transport)
        assert control.token == CONTROL_TOKEN
        tokens = RESOLVER_TOKENS if role_kind == "resolver" \
            else TLOG_TOKENS
        streams = {}
        for key in tokens:
            st = TcpRequestStream(transport)
            assert st.token == tokens[key], (key, st.token)
            streams[key] = st

        run_dir = cfg.get("run_dir")
        if run_dir:
            write_proc_file(run_dir, name, transport.port,
                            tokens["status"])
        state = {"role": None, "counters": {"requests": 0,
                                            "journaled": 0,
                                            "replayed": 0,
                                            "checkpoints": 0}}
        started = time.perf_counter()
        pid = os.getpid()
        journal = (ResolverJournal(cfg["state_dir"])
                   if role_kind == "resolver" and cfg.get("state_dir")
                   else None)

        def write_ready() -> None:
            if not run_dir:
                return
            path = os.path.join(run_dir, f"ready.{name}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"name": name, "role": role_kind, "pid": pid,
                           "host": cfg.get("host", "127.0.0.1"),
                           "port": transport.port,
                           "tokens": dict(tokens),
                           "control": CONTROL_TOKEN,
                           "recovered": journal is not None
                           and journal.has_state()}, fh)
            os.replace(tmp, path)

        # ---------------------------------------------------- role build
        def build_resolver(recovery_version: int, backend: str):
            from ..server.resolver_role import Resolver
            r = Resolver(proc, backend=backend,
                         recovery_version=recovery_version)
            r.start()
            return r

        def build_tlog(store: str, recovery_version: int):
            from ..server.tlog import TLog
            t = TLog(proc, disk=None, name=store,
                     recovery_version=recovery_version)
            t.start()
            return t

        async def feed(stream, req):
            reply = _LocalReply()
            stream.stream.send((req, reply))
            return await flow.timeout_error(reply.promise.future, 60.0)

        async def recover_resolver():
            """Respawn path: checkpoint + reply cache restore, then the
            gapless journal prefix replayed with the modeled cost
            disarmed — deterministic recompute, not re-resolution."""
            from ..rpc import wire
            init = journal.read_init()
            records = journal.read_records()
            ck = journal.read_checkpoint()
            version = int(init["recovery_version"])
            doc = None
            if ck is not None:
                doc = wire.from_bytes(ck, None)
                version = int(doc["version"])
            flow.SERVER_KNOBS.set("sim_resolve_cost_per_txn", 0.0)
            role = build_resolver(version, init.get("backend", "python"))
            if doc is not None:
                role.conflict_set.restore(doc["ckpt"])
                role._reply_cache = dict(doc["replies"])
                from collections import deque
                role._reply_order = deque(doc["order"])
            # installs always re-graft (pointwise max: idempotent);
            # resolves replay only the gapless chain above the
            # checkpoint — anything past a hole was never replied and
            # the proxies' retries re-drive it live
            cur = version
            resolves = sorted(
                {r.version: r for t, r in records
                 if t == REC_RESOLVE}.values(),
                key=lambda r: r.version)
            installs = [r for t, r in records if t == REC_INSTALL]
            for req in installs:
                await feed(role.handoffs, req)
            for req in resolves:
                if req.version <= cur:
                    continue
                if req.prev_version > cur:
                    break
                await feed(role.resolves, req)
                cur = req.version
                state["counters"]["replayed"] += 1
            journal.open_segment(max(journal.segments() or [0]) + 1)
            flow.SERVER_KNOBS.set("sim_resolve_cost_per_txn",
                                  float(cfg.get("resolve_cost", 0.0)))
            flow.TraceEvent("RoleHostRecovered", name).detail(
                CheckpointVersion=version, ReplayTo=cur,
                Replayed=state["counters"]["replayed"]).log()
            return role

        # --------------------------------------------------------- pumps
        def forward(stream_key: str, role_stream) -> None:
            async def pump():
                st = streams[stream_key]
                while True:
                    req, reply = await st.pop()
                    state["counters"]["requests"] += 1
                    role_stream.stream.send((req, reply))
            flow.spawn(pump(), name=f"{name}.{stream_key}")

        def forward_journaled(stream_key: str, role_stream, tag: int,
                              version_of) -> None:
            from ..rpc import wire

            async def pump():
                st = streams[stream_key]
                while True:
                    req, reply = await st.pop()
                    state["counters"]["requests"] += 1
                    try:
                        journal.append(tag, wire.to_bytes(req),
                                       version_of(req))
                        state["counters"]["journaled"] += 1
                    except wire.WireError:
                        pass    # non-replayable (e.g. checkpoint park)
                    role_stream.stream.send((req, reply))
            flow.spawn(pump(), name=f"{name}.{stream_key}")

        async def handoff_pump(role) -> None:
            """Handoffs split by type: installs (state grafts) are
            journaled, checkpoint parks are pass-through."""
            from ..rpc import wire
            from ..server.types import ResolverInstallRequest
            st = streams["handoffs"]
            while True:
                req, reply = await st.pop()
                state["counters"]["requests"] += 1
                if journal is not None and \
                        isinstance(req, ResolverInstallRequest):
                    journal.append(REC_INSTALL, wire.to_bytes(req))
                    state["counters"]["journaled"] += 1
                role.handoffs.stream.send((req, reply))

        async def status_loop():
            st = streams["status"]
            while True:
                _req, reply = await st.pop()
                role = state["role"]
                doc = {"process": f"{name}:{pid}", "role": role_kind,
                       "name": name, "pid": pid, "machine_id": name,
                       "uptime_s": round(
                           time.perf_counter() - started, 3),
                       "counters": dict(state["counters"]),
                       "process_metrics": metrics.sample(),
                       "flightrec": flow.g_flightrec.status()}
                if role is not None and role_kind == "resolver":
                    doc["version"] = role.version.get()
                reply.send(doc)

        async def control_loop():
            while True:
                req, reply = await control.pop()
                try:
                    op = req.get("type")
                    if op == "init":
                        if state["role"] is None:
                            if journal is not None:
                                journal.write_init(
                                    {"name": req.get("store", name),
                                     "recovery_version":
                                         int(req["recovery_version"]),
                                     "backend": req.get("backend",
                                                        "python")})
                                journal.open_segment(0)
                            if role_kind == "resolver":
                                flow.SERVER_KNOBS.set(
                                    "sim_resolve_cost_per_txn",
                                    float(cfg.get("resolve_cost", 0.0)))
                                role = build_resolver(
                                    int(req["recovery_version"]),
                                    req.get("backend", "python"))
                                start_resolver_pumps(role)
                            else:
                                role = build_tlog(
                                    req.get("store", name),
                                    int(req["recovery_version"]))
                                start_tlog_pumps(role)
                            state["role"] = role
                        reply.send({"ok": True, "pid": pid})
                    elif op == "set_expected_replicas":
                        mapping = {int(k): tuple(v) for k, v in
                                   dict(req["expected"]).items()}
                        state["role"].set_expected_replicas(mapping)
                        reply.send({"ok": True})
                    elif op == "ping":
                        reply.send({"ok": True, "pid": pid,
                                    "ready": state["role"] is not None})
                    elif op == "trace_flush":
                        # the host merges trace files while this
                        # process is still alive — push buffered spans
                        # (TraceBatch holds them below MAX_BUFFERED)
                        # out to disk so tracemerge sees this leg
                        flow.g_trace_batch.dump()
                        flow.g_trace.flush()
                        reply.send({"ok": True})
                    else:
                        reply.send_error(flow.error(
                            "client_invalid_operation"))
                except flow.FdbError as e:
                    if e.name == "operation_cancelled":
                        raise
                    reply.send_error(e)
                except Exception:  # noqa: BLE001 — one bad frame
                    reply.send_error(flow.error("internal_error"))

        def start_resolver_pumps(role) -> None:
            forward_journaled("resolves", role.resolves, REC_RESOLVE,
                              lambda r: r.version) \
                if journal is not None else \
                forward("resolves", role.resolves)
            forward("metrics", role.metrics)
            flow.spawn(handoff_pump(role), name=f"{name}.handoffs")
            if journal is not None:
                flow.spawn(checkpoint_loop(role), name=f"{name}.ckpt")

        def start_tlog_pumps(role) -> None:
            forward("commits", role.commits)
            forward("peeks", role.peeks)
            forward("pops", role.pops)
            forward("locks", role.locks)

        async def checkpoint_loop(role) -> None:
            from ..rpc import wire
            every = float(cfg.get("checkpoint_every", 1.0))
            while True:
                await flow.delay(every)
                if role._inflight:
                    continue    # state mid-pipeline: next tick
                doc = {"version": role.version.get(),
                       "ckpt": role.conflict_set.checkpoint(),
                       "replies": dict(role._reply_cache),
                       "order": list(role._reply_order)}
                try:
                    payload = wire.to_bytes(doc)
                except wire.WireError:
                    continue    # backend without a wire-able checkpoint
                journal.write_checkpoint(payload, doc["version"])
                state["counters"]["checkpoints"] += 1

        async def trace_flush_loop():
            # span dumps otherwise wait for process exit (the finally
            # below) — but a kill -9 never gets there, and the soak's
            # tracemerge runs while this process is still serving.
            # Cheap: dump() walks only what's buffered since last time.
            while True:
                await flow.delay(5.0)
                flow.g_trace_batch.dump()
                flow.g_trace.flush()

        async def main():
            transport.start()
            flow.spawn(status_loop(), name=f"{name}.status")
            flow.spawn(loop_lag_probe(metrics))
            flow.spawn(trace_flush_loop(), name=f"{name}.traceflush")
            if journal is not None and journal.has_state():
                role = await recover_resolver()
                state["role"] = role
                start_resolver_pumps(role)
            flow.spawn(control_loop(), name=f"{name}.control")
            write_ready()
            while True:     # the driver owns this process's lifetime
                await flow.delay(3600.0)

        t = s.spawn(main())
        s.run(until=t)
        return 0
    finally:
        if transport is not None:
            transport.close()
        try:
            flow.g_trace_batch.dump()
            flow.g_trace.flush()
        except Exception:  # noqa: BLE001 — exiting anyway
            pass
        flow.g_flightrec.disarm()
        flow.set_scheduler(prev_sched)
        _rng.restore_rng_state(prev_rng)


# ----------------------------------------------------- host-side directory
class ExternalRoles:
    """The cluster host's directory of externally-hosted roles.

    Attach one to a SimCluster BEFORE its first scheduler tick
    (`cluster.cc.external_roles = ext`): the master's recruitment phase
    then recruits resolvers/tlogs here — an init RPC per role host over
    its control token — instead of on in-process workers, and stashes
    the addr-carrying peer descriptors the TcpGateway serves to worker
    processes. All refs handed back are RetryingTcpRefs, so a role
    process kill -9 + same-port respawn heals through role idempotency
    instead of surfacing as broken_promise."""

    def __init__(self, resolvers=(), tlogs=(),
                 host: str = "127.0.0.1"):
        # each entry: the role host's ready-file doc (port + tokens)
        self.resolvers = list(resolvers)
        self.tlogs = list(tlogs)
        self.host = host
        self._transport = None
        self._names: dict = {}

    @property
    def n_resolvers(self) -> int:
        return len(self.resolvers)

    @property
    def n_tlogs(self) -> int:
        return len(self.tlogs)

    def _tp(self):
        if self._transport is None:
            from ..rpc.tcp import TcpTransport
            self._transport = TcpTransport()
            self._transport.start()
        return self._transport

    def _ref(self, entry: dict, key: str, retry: bool = True):
        from ..rpc.tcp import RetryingTcpRef
        token = entry["tokens"][key] if key != "control" \
            else CONTROL_TOKEN
        ref = self._tp().ref(entry.get("host", self.host),
                             int(entry["port"]), token)
        return RetryingTcpRef(ref) if retry else ref

    async def _control(self, entry: dict, request: dict) -> dict:
        ctrl = self._ref(entry, "control")
        return await flow.timeout_error(ctrl.get_reply(request), 60.0)

    async def recruit_resolver(self, i: int, name: str,
                               recovery_version: int, backend: str):
        entry = self.resolvers[i]
        await self._control(entry, {"type": "init", "store": name,
                                    "recovery_version": recovery_version,
                                    "backend": backend})
        self._names[("resolver", i)] = name
        return (self._ref(entry, "resolves"),
                self._ref(entry, "metrics"),
                self._ref(entry, "handoffs"))

    async def recruit_tlog(self, i: int, store: str,
                           recovery_version: int):
        from ..server.dbinfo import LogRefs
        entry = self.tlogs[i]
        await self._control(entry, {"type": "init", "store": store,
                                    "recovery_version": recovery_version})
        self._names[("tlog", i)] = store
        return LogRefs(store, entry.get("name", f"ext-tlog-{i}"),
                       self._ref(entry, "commits"),
                       self._ref(entry, "peeks"),
                       self._ref(entry, "pops"),
                       self._ref(entry, "locks"))

    async def flush_traces(self) -> int:
        """Ask every live role process to dump its buffered trace
        spans to disk NOW — the host calls this right before
        tracemerge reads the run directory, so the externally-hosted
        resolver/tlog legs of the commit chains are on disk instead of
        parked in each process's TraceBatch buffer. Best-effort: a
        mid-respawn process is skipped, not fatal. Returns the number
        of processes that acknowledged."""
        acked = 0
        for entry in list(self.resolvers) + list(self.tlogs):
            try:
                await self._control(entry, {"type": "trace_flush"})
                acked += 1
            except flow.FdbError:
                continue
        return acked

    async def set_expected_replicas(self, i: int, expected: dict) -> None:
        await self._control(self.tlogs[i],
                            {"type": "set_expected_replicas",
                             "expected": {int(k): tuple(v)
                                          for k, v in expected.items()}})

    def resolver_descriptors(self) -> list:
        return [{"name": self._names.get(("resolver", i),
                                         e.get("name", f"ext-resolver-{i}")),
                 "addr": [e.get("host", self.host), int(e["port"])],
                 "resolves": e["tokens"]["resolves"],
                 "handoffs": e["tokens"]["handoffs"]}
                for i, e in enumerate(self.resolvers)]

    def tlog_descriptors(self) -> list:
        return [{"addr": [e.get("host", self.host), int(e["port"])],
                 "commits": e["tokens"]["commits"]}
                for e in self.tlogs]

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None


def flush_role_traces(entries, host: str = "127.0.0.1",
                      timeout: float = 5.0) -> int:
    """Synchronous best-effort trace flush across live role processes:
    ask each one (by its ready-file doc) to dump its buffered spans to
    disk NOW. Hosts its own wall-clock loop with the ambient
    scheduler/RNG restored on exit (the networktest discipline), so
    the cluster host can call it AFTER its sim scheduler has finished
    — which is exactly when the soak merges the run directory. A
    process that no longer answers (mid-respawn) is skipped. Returns
    the number of processes that acknowledged."""
    from .. import flow
    from ..flow import rng as _rng
    from ..rpc.tcp import TcpTransport
    entries = [e for e in entries if e and e.get("port")]
    if not entries:
        return 0
    prev_sched = flow.get_scheduler()
    prev_rng = _rng.rng_state()
    transport = None
    try:
        flow.set_seed(0)
        s = flow.Scheduler(virtual=False)
        flow.set_scheduler(s)
        transport = TcpTransport()

        async def one(entry: dict) -> int:
            ref = transport.ref(entry.get("host", host),
                                int(entry["port"]), CONTROL_TOKEN)
            try:
                await flow.timeout_error(
                    ref.get_reply({"type": "trace_flush"}), timeout)
                return 1
            except flow.FdbError:
                return 0

        async def run():
            transport.start()
            return sum(await flow.wait_for_all(
                [flow.spawn(one(e)) for e in entries]))

        t = s.spawn(run())
        return s.run(until=t, timeout_time=timeout * len(entries) + 30)
    finally:
        if transport is not None:
            transport.close()
        flow.set_scheduler(prev_sched)
        _rng.restore_rng_state(prev_rng)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--worker":
        return run_rolehost(json.loads(argv[1]))
    print("usage: rolehost --worker '<json cfg>'")
    return 2


if __name__ == "__main__":
    sys.exit(main())
