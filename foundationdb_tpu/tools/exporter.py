"""Prometheus exporter: the cluster status document as scrape text.

Reference: the reference cluster is scraped by parsing `status json`
(the community fdb-exporter pattern); here the status document the
ClusterController assembles (server/cluster_controller.py get_status)
is rendered directly into the Prometheus text exposition format —
every role's counters, the per-stage latency-band histograms, the
TPU-kernel profile gauges, the latency-probe readings, the conflict
hot-spot table, and the health messages — so one scrape covers the
whole commit pipeline.

Use in-process (`render_prometheus(status)`), or serve over HTTP:
`python -m foundationdb_tpu.tools.exporter --connect host:port
[--listen-port 9090]` attaches to a tools.server cluster and serves
GET /metrics.
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, List, Optional, Tuple

_PREFIX = "fdbtpu"


def _esc(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class _Families:
    """Accumulate samples grouped by metric family so each family
    renders one # HELP/# TYPE header (the format requires grouping)."""

    def __init__(self, extra_labels: dict = None):
        self._fams: dict = {}   # name -> (type, help, [(suffix, labels, value)])
        self._order: List[str] = []
        # labels stamped onto every sample added while set — the
        # federated render swaps this per process so one accumulator
        # (and so one HELP/TYPE header per family) covers them all
        self.extra: dict = dict(extra_labels or {})

    def add(self, name: str, mtype: str, help_text: str,
            labels: dict, value, suffix: str = "") -> None:
        """`suffix` names histogram children (`_bucket`, `_count`):
        the TYPE/HELP header goes on the FAMILY name and the samples on
        name+suffix, the grouping strict OpenMetrics parsers require."""
        if value is None:
            return
        if self.extra:
            labels = {**self.extra, **labels}
        fam = self._fams.get(name)
        if fam is None:
            fam = self._fams[name] = (mtype, help_text, [])
            self._order.append(name)
        fam[2].append((suffix, labels, value))

    def render(self) -> str:
        out: List[str] = []
        for name in self._order:
            mtype, help_text, samples = self._fams[name]
            out.append(f"# HELP {name} {help_text}")
            out.append(f"# TYPE {name} {mtype}")
            for suffix, labels, value in samples:
                if labels:
                    lab = ",".join(f'{k}="{_esc(v)}"'
                                   for k, v in labels.items())
                    out.append(f"{name}{suffix}{{{lab}}} {value}")
                else:
                    out.append(f"{name}{suffix} {value}")
        return "\n".join(out) + "\n"


def _band_seconds(band_key: str) -> str:
    # "<=0.005s" -> "0.005"
    return band_key[2:].rstrip("s")


def _add_latency(f: _Families, kind: str, role: str, request: str,
                 snap: dict, stem: str = None) -> None:
    """One RequestLatency snapshot -> a WELL-FORMED Prometheus
    histogram (cumulative `_bucket` counts ordered by `le`, a final
    `+Inf` bucket, and matching `_count`/`_sum` children) plus max and
    quantile gauges (the reservoir percentiles ride a separate family:
    a summary and a histogram may not share a metric name). The raw
    per-band counters additionally ride a `*_band` series, so a
    dashboard keyed on the LatencyBands thresholds keeps working.
    `stem` picks the family name prefix (default: the shared
    request-latency family; the resolve pipeline uses its own)."""
    stem = stem or f"{_PREFIX}_request_latency"
    base = f"{stem}_seconds"
    help_text = "Request latency bands per pipeline stage"
    labels = {"kind": kind, "role": role, "request": request}
    # LatencyBands.record increments EVERY band at or above the
    # latency, so the snapshot counts are already cumulative — emit
    # them in threshold order (dict order follows the sorted band
    # tuple, but sort defensively: bucket monotonicity is a format
    # invariant, not a hope)
    bands = sorted(snap.get("bands", {}).items(),
                   key=lambda kv: float(_band_seconds(kv[0])))
    for bk, count in bands:
        f.add(base, "histogram", help_text,
              {**labels, "le": _band_seconds(bk)}, count, suffix="_bucket")
    f.add(base, "histogram", help_text,
          {**labels, "le": "+Inf"}, snap.get("total", 0),
          suffix="_bucket")
    f.add(base, "histogram", help_text, labels, snap.get("total", 0),
          suffix="_count")
    f.add(base, "histogram", help_text, labels,
          snap.get("sum_seconds", 0.0), suffix="_sum")
    for bk, count in bands:
        f.add(f"{stem}_band", "gauge",
              "Raw per-band request counts (LatencyBands thresholds)",
              {**labels, "band": _band_seconds(bk)}, count)
    f.add(f"{stem}_max_seconds", "gauge",
          "Largest latency ever observed per stage", labels,
          snap.get("max_seconds"))
    for q in ("p50", "p90", "p99"):
        if q in snap:
            f.add(f"{stem}_quantile_seconds", "gauge",
                  "Recent-reservoir latency percentiles per stage",
                  {**labels, "quantile": "0." + q[1:]}, snap[q])


def _add_process_metrics(f: _Families, sample: dict) -> None:
    """One ProcessMetrics sample (server/process_metrics.py) into the
    fdbtpu_process_* family set — shared by the host render and the
    federated per-worker render so the families line up."""
    if not sample:
        return
    labels = {"role": str(sample.get("role", "?")),
              "pid": str(sample.get("pid", "?"))}
    for field, mtype, help_text in (
            ("cpu_seconds", "counter",
             "Process CPU seconds (user+system) since sampling began"),
            ("rss_bytes", "gauge",
             "Resident set size in bytes (-1 where unreadable)"),
            ("open_fds", "gauge",
             "Open file descriptors (-1 where unreadable)"),
            ("gc_collections", "counter",
             "Cumulative Python GC collections across generations"),
            ("loop_lag_ms", "gauge",
             "Run-loop lag: how late a fixed real-time sleep fired"),
            ("uptime_seconds", "counter",
             "Wall seconds since the process's sampler started")):
        f.add(f"{_PREFIX}_process_{field}", mtype, help_text, labels,
              sample.get(field))


def _add_counters(f: _Families, kind: str, role: str, counters: dict) -> None:
    for cname, value in sorted((counters or {}).items()):
        f.add(f"{_PREFIX}_role_counter", "counter",
              "Role counters (flow/Stats CounterCollection values)",
              {"kind": kind, "role": role, "counter": cname}, value)


def render_prometheus(status: dict, f: _Families = None) -> str:
    """The status document as Prometheus text exposition format.
    Pass an existing `_Families` to accumulate into it instead (the
    federated scrape does — it returns "" and the caller renders)."""
    cl = status.get("cluster", status) or {}
    own = f is None
    if own:
        f = _Families()
    f.add(f"{_PREFIX}_cluster_epoch", "gauge",
          "Current recovery epoch", {}, cl.get("epoch"))
    f.add(f"{_PREFIX}_cluster_recovered", "gauge",
          "1 when recovery_state is fully_recovered", {},
          int(cl.get("recovery_state") == "fully_recovered"))
    qos = cl.get("qos") or {}
    f.add(f"{_PREFIX}_qos_transactions_per_second_limit", "gauge",
          "Ratekeeper transaction budget", {},
          qos.get("transactions_per_second_limit"))
    f.add(f"{_PREFIX}_qos_batch_transactions_per_second_limit", "gauge",
          "Ratekeeper batch-priority transaction budget", {},
          qos.get("batch_transactions_per_second_limit"))
    # the limiting reason as a one-hot family: exactly one reason label
    # carries 1 (an enum gauge dashboards can alert on without string
    # parsing); the enum matches server/ratekeeper.py LIMIT_REASONS
    reason = qos.get("limiting_reason")
    if reason is not None:
        from ..server.ratekeeper import LIMIT_REASONS
        for r in LIMIT_REASONS:
            f.add(f"{_PREFIX}_qos_limiting_reason", "gauge",
                  "Active Ratekeeper limiting reason (one-hot)",
                  {"reason": r}, int(r == reason))
    for iname, val in sorted((qos.get("inputs") or {}).items()):
        f.add(f"{_PREFIX}_qos_input", "gauge",
              "Ratekeeper decision input signals (RkUpdate)",
              {"input": iname}, val)
    # per-role smoothed saturation signals (the QosSample plane)
    for kind, roles in sorted((qos.get("roles") or {}).items()):
        for rname, signals in sorted(roles.items()):
            for sname, val in sorted(signals.items()):
                if sname == "sampled_at":
                    continue
                f.add(f"{_PREFIX}_qos_signal", "gauge",
                      "Per-role smoothed saturation signals",
                      {"kind": kind, "role": rname, "signal": sname},
                      val)
    # per-tag traffic accounting (the TransactionTagCounter surface)
    for row in qos.get("tags", ()):
        tl = {"tag": row["tag"]}
        f.add(f"{_PREFIX}_tag_busyness", "gauge",
              "Decayed per-tag commit-traffic score", tl,
              row.get("busyness"))
        for c in ("started", "committed", "conflicted"):
            f.add(f"{_PREFIX}_tag_transactions", "counter",
                  "Per-tag transaction outcomes at the proxies",
                  {**tl, "outcome": c}, row.get(c))
    for prio, counts in sorted((qos.get("priorities") or {}).items()):
        for c in ("started", "committed", "conflicted"):
            f.add(f"{_PREFIX}_qos_priority_transactions", "counter",
                  "Per-priority-class transaction outcomes",
                  {"priority": prio, "outcome": c}, counts.get(c))

    # conflict prediction & transaction repair (server/scheduler.py +
    # server/repair.py): armed planes, cluster totals, and the
    # client-side early-abort counters
    sched = cl.get("conflict_scheduling") or {}
    if sched:
        for feat in ("scheduling", "repair", "client_windows"):
            f.add(f"{_PREFIX}_sched_enabled", "gauge",
                  "1 while the named conflict-scheduling plane is armed",
                  {"feature": feat}, sched.get(f"{feat}_enabled"))
        for cname, value in sorted((sched.get("client") or {}).items()):
            if cname == "windows_cached":
                # a level (Counter.set), not a monotone count: typing
                # it counter would make rate() read every decrease as
                # a reset
                f.add(f"{_PREFIX}_sched_client_windows", "gauge",
                      "Hot-key conflict windows currently cached "
                      "client-side", {}, value)
            else:
                f.add(f"{_PREFIX}_sched_client", "counter",
                      "Client-side conflict-window cache counters "
                      "(early aborts, checks, updates)",
                      {"counter": cname}, value)

    # enforced admission control & tag throttling (server/admission.py
    # + server/tag_throttler.py): armed planes, the merged throttle
    # table, the ratekeeper's auto-throttler, and client backoff
    adm = cl.get("admission_control") or {}
    if adm:
        for feat in ("grv_admission", "tag_throttling",
                     "auto_tag_throttling"):
            f.add(f"{_PREFIX}_admission_enabled", "gauge",
                  "1 while the named admission-control plane is armed",
                  {"feature": feat}, adm.get(f"{feat}_enabled"))
        for r in adm.get("throttled_tags", ()):
            tl = {"tag": r["tag"], "priority": r.get("priority", "?"),
                  "auto": str(r.get("auto", 0))}
            f.add(f"{_PREFIX}_throttle_tag_tps", "gauge",
                  "Enforced per-tag transaction rate from the "
                  "throttledTags system keyspace", tl, r.get("tps"))
        f.add(f"{_PREFIX}_throttle_tags", "gauge",
              "Live rows in the tag-throttle table", {},
              len(adm.get("throttled_tags", ())))
        auto = adm.get("auto_throttler") or {}
        f.add(f"{_PREFIX}_throttle_auto_written", "counter",
              "Auto-throttle rows written by the ratekeeper", {},
              auto.get("auto_throttles"))
        f.add(f"{_PREFIX}_throttle_auto_cleared", "counter",
              "Expired auto-throttle rows cleared by the ratekeeper",
              {}, auto.get("auto_cleared"))
        for cname, value in sorted((adm.get("client") or {}).items()):
            if cname == "tags_cached":
                f.add(f"{_PREFIX}_throttle_client_tags", "gauge",
                      "Throttled tags currently cached client-side",
                      {}, value)
            else:
                f.add(f"{_PREFIX}_throttle_client", "counter",
                      "Client-honored backoff counters (local delays "
                      "before tagged GRVs)", {"counter": cname}, value)

    for p in cl.get("proxies", ()):
        _add_counters(f, "proxy", p["name"], p.get("counters"))
        for req, snap in (p.get("latency_bands") or {}).items():
            _add_latency(f, "proxy", p["name"], req, snap)
        pa = p.get("admission") or {}
        if pa:
            alabels = {"role": p["name"]}
            for cls, n in sorted((pa.get("admitted") or {}).items()):
                f.add(f"{_PREFIX}_admission_admitted", "counter",
                      "Transactions admitted through the GRV token "
                      "buckets per priority class",
                      {**alabels, "priority": cls}, n)
            for cls, n in sorted((pa.get("queued") or {}).items()):
                f.add(f"{_PREFIX}_admission_queued", "gauge",
                      "GRV requests currently queued per priority class",
                      {**alabels, "priority": cls}, n)
            for c, help_text in (
                    ("rejected", "GRV requests rejected by the queue "
                                 "depth bound (retryable)"),
                    ("timed_out", "Queued GRV requests shed by the "
                                  "wait bound (retryable)"),
                    ("confirm_rounds", "Causal-confirmation round "
                                       "trips (the GRV batching "
                                       "denominator)")):
                f.add(f"{_PREFIX}_admission_{c}", "counter", help_text,
                      alabels, pa.get(c))
            for c, help_text in (
                    ("delayed", "Tagged GRVs parked by a per-tag "
                                "throttle bucket"),
                    ("released", "Parked GRVs released at the tag's "
                                 "commanded pace"),
                    ("rejected", "Tagged GRVs rejected by the per-tag "
                                 "queue bound (retryable)")):
                f.add(f"{_PREFIX}_throttle_{c}", "counter", help_text,
                      alabels, pa.get(f"throttle_{c}"))
        ps = p.get("scheduler") or {}
        if ps:
            slabels = {"role": p["name"]}
            for c, help_text in (
                    ("deferrals", "Commits captured into per-hot-range "
                                  "deferral queues"),
                    ("released", "Deferred commits released back into "
                                 "the batcher"),
                    ("overflow", "Deferrals refused by the bounded-"
                                 "delay/queue-cap contract"),
                    ("pushes", "Hot-spot pushes received from the CC")):
                f.add(f"{_PREFIX}_sched_{c}", "counter", help_text,
                      slabels, ps.get(c))
            for g, help_text in (
                    ("deferred_now", "Commits currently held deferred"),
                    ("queue_ranges", "Hot ranges with a live deferral "
                                     "queue"),
                    ("hot_rows", "Hot-spot rows in the predictor")):
                f.add(f"{_PREFIX}_sched_{g}", "gauge", help_text,
                      slabels, ps.get(g))
        pr = p.get("repair") or {}
        if pr:
            rlabels = {"role": p["name"]}
            for c, help_text in (
                    ("attempts", "Conflicted transactions captured for "
                                 "server-side repair"),
                    ("committed", "Repairs that committed without a "
                                  "client round trip"),
                    ("conflicted", "Repairs that re-conflicted with the "
                                   "attempt budget exhausted"),
                    ("failed", "Repairs whose resubmission outcome is "
                               "unknown"),
                    ("fallbacks", "Repairs that fell back to the "
                                  "ordinary abort before resubmitting"),
                    ("shed", "Repairs refused by the in-flight cap"),
                    ("reread_rows", "Rows re-read from storage during "
                                    "partial re-execution")):
                f.add(f"{_PREFIX}_repair_{c}", "counter", help_text,
                      rlabels, pr.get(c))
            f.add(f"{_PREFIX}_repair_in_flight", "gauge",
                  "Repairs currently in flight", rlabels,
                  pr.get("in_flight"))
    for r in cl.get("resolvers", ()):
        _add_counters(f, "resolver", r["name"], r.get("counters"))
        for req, snap in (r.get("latency_bands") or {}).items():
            _add_latency(f, "resolver", r["name"], req, snap)
        kern = r.get("kernel") or {}
        if kern:
            f.add(f"{_PREFIX}_resolver_state_rows", "gauge",
                  "Conflict-history rows held by the resolver backend",
                  {"role": r["name"]}, kern.get("state_rows"))
            f.add(f"{_PREFIX}_resolver_state_capacity", "gauge",
                  "Device history capacity (rows)",
                  {"role": r["name"]}, kern.get("capacity"))
            f.add(f"{_PREFIX}_resolver_kernel_batches", "counter",
                  "Batches dispatched through the device kernel",
                  {"role": r["name"]}, kern.get("batches"))
            for dim, occ in (kern.get("occupancy") or {}).items():
                if occ is not None:
                    f.add(f"{_PREFIX}_resolver_kernel_occupancy", "gauge",
                          "Real rows / padded slots per batch dimension",
                          {"role": r["name"], "dim": dim}, occ)
            # feed-path transfer accounting (the packed single-buffer
            # discipline: per_batch == 1 when live, ~12 on the
            # unpacked fallback — counted at _dispatch, not inferred)
            h2d = kern.get("h2d") or {}
            if h2d:
                f.add(f"{_PREFIX}_kernel_h2d_transfers", "counter",
                      "Host->device transfers issued by the resolver "
                      "feed path",
                      {"role": r["name"]}, h2d.get("transfers"))
                f.add(f"{_PREFIX}_kernel_h2d_bytes", "counter",
                      "Bytes moved host->device by the resolver feed "
                      "path",
                      {"role": r["name"]}, h2d.get("bytes"))
                if h2d.get("per_batch") is not None:
                    f.add(f"{_PREFIX}_kernel_h2d_per_batch", "gauge",
                          "H2D transfers per dispatched batch (1 = "
                          "packed single-buffer feed live)",
                          {"role": r["name"]}, h2d.get("per_batch"))
                f.add(f"{_PREFIX}_kernel_h2d_staging_allocs", "counter",
                      "Packed-feed staging buffers allocated (flat in "
                      "steady state: buffers are bucket-reused)",
                      {"role": r["name"]}, h2d.get("staging_allocs"))
        pipe = r.get("pipeline") or {}
        if pipe:
            plabels = {"role": r["name"]}
            for g, help_text in (
                    ("depth", "Configured RESOLVE_PIPELINE_DEPTH"),
                    ("in_flight", "Batches submitted but not drained"),
                    ("peak_in_flight",
                     "High-water mark of the in-flight window"),
                    ("occupancy",
                     "Mean in-flight depth over configured depth")):
                f.add(f"{_PREFIX}_resolve_pipeline_{g}", "gauge",
                      help_text, plabels, pipe.get(g))
            for c, help_text in (
                    ("submits", "Batches submitted to the pipeline"),
                    ("drains", "Batch verdicts read back"),
                    ("forced_drains",
                     "Submits that hit the depth backpressure")):
                f.add(f"{_PREFIX}_resolve_pipeline_{c}", "counter",
                      help_text, plabels, pipe.get(c))
            for stage, snap in (pipe.get("latency") or {}).items():
                if snap.get("total"):
                    _add_latency(f, "resolver", r["name"], stage, snap,
                                 stem=f"{_PREFIX}_resolve_pipeline_latency")
        fo = r.get("failover") or {}
        if fo:
            flabels = {"role": r["name"]}
            f.add(f"{_PREFIX}_conflict_failover_on_primary", "gauge",
                  "1 while the device backend serves, 0 after failover",
                  flabels, int(bool(fo.get("on_primary"))))
            f.add(f"{_PREFIX}_conflict_failover_replay_log", "gauge",
                  "Batches in the bounded replay log since the last "
                  "checkpoint", flabels, fo.get("replay_log"))
            f.add(f"{_PREFIX}_conflict_failover_checkpoint_version",
                  "gauge", "Version of the last backend checkpoint",
                  flabels, fo.get("checkpoint_version"))
            for c, help_text in (
                    ("checkpoints", "Backend state checkpoints taken"),
                    ("device_faults", "Simulated/real device faults hit"),
                    ("device_recoveries",
                     "Rebuilds that stayed on a fresh device backend"),
                    ("failovers", "Falls to the CPU fallback backend"),
                    ("replayed_batches",
                     "Batches deterministically replayed during rebuilds"),
                    ("reattaches", "Successful moves back to the device"),
                    ("reattach_failures", "Reattach attempts that faulted")):
                f.add(f"{_PREFIX}_conflict_failover_{c}", "counter",
                      help_text, flabels, fo.get(c))
            sh = fo.get("shadow") or {}
            f.add(f"{_PREFIX}_shadow_resolve_sample", "gauge",
                  "Shadow-validation sampling interval (0 = off)",
                  flabels, sh.get("sample"))
            f.add(f"{_PREFIX}_shadow_resolve_sampled", "counter",
                  "Batches re-resolved on the CPU shadow backend",
                  flabels, sh.get("sampled"))
            f.add(f"{_PREFIX}_shadow_resolve_mismatches", "counter",
                  "Sampled batches whose shadow verdicts DIVERGED "
                  "(corruption-grade)", flabels, sh.get("mismatches"))
        # dynamic resolver split/merge (ISSUE 15): per-resolver skew
        # surface — owned ranges, state rows, handoff traffic — so a
        # dashboard shows the balancer's effect before and after
        sp = r.get("splits") or {}
        if sp:
            splabels = {"role": r["name"]}
            f.add(f"{_PREFIX}_resolver_split_owned_ranges", "gauge",
                  "Key ranges this resolver currently owns in the "
                  "keyResolvers map", splabels, sp.get("owned_ranges"))
            f.add(f"{_PREFIX}_resolver_split_state_rows", "gauge",
                  "Conflict-history rows held by this resolver's "
                  "backend", splabels, sp.get("state_rows"))
            f.add(f"{_PREFIX}_resolver_split_checkpoints", "counter",
                  "Handoff checkpoints served as split/merge donor",
                  splabels, sp.get("checkpoints_served"))
            f.add(f"{_PREFIX}_resolver_split_installs", "counter",
                  "Handoff pieces grafted in as split/merge recipient",
                  splabels, sp.get("installs"))
    bal = cl.get("resolver_balance") or {}
    if bal:
        f.add(f"{_PREFIX}_resolver_split_enabled", "gauge",
              "1 while the RESOLVER_BALANCE loop is armed", {},
              bal.get("enabled"))
        for c, help_text in (
                ("splits", "Balance-loop range splits (donor -> "
                           "recipient with live state handoff)"),
                ("merges", "Cooled ranges stitched back to their "
                           "former owner"),
                ("releases", "Early former-owner releases (double "
                             "delivery ended before the MVCC window)"),
                ("handoff_timeouts",
                 "Handoffs that fell back to window-only semantics")):
            f.add(f"{_PREFIX}_resolver_split_{c}", "counter", help_text,
                  {}, bal.get(c))
    for lg in cl.get("logs", ()):
        _add_counters(f, "tlog", lg.get("store", "?"), lg.get("counters"))
        f.add(f"{_PREFIX}_tlog_queue_length", "gauge",
              "Unpopped log entries", {"role": lg.get("store", "?")},
              lg.get("queue_length"))
        for req, snap in (lg.get("latency_bands") or {}).items():
            _add_latency(f, "tlog", lg.get("store", "?"), req, snap)
    seen_reps: set = set()
    for s in cl.get("storages", ()):
        for rep in s.get("replicas", ()):
            # the storages list is per SHARD; a server hosting several
            # shards carries the same snapshot in each entry
            if rep["name"] in seen_reps or "counters" not in rep:
                continue
            seen_reps.add(rep["name"])
            _add_counters(f, "storage", rep["name"], rep.get("counters"))
            for req, snap in (rep.get("latency_bands") or {}).items():
                _add_latency(f, "storage", rep["name"], req, snap)
            # the storage heat plane's per-server meters (ISSUE 13):
            # sampled shard bytes + smoothed write/read bandwidth and
            # read ops (read meters sit at zero while the plane is off
            # — the families stay, so dashboards are stable)
            slabels = {"role": rep["name"]}
            f.add(f"{_PREFIX}_storage_shard_bytes", "gauge",
                  "Sampled logical bytes per storage replica "
                  "(byteSample estimator)", slabels,
                  rep.get("sampled_bytes"))
            f.add(f"{_PREFIX}_storage_write_bandwidth", "gauge",
                  "Smoothed write bytes/sec into the shard", slabels,
                  rep.get("write_bytes_per_sec"))
            f.add(f"{_PREFIX}_storage_read_bytes", "gauge",
                  "Smoothed read bytes/sec out of the shard "
                  "(STORAGE_HEAT_TRACKING)", slabels,
                  rep.get("read_bytes_per_sec"))
            f.add(f"{_PREFIX}_storage_read_ops", "gauge",
                  "Smoothed key reads/sec (point reads + range rows)",
                  slabels, rep.get("read_ops_per_sec"))

    # the storage heat rollup (ISSUE 13): read-hot sub-ranges (decayed
    # read-bandwidth score per flagged range) + per-server busiest
    # read tag
    heat = cl.get("storage_heat") or {}
    if heat:
        f.add(f"{_PREFIX}_storage_heat_tracking", "gauge",
              "1 while STORAGE_HEAT_TRACKING is armed", {},
              heat.get("tracking_enabled"))
        for i, row in enumerate(heat.get("ranges", ())):
            hlabels = {"rank": str(i), "server": row["server"],
                       "begin": row["begin"], "end": row["end"]}
            f.add(f"{_PREFIX}_storage_read_hot_ranges", "gauge",
                  "Read-hot sub-ranges: decayed read bytes/sec per "
                  "flagged range (density in the density label set)",
                  hlabels, row.get("read_bps"))
            f.add(f"{_PREFIX}_storage_read_hot_density", "gauge",
                  "Read-bandwidth / sampled-byte density ratio vs the "
                  "shard's own density", hlabels, row.get("density"))
        for row in heat.get("busiest_read_tags", ()):
            f.add(f"{_PREFIX}_storage_tag_busyness", "gauge",
                  "Busiest read tag per storage server (decayed "
                  "read-cost score)",
                  {"server": row["server"], "tag": row["tag"]},
                  row.get("busyness"))

    # process-wide jitted-kernel profile: "family[shape].counter" keys
    for key, value in sorted((cl.get("kernels") or {}).items()):
        kernel, _, counter = key.rpartition(".")
        f.add(f"{_PREFIX}_kernel_profile", "counter",
              "Jitted-kernel compile/execute accounting per shape bucket",
              {"kernel": kernel or key, "counter": counter}, value)

    probe = cl.get("latency_probe") or {}
    for field, stage in (("transaction_start_seconds", "grv"),
                         ("read_seconds", "read"),
                         ("commit_seconds", "commit")):
        f.add(f"{_PREFIX}_latency_probe_seconds", "gauge",
              "Last cluster-controller probe transaction latencies",
              {"stage": stage}, probe.get(field))
    f.add(f"{_PREFIX}_latency_probe_rounds", "counter",
          "Probe rounds completed", {}, probe.get("rounds"))
    for stage, snap in (probe.get("bands") or {}).items():
        _add_latency(f, "probe", "cluster_controller", stage, snap)

    for i, row in enumerate(cl.get("conflict_hot_spots", ())):
        labels = {"rank": str(i), "begin": row["begin"],
                  "end": row["end"]}
        f.add(f"{_PREFIX}_conflict_hot_spot_score", "gauge",
              "Decayed conflict-attribution score per key range", labels,
              row["score"])
        f.add(f"{_PREFIX}_conflict_hot_spot_total", "counter",
              "Raw attributed-conflict count per key range", labels,
              row["total"])

    # the chaos plane's shared fault accounting (server/chaos.py):
    # injected-fault totals per kind + per-scenario run counts, so a
    # dashboard can confirm a storm actually fired without trace greps
    chaos = cl.get("chaos") or {}
    for kind, n in sorted((chaos.get("injected") or {}).items()):
        f.add(f"{_PREFIX}_chaos_injected", "counter",
              "Injected chaos faults by kind (network, disk, kills, "
              "device seams)", {"kind": kind}, n)
    for sc, n in sorted((chaos.get("scenarios") or {}).items()):
        f.add(f"{_PREFIX}_chaos_scenario_runs", "counter",
              "Chaos scenario storms started, by scenario name",
              {"scenario": sc}, n)
    if chaos:
        f.add(f"{_PREFIX}_chaos_events", "counter",
              "Total recorded chaos events", {}, chaos.get("events"))
        f.add(f"{_PREFIX}_chaos_messages_dropped", "counter",
              "Messages dropped by kills/partitions", {},
              chaos.get("messages_dropped"))
        f.add(f"{_PREFIX}_chaos_messages_duplicated", "counter",
              "One-way datagrams duplicated by swizzled links", {},
              chaos.get("messages_duplicated"))

    # the SLO engine's verdict (server/slo.py, METRIC_HISTORY armed):
    # overall state + per-rule ok/value so a dashboard alerts on the
    # same burn-rate math the cluster controller evaluates in-process
    slo = cl.get("slo") or {}
    if slo.get("enabled"):
        f.add(f"{_PREFIX}_slo_ok", "gauge",
              "1 when every SLO rule currently holds, 0 on breach", {},
              1 if slo.get("state") == "ok" else 0)
        f.add(f"{_PREFIX}_slo_breaches", "counter",
              "ok->breach transitions seen by the online SLO engine",
              {}, slo.get("breaches"))
        f.add(f"{_PREFIX}_slo_timekeeper_rows", "counter",
              "version<->wallclock rows committed by the TimeKeeper",
              {}, slo.get("timekeeper_rows"))
        rec = slo.get("recorder") or {}
        f.add(f"{_PREFIX}_slo_metric_rows", "counter",
              "Metric-history chunk rows flushed to the keyspace", {},
              rec.get("rows_written"))
        for r in slo.get("rules", ()):
            rl = {"rule": r.get("name", "?")}
            f.add(f"{_PREFIX}_slo_rule_ok", "gauge",
                  "1 while this SLO rule holds, 0 while breached", rl,
                  1 if r.get("ok") else 0)
            if r.get("value") is not None:
                f.add(f"{_PREFIX}_slo_rule_value", "gauge",
                      "Current evaluated value for this SLO rule "
                      "(fixed-point: floats scaled x1000)", rl,
                      r.get("value"))

    # the latency-forensics plane (ISSUE 18, CRITICAL_PATH armed):
    # commit critical-path decomposition — per-station seconds with a
    # wait/service split where the serving side keeps one, dominant-
    # station attribution, the decaying top-cause table, and the
    # telescoping-sum residual bound
    cp = cl.get("critical_path") or {}
    if cp.get("enabled"):
        f.add(f"{_PREFIX}_path_samples_total", "counter",
              "Commits decomposed into critical-path stations", {},
              cp.get("samples"))
        f.add(f"{_PREFIX}_path_residual_seconds_max", "gauge",
              "Largest |sum(stations) - end_to_end| seen (the "
              "telescoping-decomposition error bound)", {},
              cp.get("max_residual_seconds"))
        for s, n in sorted((cp.get("dominant") or {}).items()):
            f.add(f"{_PREFIX}_path_dominant_total", "counter",
                  "Decomposed commits whose largest segment was this "
                  "station", {"station": s}, n)
        for s, v in sorted((cp.get("station_seconds") or {}).items()):
            f.add(f"{_PREFIX}_path_station_seconds_total", "counter",
                  "Cumulative seconds attributed per pipeline station "
                  "(kind: total from the proxy decomposition, "
                  "wait/service from the serving role's split)",
                  {"station": s, "kind": "total"}, v)
        for station, split in sorted((cp.get("splits") or {}).items()):
            for kind in ("wait", "service"):
                f.add(f"{_PREFIX}_path_station_seconds_total", "counter",
                      "Cumulative seconds attributed per pipeline "
                      "station (kind: total from the proxy "
                      "decomposition, wait/service from the serving "
                      "role's split)",
                      {"station": station, "kind": kind},
                      (split.get(kind) or {}).get("sum_seconds"))
        for i, row in enumerate(cp.get("top", ())):
            f.add(f"{_PREFIX}_path_cause_score", "gauge",
                  "Decaying dominant-cause score per station (rank 0 "
                  "= the cluster's current primary latency cause)",
                  {"rank": str(i), "station": row.get("station", "?")},
                  row.get("score"))

    # per-process resource telemetry (ISSUE 18): the host's sample
    # here; every worker's rides the federated render
    pm = cl.get("process_metrics") or {}
    if pm.get("enabled"):
        _add_process_metrics(f, pm.get("host") or {})
        for role, share in sorted((pm.get("role_cpu_share")
                                   or {}).items()):
            f.add(f"{_PREFIX}_process_role_cpu_share", "gauge",
                  "Run-loop busy-time share per sim role inside this "
                  "host process (SIM_TASK_STATS fold)",
                  {"sim_role": role}, share)

    msgs = cl.get("messages", ())
    f.add(f"{_PREFIX}_health_messages", "gauge",
          "Active health messages in the status rollup", {}, len(msgs))
    # aggregate per (name, severity): two lagging storages would
    # otherwise emit identical label sets, which a real Prometheus
    # server rejects as duplicate samples — failing the whole scrape
    # exactly when the cluster is unhealthy
    by_kind: dict = {}
    for m in msgs:
        key = (m.get("name", "?"), str(m.get("severity", 0)))
        by_kind[key] = by_kind.get(key, 0) + 1
    for (name, severity), count in sorted(by_kind.items()):
        f.add(f"{_PREFIX}_health_message", "gauge",
              "Active conditions per health-message kind",
              {"name": name, "severity": severity}, count)

    rl = cl.get("run_loop") or {}
    f.add(f"{_PREFIX}_run_loop_tasks", "counter",
          "Scheduler tasks executed", {}, rl.get("tasks_run"))
    f.add(f"{_PREFIX}_run_loop_busy_seconds", "counter",
          "Scheduler busy time", {}, rl.get("busy_seconds"))
    # run-loop slow-task profiler (flow/scheduler.py SlowTask events)
    f.add(f"{_PREFIX}_run_loop_slow_tasks", "counter",
          "Steps that exceeded SLOW_TASK_THRESHOLD", {},
          rl.get("slow_task_count"))
    f.add(f"{_PREFIX}_run_loop_slow_task_threshold_seconds", "gauge",
          "Active slow-task threshold", {},
          rl.get("slow_task_threshold"))
    worst: dict = {}   # the same task label may recur: keep its worst
    for t in rl.get("slow_tasks", ()):
        prev = worst.get(t["task"])
        if prev is None or t["seconds"] > prev[0]:
            worst[t["task"]] = (t["seconds"], t.get("stack", "?"))
    for task, (seconds, stack) in sorted(worst.items()):
        f.add(f"{_PREFIX}_run_loop_slow_task_seconds", "gauge",
              "Worst run-loop steps by task label (stack = coroutine "
              "suspension stack at the slow step)",
              {"task": task, "stack": stack}, seconds)

    # sim-perf attribution plane (SIM_TASK_STATS — flow/scheduler.py
    # task table + rpc/network.py message accounting): the fdbtpu_sim_*
    # wall-vs-sim headline, the fdbtpu_task_* attribution families, and
    # the fdbtpu_net_* message families
    f.add(f"{_PREFIX}_sim_seconds", "counter",
          "Simulated seconds elapsed on the run loop's timeline", {},
          rl.get("sim_seconds"))
    f.add(f"{_PREFIX}_sim_per_busy_second", "gauge",
          "Sim seconds advanced per busy wall second (the sim-scale "
          "headline)", {}, rl.get("sim_per_busy"))
    ts = rl.get("task_stats") or {}
    if ts:
        f.add(f"{_PREFIX}_sim_task_stats_armed", "gauge",
              "1 while per-task run-loop attribution is armed", {},
              ts.get("armed"))
        f.add(f"{_PREFIX}_task_names_dropped", "counter",
              "Task-stat folds routed to the (other) bucket by the "
              "table bound", {}, ts.get("dropped_names"))
    for row in ts.get("tasks", ()):
        tl = {"task": row["task"]}
        f.add(f"{_PREFIX}_task_steps", "counter",
              "Run-loop steps per task family (SIM_TASK_STATS)", tl,
              row.get("steps"))
        f.add(f"{_PREFIX}_task_busy_us", "counter",
              "Cumulative step wall-microseconds per task family", tl,
              row.get("busy_us"))
        f.add(f"{_PREFIX}_task_max_step_us", "gauge",
              "Worst single step per task family (µs)", tl,
              row.get("max_us"))
    for row in ts.get("bands", ()):
        bl = {"band": row["band"]}
        f.add(f"{_PREFIX}_task_band_steps", "counter",
              "Run-loop steps per TaskPriority band", bl,
              row.get("steps"))
        f.add(f"{_PREFIX}_task_band_busy_us", "counter",
              "Cumulative step wall-microseconds per TaskPriority band",
              bl, row.get("busy_us"))
    netdoc = cl.get("network") or {}
    if netdoc:
        for row in netdoc.get("types", ()):
            f.add(f"{_PREFIX}_net_messages", "counter",
                  "Sim-network messages delivered, by request type "
                  "(armed with SIM_TASK_STATS)", {"type": row["type"]},
                  row.get("count"))
        f.add(f"{_PREFIX}_net_messages_sent", "counter",
              "Total sim-network messages sent", {},
              netdoc.get("messages_sent"))
        f.add(f"{_PREFIX}_net_messages_dropped", "counter",
              "Messages dropped by kills/partitions", {},
              netdoc.get("messages_dropped"))
        f.add(f"{_PREFIX}_net_messages_duplicated", "counter",
              "Datagrams duplicated by swizzled links", {},
              netdoc.get("messages_duplicated"))
        f.add(f"{_PREFIX}_net_delivery_timers", "gauge",
              "Scheduler timer-heap population (in-flight deliveries "
              "+ role timers)", {}, netdoc.get("timers_now"))
        f.add(f"{_PREFIX}_net_ready_tasks", "gauge",
              "Runnable task backlog on the scheduler ready heap", {},
              netdoc.get("ready_now"))

    # client transaction-profiling sampler (client/profiling.py,
    # process-wide like the kernel profile)
    for cname, value in sorted((cl.get("client_profile") or {}).items()):
        f.add(f"{_PREFIX}_client_profile", "counter",
              "Sampled-transaction profiler counters",
              {"counter": cname}, value)
    return f.render() if own else ""


# ------------------------------------------------------- federation
# ISSUE 16: every worker OS process serves a StatusRequest endpoint
# (tools/clusterbench.py run_worker) and drops a proc.<role>.<pid>.json
# discovery stub in the shared run directory. The helpers below read
# the stubs, fetch the per-process docs over real TCP, fold them into
# one `cluster.processes` status section, and render ONE Prometheus
# scrape where every sample carries process="role:pid" labels.

def _render_worker_doc(doc: dict, f: _Families) -> None:
    """One worker-process status doc (clusterbench worker_status shape)
    into the shared family accumulator. `f.extra` already carries the
    process label."""
    labels = {"role": doc.get("role", "?")}
    f.add(f"{_PREFIX}_process_up", "gauge",
          "1 while the worker process answers StatusRequest",
          labels, doc.get("up", 1))
    f.add(f"{_PREFIX}_process_uptime_seconds", "gauge",
          "Worker uptime since its workload started", labels,
          doc.get("uptime_s"))
    for cname, value in sorted((doc.get("counters") or {}).items()):
        if isinstance(value, (int, float)):
            f.add(f"{_PREFIX}_worker_txn", "counter",
                  "Per-worker workload transaction outcomes",
                  {**labels, "counter": cname}, value)
    for req in ("grv", "commit"):
        snap = doc.get(req) or {}
        for q, value in sorted(snap.items()):
            # clusterbench _lat_ms shape: p50_ms/p95_ms/... gauges
            if q.endswith("_ms") and isinstance(value, (int, float)):
                f.add(f"{_PREFIX}_worker_latency_ms", "gauge",
                      "Per-worker request-latency percentiles "
                      "(milliseconds)",
                      {**labels, "request": req,
                       "quantile": q[:-3]}, value)
    # per-process resource telemetry (ISSUE 18) — .get throughout:
    # a worker running an OLDER build has no process_metrics section,
    # and the federated scrape must render it with defaults, not fail
    # (version-skew tolerance)
    _add_process_metrics(f, doc.get("process_metrics") or {})
    fr = doc.get("flightrec") or {}
    if fr:
        f.add(f"{_PREFIX}_flightrec_buffered", "gauge",
              "Trace events currently held in the flight-recorder "
              "ring", labels, fr.get("buffered"))
        f.add(f"{_PREFIX}_flightrec_noted_total", "counter",
              "Trace events ever filed into the flight recorder",
              labels, fr.get("noted"))
        f.add(f"{_PREFIX}_flightrec_dumps_total", "counter",
              "Flight-recorder dumps written by this process", labels,
              fr.get("dumps"))


def render_federated(host_status: dict, procs: List[dict],
                     host_process: str = "cluster-host") -> str:
    """One Prometheus scrape for the whole multi-process cluster: the
    host CC status document plus every worker doc, each sample labelled
    with its process identity. One accumulator keeps one HELP/TYPE
    header per family even when several processes emit it."""
    f = _Families()
    if host_status:
        f.extra = {"process": host_process}
        render_prometheus(host_status, f=f)
    for doc in procs or ():
        f.extra = {"process": str(doc.get("process", "?"))}
        _render_worker_doc(doc, f)
    f.extra = {}
    f.add(f"{_PREFIX}_federated_processes", "gauge",
          "Processes folded into this scrape (host + workers)", {},
          (1 if host_status else 0) + len(procs or ()))
    # cross-process role CPU share (ISSUE 19): host in-process fold
    # weighted by host cpu_seconds plus every worker/role process's
    # measured cpu_seconds under its role
    from ..server.process_metrics import federated_role_cpu_share
    pm = ((host_status or {}).get("cluster") or {}) \
        .get("process_metrics") or {}
    for role, share in federated_role_cpu_share(
            pm.get("role_cpu_share"),
            (pm.get("host") or {}).get("cpu_seconds"),
            list(procs or ())).items():
        f.add(f"{_PREFIX}_federated_role_cpu_share", "gauge",
              "CPU-seconds share per role across every OS process in "
              "the deployment (host sim-fold x host CPU + each "
              "worker/role process's own CPU)", {"role": role}, share)
    return f.render()


#: sections every federated process doc is normalized to carry —
#: version-skew tolerance: a worker running an OLDER build (or a
#: tombstone for a dead one) simply lacks the newer sections, and the
#: consumers (cli, exporter, soak timeline) must see defaults, never
#: a KeyError
_PROC_DOC_DEFAULTS = (
    ("role", "?"), ("pid", None), ("up", 1), ("uptime_s", None),
    ("counters", {}), ("grv", {}), ("commit", {}),
    ("process_metrics", {}), ("flightrec", {}),
)


def normalize_proc_doc(p: dict) -> dict:
    """Fill a worker status doc's missing sections with defaults (a
    fresh dict per doc — shared mutable defaults would alias)."""
    out = dict(p or {})
    for key, default in _PROC_DOC_DEFAULTS:
        if key not in out:
            out[key] = dict(default) if isinstance(default, dict) \
                else default
    return out


def federate_status(host_status: dict, procs: List[dict],
                    host_process: str = "cluster-host") -> dict:
    """Fold per-process docs into the host status document under
    `cluster.processes` (one section, keyed by "role:pid"), mirroring
    the reference `status json` processes map. Docs are normalized
    first (normalize_proc_doc), so a mixed-version cluster federates
    cleanly."""
    import copy
    doc = copy.deepcopy(host_status or {})
    cl = doc.setdefault("cluster", {})
    cl["processes"] = {str(p.get("process", f"?:{i}")):
                       normalize_proc_doc(p)
                       for i, p in enumerate(procs or ())}
    from ..server.process_metrics import federated_role_cpu_share
    pm = cl.get("process_metrics") or {}
    cl["federation"] = {"host_process": host_process,
                        "process_count": 1 + len(procs or ()),
                        "role_cpu_share": federated_role_cpu_share(
                            pm.get("role_cpu_share"),
                            (pm.get("host") or {}).get("cpu_seconds"),
                            list(cl["processes"].values()))}
    return doc


def read_proc_files(run_dir: str) -> List[dict]:
    """The proc.<role>.<pid>.json discovery stubs in a run dir (sorted
    by filename; unreadable stubs are skipped, not fatal — a worker may
    die mid-write)."""
    import json
    import os
    out: List[dict] = []
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return out
    for fn in names:
        if not (fn.startswith("proc.") and fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(run_dir, fn)) as fh:
                out.append(json.load(fh))
        except (OSError, ValueError):
            continue
    return out


def fetch_process_docs(run_dir: str, *, timeout: float = 5.0,
                       stubs: List[dict] = None) -> List[dict]:
    """Fetch every discovered worker's status doc over real TCP. A
    worker that no longer answers yields an `up: 0` tombstone carrying
    its stub identity, so the federated scrape shows the gap instead
    of silently shrinking. Hosts its own wall-clock loop; the ambient
    scheduler/RNG are restored on exit (the networktest discipline)."""
    from .. import flow
    from ..flow import rng as _rng
    from ..rpc.tcp import TcpTransport
    from ..server.types import STATUS_REQUEST
    if stubs is None:
        stubs = read_proc_files(run_dir)
    if not stubs:
        return []
    prev_sched = flow.get_scheduler()
    prev_rng = _rng.rng_state()
    transport = None
    try:
        flow.set_seed(0)
        s = flow.Scheduler(virtual=False)
        flow.set_scheduler(s)
        transport = TcpTransport()

        async def fetch_one(stub: dict) -> dict:
            ref = transport.ref(stub.get("host", "127.0.0.1"),
                                int(stub["port"]),
                                int(stub["status_token"]))
            try:
                doc = await flow.timeout_error(
                    ref.get_reply(STATUS_REQUEST), timeout)
            except flow.FdbError:
                return {"process": stub.get("name", "?"),
                        "role": stub.get("role", "?"),
                        "pid": stub.get("pid"), "up": 0}
            doc = dict(doc)
            doc.setdefault("process", stub.get("name", "?"))
            doc["up"] = 1
            return doc

        async def main():
            transport.start()
            return list(await flow.wait_for_all(
                [flow.spawn(fetch_one(st)) for st in stubs]))

        t = s.spawn(main())
        return s.run(until=t, timeout_time=timeout * len(stubs) + 30)
    finally:
        if transport is not None:
            transport.close()
        flow.set_scheduler(prev_sched)
        _rng.restore_rng_state(prev_rng)


_ESCAPES = {"\\": "\\", '"': '"', "n": "\n"}


def parse_prometheus(text: str) -> List[Tuple[str, dict, float]]:
    """Exposition-format parser: [(name, labels, value)]. Raises
    ValueError on a malformed line — the CI smoke and the tests use it
    as the well-formedness check. Label values are scanned with full
    escape awareness (the format's \\\\, \\" and \\n sequences), so a
    tag, signal or stack label carrying a quote, comma, brace or
    newline round-trips through _esc exactly — the old tokenizer split
    the body on commas and never unescaped, silently corrupting any
    such value."""
    out: List[Tuple[str, dict, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        labels: dict = {}
        if "{" in line:
            name, _, body = line.partition("{")
            i, n = 0, len(body)
            while True:
                if i >= n:
                    raise ValueError(f"unterminated label set: {line!r}")
                if body[i] == "}":
                    break
                j = body.find("=", i)
                if j < 0:
                    raise ValueError(f"label without '=': {line!r}")
                key = body[i:j].strip()
                i = j + 1
                if i >= n or body[i] != '"':
                    raise ValueError(f"unquoted label value: {line!r}")
                i += 1
                buf: List[str] = []
                while i < n and body[i] != '"':
                    c = body[i]
                    if c == "\\":
                        if i + 1 >= n:
                            raise ValueError(
                                f"dangling escape: {line!r}")
                        nxt = body[i + 1]
                        if nxt not in _ESCAPES:
                            raise ValueError(
                                f"bad escape \\{nxt}: {line!r}")
                        buf.append(_ESCAPES[nxt])
                        i += 2
                    else:
                        buf.append(c)
                        i += 1
                if i >= n:
                    raise ValueError(
                        f"unterminated label value: {line!r}")
                labels[key] = "".join(buf)
                i += 1          # closing quote
                if i < n and body[i] == ",":
                    i += 1
            value = body[i + 1:].strip()
        else:
            name, _, value = line.partition(" ")
            value = value.strip()
        if not name or not name.replace("_", "").replace(":", "") \
                .isalnum():
            raise ValueError(f"bad metric name: {line!r}")
        out.append((name, labels, float(value)))
    return out


class ExporterServer:
    """Tiny threaded HTTP server for GET /metrics. `get_text` runs on
    the serving thread — pass something thread-safe (for a live
    cluster, a RemoteCluster-backed closure; in tests, a canned
    string)."""

    def __init__(self, get_text: Callable[[], str], host: str = "127.0.0.1",
                 port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = exporter.get_text().encode()
                except Exception as e:  # noqa: BLE001 — scrape fails, server lives
                    self.send_error(500, explain=str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self.get_text = get_text
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    connect = None
    federate = None
    listen_port = 9090
    once = False
    while argv:
        a = argv.pop(0)
        if a == "--connect":
            connect = argv.pop(0)
        elif a == "--federate":
            federate = argv.pop(0)   # a run dir with proc.*.json stubs
        elif a == "--listen-port":
            listen_port = int(argv.pop(0))
        elif a == "--once":
            once = True   # print one scrape and exit (smoke / cron)
    if connect is None and federate is None:
        print("usage: exporter (--connect host:port | --federate "
              "run_dir) [--listen-port N] [--once]", file=sys.stderr)
        return 2
    if federate is not None and connect is None:
        # federate-only: fold every live worker in the run dir into
        # one scrape (no host CC — e.g. scraping a soak's workers)
        def scrape() -> str:
            return render_federated({}, fetch_process_docs(federate))

        if once:
            print(scrape(), end="")
            return 0
        server = ExporterServer(scrape, port=listen_port)
        server.start()
        print(f"serving /metrics on :{server.port}", flush=True)
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        finally:
            server.close()
        return 0
    from ..client.remote import RemoteCluster
    host, _, port = connect.partition(":")
    remote = RemoteCluster(host or "127.0.0.1", int(port))

    def scrape() -> str:
        status = remote.call(remote.db.get_status())
        if federate is not None:
            return render_federated(status,
                                    fetch_process_docs(federate))
        return render_prometheus(status)

    try:
        if once:
            print(scrape(), end="")
            return 0
        server = ExporterServer(scrape, port=listen_port)
        server.start()
        print(f"serving /metrics on :{server.port}", flush=True)
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        finally:
            server.close()
        return 0
    finally:
        remote.close()


if __name__ == "__main__":
    sys.exit(main())
