"""Cluster host process: serve a cluster on a TCP port.

Reference: fdbserver + fdbmonitor — one OS process hosting the
database, reachable over the network; `python -m
foundationdb_tpu.tools.server --port 4500` plays that role for this
framework: a wall-clock cluster (every role, durable disks, recovery,
DD) whose client surface is served by the TcpGateway, so external
processes — the CLI's --connect mode, the C binding, any
RemoteCluster — speak the real wire protocol to it.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from .. import flow
from ..rpc.gateway import TcpGateway
from ..server import SimCluster


def serve(port: int = 0, seed: int = 0, n_storage: int = 2,
          storage_replicas: int = 1, n_logs: int = 1, n_proxies: int = 1,
          tls=None, data_dir=None, announce=print,
          cluster_file=None, backup_agent: bool = True) -> None:
    """Run until interrupted; announces `LISTENING <port>` once up.
    With --data-dir, durable state lives in REAL files there and
    survives restarting this process. With --cluster-file, writes the
    fdb.cluster-style connection string clients dial (ref: the cluster
    file convention, fdbclient/MonitorLeader.actor.cpp)."""
    if cluster_file is not None:
        # fail BEFORE booting a cluster if the path can't be written
        import os as _os
        d = _os.path.dirname(cluster_file) or "."
        if not _os.path.isdir(d) or not _os.access(d, _os.W_OK):
            raise SystemExit(
                f"--cluster-file directory not writable: {d}")
    c = SimCluster(seed=seed, virtual=False, durable=True,
                   n_storage=n_storage, storage_replicas=storage_replicas,
                   n_logs=n_logs, n_proxies=n_proxies, data_dir=data_dir,
                   backup_driver=backup_agent)
    gw = TcpGateway(c.client("gateway-host"), port=port, tls=tls)
    try:
        async def main():
            gw.start()
            if cluster_file is not None:
                from ..client.cluster_file import (
                    ClusterConnectionString, write_cluster_file)
                write_cluster_file(cluster_file, ClusterConnectionString(
                    "fdbtpu", f"s{seed}",
                    (("127.0.0.1", gw.port),)))
            announce(f"LISTENING {gw.port}", flush=True)
            while True:
                await flow.delay(flow.SERVER_KNOBS.server_status_poll_delay)

        c.run(main())
    except KeyboardInterrupt:
        pass
    finally:
        gw.close()
        c.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from ._tlsargs import TLS_FLAGS, tls_from_args
    kwargs = {}
    tls_args = {}
    while argv:
        a = argv.pop(0)
        if a in TLS_FLAGS:
            tls_args[TLS_FLAGS[a]] = argv.pop(0)
        elif a == "--port":
            kwargs["port"] = int(argv.pop(0))
        elif a == "--data-dir":
            kwargs["data_dir"] = argv.pop(0)
        elif a == "--seed":
            kwargs["seed"] = int(argv.pop(0))
        elif a == "--storage":
            kwargs["n_storage"] = int(argv.pop(0))
        elif a == "--replicas":
            kwargs["storage_replicas"] = int(argv.pop(0))
        elif a == "--logs":
            kwargs["n_logs"] = int(argv.pop(0))
        elif a == "--proxies":
            kwargs["n_proxies"] = int(argv.pop(0))
        elif a in ("--cluster-file", "-C"):
            kwargs["cluster_file"] = argv.pop(0)
        elif a == "--no-backup-agent":
            kwargs["backup_agent"] = False
        else:
            print(f"unknown argument {a}", file=sys.stderr)
            return 2
    try:
        tls = tls_from_args(tls_args)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if tls is not None:
        kwargs["tls"] = tls
    serve(**kwargs)
    return 0


if __name__ == "__main__":
    sys.exit(main())
