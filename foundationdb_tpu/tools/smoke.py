"""CI smoke: boot an in-process cluster, run a conflicting workload and
one latency-probe round, then assert the operator surfaces are
well-formed — `status details` (conflict hot spots + latency probe
sections), `top`, and the Prometheus exporter text.

`python -m foundationdb_tpu.tools.smoke` exits 0 on success; the
tier-1 workflow runs it after the test suite as an end-to-end guard
that the observability stack assembles outside pytest too."""

from __future__ import annotations

import sys
from typing import List, Optional


def run_smoke(out=print) -> int:
    from .. import flow
    from ..client import run_transaction
    from ..server import SimCluster
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus

    cluster = SimCluster(seed=4242, durable=True)
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("smoke")

        async def workload():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            for _ in range(6):
                tr = db.create_transaction()
                tr.set_option("report_conflicting_keys")
                await tr.get(b"hot")
                tr.set(b"mine", b"v")

                async def bump(t2):
                    t2.set(b"hot", b"x")
                await run_transaction(db, bump)
                try:
                    await tr.commit()
                    raise AssertionError("expected a conflict")
                except flow.FdbError as e:
                    assert e.name == "not_committed", e.name
                assert tr.get_conflicting_ranges() == \
                    ((b"hot", b"hot\x00"),), tr.get_conflicting_ranges()
            # one probe round: past LATENCY_PROBE_INTERVAL (5s) + the
            # metric sampler tick
            await flow.delay(7.0)
            return await db.get_status()

        status = cluster.run(workload(), timeout_time=300)
        cl = status["cluster"]
        assert cl["conflict_hot_spots"], "no hot spots attributed"
        assert cl["conflict_hot_spots"][0]["begin"] == b"hot".hex()
        assert cl["latency_probe"].get("rounds", 0) >= 1, \
            "latency probe never ran"

        details = cli.execute("status details")
        for section in ("Latency (seconds):", "Conflict hot spots",
                        "Latency probe:", b"hot".hex()):
            assert str(section) in details, f"missing {section!r}"
        top = cli.execute("top")
        assert b"hot".hex() in top

        text = render_prometheus(status)
        samples = parse_prometheus(text)   # raises on malformed lines
        kinds = {l.get("kind") for n, l, _ in samples
                 if n == "fdbtpu_role_counter"}
        missing = {"proxy", "resolver", "tlog", "storage"} - kinds
        assert not missing, f"exporter missing role kinds: {missing}"
        names = {n for n, _, _ in samples}
        for need in ("fdbtpu_conflict_hot_spot_score",
                     "fdbtpu_latency_probe_seconds",
                     "fdbtpu_request_latency_seconds_bucket"):
            assert need in names, f"exporter missing {need}"
        out(f"SMOKE OK: {len(samples)} exporter samples, "
            f"{len(cl['conflict_hot_spots'])} hot spots, "
            f"{cl['latency_probe']['rounds']} probe rounds")
        return 0
    finally:
        cluster.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    return run_smoke()


if __name__ == "__main__":
    sys.exit(main())
