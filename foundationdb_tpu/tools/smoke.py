"""CI smoke: boot an in-process cluster, run a conflicting workload and
one latency-probe round, then assert the operator surfaces are
well-formed — `status details` (conflict hot spots + latency probe
sections), `top`, and the Prometheus exporter text.

`python -m foundationdb_tpu.tools.smoke` exits 0 on success; the
tier-1 workflow runs it after the test suite as an end-to-end guard
that the observability stack assembles outside pytest too.
`--profile` runs the transaction-profiling smoke instead: sampling at
100%, a conflicting workload, and the tools/profiler.py analyzer must
find both a committed and a conflicted transaction; the report lands
in /tmp/_profile_report.txt for the CI artifact."""

from __future__ import annotations

import sys
from typing import List, Optional

PROFILE_REPORT_PATH = "/tmp/_profile_report.txt"
STORM_REPORT_PATH = "/tmp/_storm_report.txt"
CHAOS_REPORT_PATH = "/tmp/_chaos_report.txt"
CHAOS_TRACE_PATH = "/tmp/_chaos_trace.jsonl"
CONTENTION_REPORT_PATH = "/tmp/_contention_report.txt"
OVERLOAD_REPORT_PATH = "/tmp/_overload_report.txt"
HEAT_REPORT_PATH = "/tmp/_heat_report.txt"
SIMPROF_REPORT_PATH = "/tmp/_simprof_smoke.txt"
SPLITS_REPORT_PATH = "/tmp/_splits_report.txt"
SOAK_REPORT_PATH = "/tmp/_soak_report.txt"
SLO_REPORT_PATH = "/tmp/_slo_report.txt"
PATH_REPORT_PATH = "/tmp/_path_report.txt"
SIMPROF_CHAOS_PATH = "/tmp/_simprof_chaos.json"
SIMPROF_CHAOS_FOLDED_PATH = "/tmp/_simprof_chaos.folded"


def run_smoke(out=print) -> int:
    import os

    from .. import flow
    from ..client import run_transaction
    from ..server import SimCluster
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus

    cluster = SimCluster(seed=4242, durable=True)
    # resolve-pipeline depth under test (CI runs RESOLVE_PIPELINE_DEPTH=4
    # on the CPU backend); set AFTER SimCluster re-initializes the knobs
    flow.SERVER_KNOBS.set(
        "resolve_pipeline_depth",
        int(os.environ.get("RESOLVE_PIPELINE_DEPTH", 4)))
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("smoke")

        async def workload():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            for _ in range(6):
                tr = db.create_transaction()
                tr.set_option("report_conflicting_keys")
                await tr.get(b"hot")
                tr.set(b"mine", b"v")

                async def bump(t2):
                    t2.set(b"hot", b"x")
                await run_transaction(db, bump)
                try:
                    await tr.commit()
                    raise AssertionError("expected a conflict")
                except flow.FdbError as e:
                    assert e.name == "not_committed", e.name
                assert tr.get_conflicting_ranges() == \
                    ((b"hot", b"hot\x00"),), tr.get_conflicting_ranges()
            # one probe round: past LATENCY_PROBE_INTERVAL (5s) + the
            # metric sampler tick
            await flow.delay(7.0)
            return await db.get_status()

        status = cluster.run(workload(), timeout_time=300)
        cl = status["cluster"]
        assert cl["conflict_hot_spots"], "no hot spots attributed"
        assert cl["conflict_hot_spots"][0]["begin"] == b"hot".hex()
        assert cl["latency_probe"].get("rounds", 0) >= 1, \
            "latency probe never ran"

        # the resolve pipeline must be visible without a bench run:
        # every resolver submitted/drained batches through it
        res = cl.get("resolvers", ())
        assert res, "no resolvers in status"
        for r in res:
            pipe = r.get("pipeline") or {}
            assert pipe.get("submits", 0) > 0, f"pipeline idle: {pipe}"
            assert pipe.get("drains") == pipe.get("submits"), pipe
            assert pipe.get("depth", 0) >= 1, pipe

        details = cli.execute("status details")
        for section in ("Latency (seconds):", "Conflict hot spots",
                        "Latency probe:", "Resolve pipeline:",
                        b"hot".hex()):
            assert str(section) in details, f"missing {section!r}"
        top = cli.execute("top")
        assert b"hot".hex() in top

        text = render_prometheus(status)
        samples = parse_prometheus(text)   # raises on malformed lines
        kinds = {l.get("kind") for n, l, _ in samples
                 if n == "fdbtpu_role_counter"}
        missing = {"proxy", "resolver", "tlog", "storage"} - kinds
        assert not missing, f"exporter missing role kinds: {missing}"
        names = {n for n, _, _ in samples}
        for need in ("fdbtpu_conflict_hot_spot_score",
                     "fdbtpu_latency_probe_seconds",
                     "fdbtpu_request_latency_seconds_bucket",
                     "fdbtpu_resolve_pipeline_submits",
                     "fdbtpu_resolve_pipeline_depth"):
            assert need in names, f"exporter missing {need}"
        out(f"SMOKE OK: {len(samples)} exporter samples, "
            f"{len(cl['conflict_hot_spots'])} hot spots, "
            f"{cl['latency_probe']['rounds']} probe rounds, "
            f"pipeline depth {res[0]['pipeline']['depth']} "
            f"({res[0]['pipeline']['submits']} submits)")
        return 0
    finally:
        cluster.shutdown()


def run_smoke_faults(out=print) -> int:
    """Backend fault-tolerance smoke: a TPU-backed cluster with device
    faults injected at the submit/materialize/drain seams
    (DEVICE_FAULT_INJECTION env, default 0.05) and shadow validation
    sampling every SHADOW_RESOLVE_SAMPLE-th batch (default 2) runs a
    conflicting workload; commits must keep succeeding, the
    failover/shadow counters must surface in `status details` and the
    exporter text, and the shadow must report ZERO mismatches (the
    backend is honest — only the fault timing is hostile)."""
    import os

    from .. import flow
    from ..client import run_transaction
    from ..server import SimCluster
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus

    cluster = SimCluster(seed=4646, durable=True, conflict_backend="tpu")
    # knobs AFTER SimCluster re-initializes them; capture the
    # re-initialized values so the finally restores ALL of them for
    # in-process callers that run another smoke after this one
    saved = {n: getattr(flow.SERVER_KNOBS, n) for n in
             ("device_fault_injection", "shadow_resolve_sample",
              "resolve_pipeline_depth", "conflict_checkpoint_versions")}
    flow.SERVER_KNOBS.set(
        "device_fault_injection",
        float(os.environ.get("DEVICE_FAULT_INJECTION", 0.05)))
    flow.SERVER_KNOBS.set(
        "shadow_resolve_sample",
        int(os.environ.get("SHADOW_RESOLVE_SAMPLE", 2)))
    flow.SERVER_KNOBS.set(
        "resolve_pipeline_depth",
        int(os.environ.get("RESOLVE_PIPELINE_DEPTH", 4)))
    flow.SERVER_KNOBS.set("conflict_checkpoint_versions", 200_000)
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("fsmoke")

        async def workload():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            conflicts = 0
            for i in range(20):
                tr = db.create_transaction()
                await tr.get(b"hot")
                tr.set(b"mine%d" % i, b"v")

                async def bump(t2):
                    t2.set(b"hot", b"x")
                await run_transaction(db, bump)
                try:
                    await tr.commit()
                except flow.FdbError as e:
                    assert e.name == "not_committed", e.name
                    conflicts += 1
            assert conflicts == 20, conflicts
            return await db.get_status()

        status = cluster.run(workload(), timeout_time=600)
        res = status["cluster"].get("resolvers", ())
        assert res, "no resolvers in status"
        fo = res[0].get("failover") or {}
        assert fo, "device backend not under the failover controller"
        assert fo["shadow"]["sampled"] > 0, fo
        assert fo["shadow"]["mismatches"] == 0, fo
        assert fo["shadow"]["errors"] == 0, fo
        # the injection campaign must actually FIRE (deterministic at
        # this seed/probability) and every fault must be survived:
        # recovered on a fresh device or failed over to the CPU
        assert fo["device_faults"] > 0, fo
        assert fo["device_recoveries"] + fo["failovers"] > 0, fo
        assert fo["checkpoints"] > 0, fo
        details = cli.execute("status details")
        assert "Backend failover:" in details, details
        assert "shadow=" in details, details

        text = render_prometheus(status)
        samples = parse_prometheus(text)
        names = {n for n, _, _ in samples}
        for need in ("fdbtpu_conflict_failover_on_primary",
                     "fdbtpu_conflict_failover_checkpoints",
                     "fdbtpu_conflict_failover_device_faults",
                     "fdbtpu_shadow_resolve_sampled",
                     "fdbtpu_shadow_resolve_mismatches"):
            assert need in names, f"exporter missing {need}"
        mm = [v for n, l, v in samples
              if n == "fdbtpu_shadow_resolve_mismatches"]
        assert mm and all(v == 0 for v in mm), mm
        out(f"FAULT SMOKE OK: {fo['device_faults']} device faults, "
            f"{fo['device_recoveries']} recoveries, "
            f"{fo['failovers']} failovers, "
            f"{fo['reattaches']} reattaches, "
            f"{fo['checkpoints']} checkpoints, "
            f"shadow {fo['shadow']['sampled']} sampled / "
            f"{fo['shadow']['mismatches']} mismatches")
        return 0
    finally:
        for name, value in saved.items():
            flow.SERVER_KNOBS.set(name, value)
        cluster.shutdown()


def run_smoke_storm(out=print,
                    report_path: str = STORM_REPORT_PATH) -> int:
    """QoS-telemetry storm smoke: an open-loop Zipfian burst workload
    (server/workloads.py OpenLoopStorm — seeded arrivals, tagged and
    priority-mixed traffic) against a cluster whose storage-queue
    target is tightened so the burst saturates it. Asserts the whole
    measurement plane moves: every role kind publishes QoS signals,
    the Ratekeeper's RkUpdate trace reports a non-`none` limiting
    reason under the burst, tag/priority counts surface in status and
    the exporter, p99 GRV latency of ADMITTED transactions stays
    bounded (the cluster degrades by shedding at a controlled rate,
    not by collapsing), and the exporter text parses."""
    import json
    import os

    from .. import flow
    from ..server import SimCluster
    from ..server.ratekeeper import LIMIT_REASONS
    from ..server.workloads import OpenLoopStorm
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus

    cluster = SimCluster(seed=int(os.environ.get("STORM_SEED", 6262)),
                         durable=True)
    # knobs AFTER SimCluster re-initializes them: a storage-queue
    # target small enough that the burst's MVCC-window bytes blow
    # through it (the durability lag holds ~5s of writes pending), and
    # a fast QoS collection cadence so signals land within the run
    saved = {n: getattr(flow.SERVER_KNOBS, n) for n in
             ("rk_target_storage_queue_bytes",
              "rk_spring_storage_queue_bytes", "qos_sample_interval")}
    flow.SERVER_KNOBS.set("rk_target_storage_queue_bytes",
                          int(os.environ.get("STORM_QUEUE_TARGET", 4000)))
    flow.SERVER_KNOBS.set("rk_spring_storage_queue_bytes", 1000)
    flow.SERVER_KNOBS.set("qos_sample_interval", 0.25)
    cli = Cli.for_cluster(cluster)
    try:
        n_clients = int(os.environ.get("STORM_CLIENTS", 8))
        dbs = [cluster.client(f"storm{i}") for i in range(n_clients)]

        async def workload():
            storm = OpenLoopStorm(
                dbs, flow.g_random,
                duration=float(os.environ.get("STORM_DURATION", 3.0)),
                rate=float(os.environ.get("STORM_RATE", 80.0)),
                burst_rate=float(os.environ.get("STORM_BURST_RATE",
                                                500.0)),
                burst_start=1.0, burst_len=1.0, max_inflight=256)
            stats = await storm.run()
            status = await dbs[0].get_status()
            return stats, status

        stats, status = cluster.run(workload(), timeout_time=600)
        cl = status["cluster"]
        qos = cl.get("qos") or {}

        # (1) every role kind publishes smoothed saturation signals
        roles = qos.get("roles") or {}
        for kind in ("storage", "tlog", "proxy", "resolver"):
            assert roles.get(kind), f"no {kind} QoS samples: {roles.keys()}"
        sto = next(iter(roles["storage"].values()))
        assert sto["queue_bytes"] > 0, sto   # the signals actually moved
        assert qos.get("limiting_reason") in LIMIT_REASONS, qos

        # (2) the burst drove the Ratekeeper past a limit: some RkUpdate
        # during the run reported a non-none limiting reason
        rk_updates = [e for e in flow.g_trace.events
                      if e.get("Type") == "RkUpdate"]
        assert rk_updates, "no RkUpdate traces emitted"
        limited = [e for e in rk_updates
                   if e.get("LimitingReason") not in (None, "none")]
        assert limited, ("limiting reason never engaged",
                         rk_updates[-3:])
        for e in limited:
            assert e["LimitingReason"] in LIMIT_REASONS, e

        # (3) tag & priority accounting surfaced
        tags = {r["tag"] for r in qos.get("tags", ())}
        assert tags, "no tag rows in status.cluster.qos"
        assert any(r["started"] > 0 for r in qos["tags"]), qos["tags"]
        prios = qos.get("priorities") or {}
        assert prios.get("batch", {}).get("started", 0) > 0, prios
        assert prios.get("default", {}).get("started", 0) > 0, prios

        # (4) controlled degradation: admitted GRVs keep a bounded p99
        # (shed/timed-out arrivals are the DESIGNED overload response)
        grv = stats["grv"]
        assert stats["completed"] > 0, stats
        assert grv["p99"] <= float(
            flow.SERVER_KNOBS.client_request_timeout), grv

        # (5) operator surfaces: cli qos view + status details section
        qos_view = cli.execute("qos")
        for section in ("Ratekeeper:", "Storage signals:",
                        "Tag traffic", "Priority classes:"):
            assert section in qos_view, f"missing {section!r}\n{qos_view}"
        details = cli.execute("status details")
        assert "Ratekeeper:" in details, details
        assert "limited_by=" in details, details

        # (6) exporter families parse and cover the plane
        text = render_prometheus(status)
        samples = parse_prometheus(text)
        names = {n for n, _, _ in samples}
        for need in ("fdbtpu_qos_signal", "fdbtpu_qos_limiting_reason",
                     "fdbtpu_qos_input", "fdbtpu_tag_busyness",
                     "fdbtpu_tag_transactions",
                     "fdbtpu_qos_priority_transactions"):
            assert need in names, f"exporter missing {need}"
        hot = [(l["reason"], v) for n, l, v in samples
               if n == "fdbtpu_qos_limiting_reason"]
        assert sum(v for _r, v in hot) == 1, hot   # one-hot enum

        report = {"storm": stats, "qos": qos,
                  "rk_updates": len(rk_updates),
                  "limited_updates": len(limited),
                  "limiting_reasons_seen": sorted(
                      {e["LimitingReason"] for e in limited})}
        with open(report_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")
        out(f"STORM SMOKE OK: {stats['issued']} arrivals "
            f"({stats['completed']} committed, "
            f"{stats['conflicted']} conflicted, {stats['shed']} shed), "
            f"grv p99 {grv['p99']}s, "
            f"{len(limited)}/{len(rk_updates)} RkUpdates limited by "
            f"{report['limiting_reasons_seen']}; report at {report_path}")
        return 0
    finally:
        for name, value in saved.items():
            flow.SERVER_KNOBS.set(name, value)
        cluster.shutdown()


def run_smoke_profile(out=print,
                      report_path: str = PROFILE_REPORT_PATH) -> int:
    """The transaction-profiling end-to-end: sample EVERY transaction,
    drive a workload with a guaranteed conflict, and require the
    analyzer to read back ≥1 committed and ≥1 conflicted transaction
    from the \\xff\\x02/fdbClientInfo/ keyspace."""
    from .. import flow
    from ..client import run_transaction
    from ..client.profiling import profiler_counters
    from ..server import SimCluster
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus
    from .profiler import format_report, profile_analysis

    cluster = SimCluster(seed=2424, durable=True, profile_janitor=True)
    flow.SERVER_KNOBS.set("profile_sample_rate", 1.0)
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("psmoke")

        async def workload():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            for i in range(4):
                async def w(tr, i=i):
                    await tr.get(b"hot")
                    tr.set(b"k%d" % i, b"v")
                await run_transaction(db, w)
            # one transaction that conflicts and is NOT retried, so a
            # "conflicted" verdict persists
            tr = db.create_transaction()
            tr.set_option("report_conflicting_keys")
            await tr.get(b"hot")
            tr.set(b"mine", b"v")

            async def bump(t2):
                t2.set(b"hot", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected a conflict")
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
            await flow.delay(2.0)   # let background flushes land
            return await profile_analysis(db)

        analysis, stats = cluster.run(workload(), timeout_time=300)
        assert analysis["records"] >= 2, analysis
        assert analysis["committed"] >= 1, analysis
        assert analysis["conflicted"] >= 1, analysis
        assert stats["skipped_missing_chunks"] == 0, stats
        assert any(r["key"] == b"hot".hex()
                   for r in analysis["hottest_keys"]), analysis

        # the cli renders the same analysis
        report = cli.execute("profile analyze")
        assert "Slowest transactions:" in report, report
        assert "conflicted" in report, report

        # sampler counters reach status + the exporter
        async def st():
            return await db.get_status()
        status = cluster.run(st(), timeout_time=60)
        counters = status["cluster"].get("client_profile", {})
        assert counters.get("transactions_sampled", 0) >= 2, counters
        names = {n for n, _, _ in
                 parse_prometheus(render_prometheus(status))}
        assert "fdbtpu_client_profile" in names, sorted(names)

        with open(report_path, "w") as f:
            f.write(format_report(analysis, stats) + "\n")
        out(f"PROFILE SMOKE OK: {analysis['records']} records "
            f"({analysis['committed']} committed, "
            f"{analysis['conflicted']} conflicted), "
            f"{profiler_counters()['chunks_written']} chunks; "
            f"report at {report_path}")
        return 0
    finally:
        flow.SERVER_KNOBS.set("profile_sample_rate", 0.0)
        cluster.shutdown()


def run_smoke_chaos(out=print,
                    report_path: str = CHAOS_REPORT_PATH) -> int:
    """Single-scenario chaos smoke (the nightly chaos-matrix runs this
    per grid cell; tier-1 runs one fast cell): one named scenario
    (`CHAOS_SCENARIO`, default partition_minority) applied as a
    ChaosStorm at a seeded sim (`CHAOS_SEED`) — open-loop traffic,
    mid-flight faults, heal, quiesce, `check_consistency` + shadow
    cleanliness + bounded recovery — then the SAME seed replayed,
    asserting an identical chaos event schedule and keyspace digest.
    Chaos accounting must surface in status, the exporter, and the cli
    section; the full report (events + digest + counters) and the
    trace file land at /tmp/_chaos_{report.txt,trace.jsonl} for the CI
    artifacts."""
    import json
    import os

    from .. import flow
    from ..server import SimCluster
    from ..server.chaos import SCENARIOS
    from ..server.workloads import ChaosStorm
    from .cli import _render_details
    from .exporter import parse_prometheus, render_prometheus

    scenario = os.environ.get("CHAOS_SCENARIO", "partition_minority")
    seed = int(os.environ.get("CHAOS_SEED", 101))
    # CHAOS_BUGGIFY=1: BUGGIFY knob randomization on top of the
    # scenario (the nightly's randomized-knob cells) — the same seed
    # draws the same knob distortions, so replay determinism holds,
    # and the CONFLICT_SCHEDULING/TXN_REPAIR/CLIENT_CONFLICT_WINDOWS
    # buggify arms run the scheduler/repair paths under the storm
    buggify = os.environ.get("CHAOS_BUGGIFY", "") not in ("", "0")
    if scenario not in SCENARIOS:
        out(f"unknown scenario {scenario!r}; known: {sorted(SCENARIOS)}")
        return 2
    flow.g_trace.reset(os.environ.get("CHAOS_TRACE_FILE",
                                      CHAOS_TRACE_PATH))

    # CHAOS_ADMISSION=1: force the enforced-admission planes on under
    # the scenario (the nightly's admission-armed storm cells — GRV
    # queues, tag throttling and the auto-throttler run under
    # partitions/kills/recoveries with the same consistency + replay
    # oracles; the storm's tagged open-loop traffic drives them)
    admission = os.environ.get("CHAOS_ADMISSION", "") not in ("", "0")

    # CHAOS_HEAT=1: arm the storage heat plane under the scenario (the
    # nightly's heat-armed storm cells — read sampling, read-hot
    # detection and per-SS tag busyness run under partitions/kills
    # with the same consistency + same-seed replay oracles; the plane
    # is observe-only, so the oracles must hold bit-identically)
    heat = os.environ.get("CHAOS_HEAT", "") not in ("", "0")

    # CHAOS_SPLITS=1: arm the resolver balance loop under the scenario
    # (ISSUE 15's storm-splits nightly cells — load-driven splits with
    # live checkpoint/graft handoff race partitions, kills and
    # recoveries under the same same-seed-replay + check_consistency
    # oracles; the storm's Zipfian traffic is skewed enough for the
    # one-shot FORCE to land a split on multi-resolver scenarios)
    splits = os.environ.get("CHAOS_SPLITS", "") not in ("", "0")

    def run_once() -> dict:
        kwargs = dict(SCENARIOS[scenario].cluster_kwargs)
        if buggify:
            kwargs["buggify"] = True
        if splits:
            # the balance loop only exists on multi-resolver clusters;
            # both the run and its replay share this shape, so the
            # same-seed determinism oracle is unaffected
            kwargs["n_resolvers"] = 2
        cluster = SimCluster(seed=seed, **kwargs)
        # the sim-perf plane rides every chaos cell: profiling reads
        # only the wall clock (armed-vs-off same-seed equivalence is
        # test-pinned), and a red cell's post-mortem then carries the
        # wall-time attribution picture (/tmp/_simprof_chaos.json)
        # plus flamegraph-ready collapsed stacks
        # (/tmp/_simprof_chaos.folded — flamegraph.pl / speedscope)
        cluster.sched.start_task_stats()
        cluster.net.arm_message_stats()
        cluster.sched.start_profiler(sample_every=16)
        if admission:
            flow.SERVER_KNOBS.set("grv_admission_control", 1)
            flow.SERVER_KNOBS.set("tag_throttling", 1)
            flow.SERVER_KNOBS.set("auto_tag_throttling", 1)
        if heat:
            flow.SERVER_KNOBS.set("storage_heat_tracking", 1)
            flow.SERVER_KNOBS.set("tag_throttle_storage_busyness", 1)
        if splits:
            flow.SERVER_KNOBS.set("resolver_balance", 1)
            flow.SERVER_KNOBS.set("resolver_balance_force", 1)
            flow.SERVER_KNOBS.set("resolver_balance_interval", 0.5)
        try:
            dbs = [cluster.client(f"chaos{i}") for i in range(3)]
            storm = ChaosStorm(cluster, dbs, flow.g_random, scenario)
            return cluster.run(storm.run(), timeout_time=900)
        finally:
            # the wall-time picture must survive a RED cell (a storm
            # that fails its oracle raises before any report exists):
            # snapshot the attribution tables straight off the
            # scheduler/network, whatever happened
            with open(SIMPROF_CHAOS_PATH, "w") as fh:
                json.dump(
                    {"scenario": scenario, "seed": seed,
                     "tasks_run": cluster.sched.tasks_run,
                     "busy_seconds": round(cluster.sched.busy_seconds,
                                           3),
                     "task_stats": cluster.sched.task_stats_report(),
                     "message_stats":
                         cluster.net.message_stats_report()},
                    fh, indent=2, sort_keys=True, default=str)
                fh.write("\n")
            with open(SIMPROF_CHAOS_FOLDED_PATH, "w") as fh:
                fh.write(cluster.sched.profile_folded() + "\n")
            cluster.shutdown()

    rep = run_once()
    chaos = rep["status"]["cluster"]["chaos"]
    # the report is THE triage artifact the CI matrix uploads on
    # failure — build it now and write it even when an assert below
    # fires (a replay divergence must not lose the event logs)
    report = {"scenario": scenario, "seed": seed,
              "digest": rep["digest"],
              "recovery_seconds": rep["recovery_seconds"],
              "consistency": rep["consistency"],
              "chaos": chaos, "storm": rep["storm"],
              "sim_perf": rep["sim_perf"],
              "events": rep["events"]}
    try:
        assert rep["storm"]["completed"] > 0, rep["storm"]
        assert rep["consistency"]["rows"] > 0, rep["consistency"]

        # the shared accounting schema: status doc, exporter, cli section
        status = rep["status"]
        assert chaos["scenarios"].get(scenario) == 1, chaos
        assert chaos["injected"].get("scenario") == 1, chaos
        samples = parse_prometheus(render_prometheus(status))
        names = {n for n, _l, _v in samples}
        for need in ("fdbtpu_chaos_injected", "fdbtpu_chaos_scenario_runs",
                     "fdbtpu_chaos_events"):
            assert need in names, f"exporter missing {need}"
        runs = {l["scenario"]: v for n, l, v in samples
                if n == "fdbtpu_chaos_scenario_runs"}
        assert runs.get(scenario) == 1, runs
        details = _render_details(status["cluster"])
        assert "Chaos (injected faults):" in details, details
        assert f"scenario {scenario}" in details, details

        # seed replay: the same seed must reproduce the identical fault
        # schedule and the identical final keyspace (the determinism half
        # of the acceptance contract, enforced per nightly grid cell)
        replay = run_once()
        report["replay"] = {"digest": replay["digest"],
                            "events": replay["events"]}
        assert replay["events"] == rep["events"], \
            "replay diverged: event schedules differ (see report)"
        assert replay["digest"] == rep["digest"], (
            rep["digest"], replay["digest"])
    finally:
        with open(report_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")
    out(f"CHAOS SMOKE OK: {scenario} seed={seed} — "
        f"{len(rep['events'])} chaos events "
        f"({', '.join(f'{k}={v}' for k, v in sorted(chaos['injected'].items()))}), "
        f"storm {rep['storm']['completed']}/{rep['storm']['issued']} "
        f"committed, recovery {rep['recovery_seconds']}s, "
        f"digest {rep['digest'][:16]} (replay identical); "
        f"report at {report_path}")
    return 0


def run_smoke_contention(out=print,
                         report_path: str = CONTENTION_REPORT_PATH) -> int:
    """Conflict-prediction & transaction-repair smoke (ISSUE 8's
    acceptance cell): the SAME seeded high-contention storm run twice
    — abort-only baseline vs scheduler + repair + client windows armed
    — at equal offered load. Asserts committed goodput improves by at
    least CONTENTION_MIN_UPLIFT (default 1.25x), the hot-key ADD
    counters sum EXACTLY to the committed count both runs (the
    bit-exactness oracle: a repair that double-applied or lost a
    mutation cannot hide), `check_consistency` stays green under the
    new paths, non-zero deferral AND repair counters surface in
    `status details`, and the fdbtpu_sched_*/fdbtpu_repair_* exporter
    families parse. The goodput table lands at /tmp/_contention_report
    for the CI artifact (and PERF.md's scheduler off/on/on+repair
    table)."""
    import json
    import os

    from .. import flow
    from ..server import SimCluster
    from ..server.consistency import check_consistency
    from ..server.workloads import ContentionStorm
    from .cli import _render_details
    from .exporter import parse_prometheus, render_prometheus

    seed = int(os.environ.get("CONTENTION_SEED", 8383))
    duration = float(os.environ.get("CONTENTION_DURATION", 4.0))
    rate = float(os.environ.get("CONTENTION_RATE", 150.0))
    min_uplift = float(os.environ.get("CONTENTION_MIN_UPLIFT", 1.25))

    def run_once(scheduling: bool, repair: bool) -> tuple:
        cluster = SimCluster(seed=seed, durable=True)
        # knobs AFTER SimCluster re-initializes them; restored by the
        # next SimCluster (and the finally) so runs stay independent
        flow.SERVER_KNOBS.set("conflict_scheduling", int(scheduling))
        flow.SERVER_KNOBS.set("client_conflict_windows", int(scheduling))
        flow.SERVER_KNOBS.set("txn_repair", int(repair))
        flow.SERVER_KNOBS.set("sched_hot_push_interval", 0.05)
        try:
            dbs = [cluster.client(f"cont{i}") for i in range(4)]

            async def main():
                storm = ContentionStorm(dbs, flow.g_random,
                                        duration=duration, rate=rate)
                stats = await storm.run()
                total = await storm.read_hot_total(dbs[0])
                # bit-exactness oracle: every committed txn added
                # exactly 1; unknown-outcome attempts may or may not
                # have landed and were deliberately not retried
                assert stats["committed"] <= total <= \
                    stats["committed"] + stats["unknown"], (total, stats)
                cons = await check_consistency(cluster)
                status = await dbs[0].get_status()
                return stats, status, cons

            stats, status, cons = cluster.run(main(), timeout_time=900)
            assert cons["rows"] > 0, cons
            return stats, status
        finally:
            flow.reset_server_knobs(randomize=False)
            cluster.shutdown()

    base_stats, _base_status = run_once(scheduling=False, repair=False)
    on_stats, on_status = run_once(scheduling=True, repair=True)

    base_good = base_stats["goodput_per_sec"]
    on_good = on_stats["goodput_per_sec"]
    report = {"seed": seed, "offered_rate": rate, "duration": duration,
              "baseline": base_stats, "scheduler_repair_on": on_stats,
              "uplift": round(on_good / max(base_good, 1e-9), 3),
              "min_uplift": min_uplift}
    try:
        assert base_stats["conflicts"] > 0, \
            ("baseline never conflicted — not a contention storm",
             base_stats)
        assert on_good >= min_uplift * base_good, (
            f"goodput uplift {on_good}/{base_good} = "
            f"{on_good / max(base_good, 1e-9):.2f}x < {min_uplift}x")

        cl = on_status["cluster"]
        sched_doc = cl["conflict_scheduling"]
        assert sched_doc["scheduling_enabled"] == 1, sched_doc
        assert sched_doc["repair_enabled"] == 1, sched_doc
        # the decision planes actually fired
        assert sched_doc["deferrals"] > 0, sched_doc
        assert sched_doc["repair_committed"] > 0, sched_doc
        details = _render_details(cl)
        assert "Conflict scheduler:" in details, details
        assert "Transaction repair:" in details, details

        samples = parse_prometheus(render_prometheus(on_status))
        names = {n for n, _l, _v in samples}
        for need in ("fdbtpu_sched_enabled", "fdbtpu_sched_deferrals",
                     "fdbtpu_sched_released", "fdbtpu_sched_client",
                     "fdbtpu_repair_attempts", "fdbtpu_repair_committed",
                     "fdbtpu_repair_in_flight"):
            assert need in names, f"exporter missing {need}"
        repaired = sum(v for n, _l, v in samples
                       if n == "fdbtpu_repair_committed")
        assert repaired > 0, "no repaired commits in the exporter"
    finally:
        with open(report_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")
    out(f"CONTENTION SMOKE OK: goodput {base_good}/s abort-only -> "
        f"{on_good}/s with scheduler+repair "
        f"({report['uplift']}x, floor {min_uplift}x) at "
        f"{rate}/s offered; "
        f"{on_stats['committed']}/{on_stats['issued']} committed "
        f"(baseline {base_stats['committed']}/{base_stats['issued']}, "
        f"{base_stats['failed']} gave up), "
        f"deferrals={sched_doc['deferrals']} "
        f"repaired={sched_doc['repair_committed']}; "
        f"report at {report_path}")
    return 0


def run_smoke_overload(out=print,
                       report_path: str = OVERLOAD_REPORT_PATH) -> int:
    """Enforced-admission-control smoke (ISSUE 10's acceptance cell):
    the SAME seeded overload storm run twice — a simulated open-loop
    client population (OVERLOAD_CLIENTS logical tenants, Zipfian keys,
    one abusive tenant tag) offering several times the ratekeeper's
    budget against a tightened storage-queue target. Disarmed, the
    run demonstrates the collapse (GRV waits walk toward the client
    timeout for every tenant). Armed (GRV admission queues + tag
    throttling + auto-throttler), the cluster must settle at the
    budget: committed throughput within the ratekeeper's limit,
    BOUNDED admitted-GRV p99, a non-none limiting reason, an auto
    throttle row for the abusive tag in \\xff\\x02/throttledTags/,
    non-zero fdbtpu_throttle_* counters, and the other tenants' p99
    recovering vs the disarmed run. The before/after table lands at
    /tmp/_overload_report.txt for the CI artifact."""
    import json
    import os

    from .. import flow
    from ..client import run_transaction
    from ..server import SimCluster
    from ..server import systemkeys as sk
    from ..server.ratekeeper import LIMIT_REASONS
    from ..server.workloads import OverloadStorm
    from .cli import _render_details
    from .exporter import parse_prometheus, render_prometheus

    seed = int(os.environ.get("OVERLOAD_SEED", 9393))
    duration = float(os.environ.get("OVERLOAD_DURATION", 4.0))
    fair_rate = float(os.environ.get("OVERLOAD_FAIR_RATE", 60.0))
    abusive_rate = float(os.environ.get("OVERLOAD_ABUSIVE_RATE", 240.0))
    n_clients = int(os.environ.get("OVERLOAD_CLIENTS", 100_000))

    def run_once(armed: bool) -> tuple:
        cluster = SimCluster(seed=seed, durable=True, n_proxies=2)
        # knobs AFTER SimCluster re-initializes them; restored by the
        # next SimCluster (and the finally) so the runs stay
        # independent. The tightened storage-queue target is what
        # makes the offered load an OVERLOAD for both runs.
        flow.SERVER_KNOBS.set("rk_target_storage_queue_bytes", 4000)
        flow.SERVER_KNOBS.set("rk_spring_storage_queue_bytes", 1000)
        flow.SERVER_KNOBS.set("qos_sample_interval", 0.25)
        if armed:
            flow.SERVER_KNOBS.set("grv_admission_control", 1)
            flow.SERVER_KNOBS.set("tag_throttling", 1)
            flow.SERVER_KNOBS.set("auto_tag_throttling", 1)
            flow.SERVER_KNOBS.set("tag_throttle_update_interval", 0.25)
            flow.SERVER_KNOBS.set("tag_throttle_poll_interval", 0.1)
            flow.SERVER_KNOBS.set("tag_throttle_busy_rate", 40.0)
            flow.SERVER_KNOBS.set("tag_throttle_duration", 30.0)
            flow.SERVER_KNOBS.set("grv_queue_max_wait", 1.0)
        try:
            dbs = [cluster.client(f"ovl{i}") for i in range(8)]

            async def main():
                storm = OverloadStorm(dbs, flow.g_random,
                                      duration=duration,
                                      fair_rate=fair_rate,
                                      abusive_rate=abusive_rate,
                                      n_clients=n_clients)
                stats = await storm.run()

                async def throttle_rows(tr):
                    tr.set_option("read_system_keys")
                    return await tr.get_range(sk.THROTTLED_TAGS_PREFIX,
                                              sk.THROTTLED_TAGS_END)
                rows = await run_transaction(dbs[0], throttle_rows,
                                             max_retries=200)
                status = await dbs[0].get_status()
                return stats, rows, status

            return cluster.run(main(), timeout_time=900)
        finally:
            flow.reset_server_knobs(randomize=False)
            cluster.shutdown()

    flow.g_trace.reset(None)
    base_stats, _base_rows, base_status = run_once(armed=False)
    base_rk = [e for e in flow.g_trace.events
               if e.get("Type") == "RkUpdate"]
    flow.g_trace.reset(None)
    on_stats, on_rows, on_status = run_once(armed=True)
    on_rk = [e for e in flow.g_trace.events if e.get("Type") == "RkUpdate"]

    def grv_economy(status, stats) -> dict:
        """The confirmation-round economy: offered arrivals vs wire
        GRV requests (client batching) vs causal-confirmation round
        trips (proxy batching + enforcement) — the interior
        request-rate drop the GRV coalescing buys."""
        px = [p["counters"] for p in status["cluster"].get("proxies",
                                                           ())]
        started = sum(c.get("transactions_started", 0) for c in px)
        wire = sum(c.get("grv_wire_requests", 0) for c in px)
        rounds = sum(c.get("grv_confirm_rounds", 0) for c in px)
        return {"offered_arrivals": stats["issued"],
                "transactions_started": started,
                "wire_grv_requests": wire,
                "confirm_rounds": rounds,
                "offered_per_confirm_round": round(
                    stats["issued"] / max(rounds, 1), 2)}

    cl = on_status["cluster"]
    adm = cl.get("admission_control") or {}
    limited = [e for e in on_rk
               if e.get("LimitingReason") not in (None, "none")]
    # the deepest throttle the controller commanded: a spring-zone
    # descent passes through barely-limited updates, so the FLOOR is
    # what proves the storm genuinely out-offered the budget
    budget = min((e["TPSLimit"] for e in limited), default=None)
    # the settle-window budget: what the ratekeeper commanded during
    # the storm's second half (each update capped at the offered rate
    # so a recovered 1e9 "unlimited" tick can't poison the mean)
    offered = fair_rate + abusive_rate
    late_cut = max((e.get("Time", 0.0) for e in on_rk), default=0.0) \
        - duration / 2
    late_updates = [e for e in on_rk if e.get("Time", 0.0) >= late_cut]
    late_budget = (sum(min(e["TPSLimit"], offered) for e in late_updates)
                   / len(late_updates) if late_updates else offered)
    report = {
        "seed": seed, "n_clients": n_clients,
        "offered_per_sec": fair_rate + abusive_rate,
        "duration": duration,
        "disarmed": base_stats, "armed": on_stats,
        "ratekeeper_budget_floor_tps": budget,
        "late_window_budget_tps": late_budget,
        "throttled_tags": [r["tag"] for r in adm.get("throttled_tags",
                                                     ())],
        "grv_batching": {"disarmed": grv_economy(base_status,
                                                 base_stats),
                         "armed": grv_economy(on_status, on_stats)},
        "rk_updates": {"disarmed": len(base_rk), "armed": len(on_rk),
                       "armed_limited": len(limited)},
    }
    try:
        wall = max(on_stats["wall_seconds"], 1e-9)
        # (1) the storm genuinely overloads: the ratekeeper engaged a
        # non-none limiting reason during the armed run
        assert limited, ("limiting reason never engaged", on_rk[-3:])
        for e in limited:
            assert e["LimitingReason"] in LIMIT_REASONS, e
        # (2) the cluster SETTLES at the budget instead of collapsing:
        # once past the initial unthrottled burst (the storm's second
        # half), committed throughput sits within the rate the
        # ratekeeper commanded over that window, with real progress —
        # and the offered load is genuinely above the throttled budget
        assert on_stats["completed"] > 0, on_stats
        assert budget is not None and budget > 0, limited[-3:]
        late_rate = on_stats["late_committed_per_sec"]
        assert late_rate <= late_budget * 1.5 + 5.0, (late_rate,
                                                      late_budget)
        assert offered > budget, ("not an overload at all", budget)
        # (3) bounded admitted-GRV p99: the wait bound (1.0s armed)
        # plus confirmation slack — far below the 5s client timeout
        # the disarmed queue walks toward
        for group in ("abusive", "others"):
            g = on_stats["grv"][group]
            if g.get("count"):
                assert g["p99"] <= 2.0, (group, g)
        # (4) the abusive tenant was auto-throttled: a live row in the
        # system keyspace, parseable, auto-flagged
        throttled = {}
        for key, value in on_rows:
            tag = sk.parse_throttled_tag_key(key)
            parsed = sk.parse_tag_throttle_value(value)
            if tag is not None and parsed is not None:
                throttled[tag] = parsed
        assert b"tenant-abuse" in throttled, sorted(throttled)
        assert throttled[b"tenant-abuse"][3] is True, throttled
        # (5) enforcement + backoff actually fired: non-zero
        # fdbtpu_throttle_* counters through the exporter
        samples = parse_prometheus(render_prometheus(on_status))
        by_name: dict = {}
        for n, _l, v in samples:
            by_name[n] = by_name.get(n, 0) + v
        for need in ("fdbtpu_admission_enabled",
                     "fdbtpu_admission_admitted",
                     "fdbtpu_throttle_tags", "fdbtpu_throttle_tag_tps"):
            assert need in by_name, f"exporter missing {need}"
        assert by_name.get("fdbtpu_throttle_tags", 0) > 0, by_name
        throttle_activity = (by_name.get("fdbtpu_throttle_delayed", 0)
                             + by_name.get("fdbtpu_throttle_client", 0)
                             + by_name.get("fdbtpu_throttle_rejected", 0))
        assert throttle_activity > 0, by_name
        # (6) the other tenants RECOVER: their p99 improves vs the
        # disarmed collapse (same seed, same offered load)
        base_p99 = base_stats["grv"]["others"]["p99"]
        on_p99 = on_stats["grv"]["others"]["p99"]
        assert on_p99 < base_p99, (on_p99, base_p99)
        # ...and the disarmed run really was a collapse: unbounded
        # queueing pushed waits at least toward seconds, or clients
        # timed out outright
        base_timeouts = base_stats["errors"].get("timed_out", 0)
        assert base_p99 > 1.0 or base_timeouts > 0, base_stats
        # (7) operator surfaces render
        details = _render_details(cl)
        assert "Admission control:" in details, details
        assert "throttled tag" in details, details
        report["asserts"] = "all passed"
    finally:
        with open(report_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")
    out(f"OVERLOAD SMOKE OK: {on_stats['distinct_clients']} of "
        f"{n_clients} simulated clients offering "
        f"{fair_rate + abusive_rate:g}/s vs budget ~{budget:.0f}/s — "
        f"armed committed {on_stats['completed']}/{on_stats['issued']} "
        f"({on_stats['committed_per_sec']}/s, attainment "
        f"{on_stats['attainment']}), others' grv p99 "
        f"{base_p99:.3f}s -> {on_p99:.3f}s, "
        f"abusive tag auto-throttled, "
        f"{report['grv_batching']['armed']['offered_per_confirm_round']}"
        f" offered GRVs per confirmation round; "
        f"report at {report_path}")
    return 0


def run_smoke_heat(out=print, report_path: str = HEAT_REPORT_PATH) -> int:
    """Storage-heat smoke (ISSUE 13's acceptance cell): the SAME seeded
    HotShardStorm (one tenant tag concentrating Zipfian reads on a
    narrow hot range, background tenants reading uniformly) run three
    times — plane off, armed, and armed replay.

    Off-posture pin: arming the plane must not perturb the sim at all
    (identical keyspace digest, scheduler step count and network
    message count — the storm is read-only, and the heat plane adds no
    messages or tasks). Armed: `status.cluster.storage_heat` must NAME
    the injected hot sub-range and the hot tenant tag, the heat
    signals must ride the storage QosSamples, the fdbtpu_storage_*
    exporter families must parse, and `cli heat` + the `status
    details` section must render. Replay: the armed run's heat rows
    must be bit-identical at the same seed. The report lands at
    /tmp/_heat_report.txt for the CI artifact."""
    import json
    import os

    from .. import flow
    from ..server import SimCluster
    from ..server.chaos import database_digest
    from ..server.workloads import HotShardStorm
    from .cli import _render_details, _render_heat
    from .exporter import parse_prometheus, render_prometheus

    seed = int(os.environ.get("HEAT_SEED", 5151))
    duration = float(os.environ.get("HEAT_DURATION", 3.0))

    def run_once(armed: bool) -> tuple:
        cluster = SimCluster(seed=seed, durable=True)
        # knobs AFTER SimCluster re-initializes them; restored by the
        # next SimCluster (and the finally) so the runs stay independent
        flow.SERVER_KNOBS.set("qos_sample_interval", 0.25)
        if armed:
            flow.SERVER_KNOBS.set("storage_heat_tracking", 1)
        try:
            dbs = [cluster.client(f"heat{i}") for i in range(4)]

            async def main():
                storm = HotShardStorm(dbs, flow.g_random,
                                      duration=duration)
                await storm.seed(dbs[0])
                stats = await storm.run()
                await flow.delay(1.0)   # QoS sampler + heat rollup ticks
                status = await dbs[0].get_status()
                digest = await database_digest(dbs[0])
                return storm, stats, status, digest

            storm, stats, status, digest = cluster.run(main(),
                                                       timeout_time=600)
            return (storm, stats, status, digest,
                    cluster.sched.tasks_run, cluster.net.messages_sent)
        finally:
            flow.reset_server_knobs(randomize=False)
            cluster.shutdown()

    _sto, off_stats, off_status, off_digest, off_tasks, off_msgs = \
        run_once(armed=False)
    storm, on_stats, on_status, on_digest, on_tasks, on_msgs = \
        run_once(armed=True)
    _sto2, re_stats, re_status, re_digest, _re_tasks, re_msgs = \
        run_once(armed=True)

    cl = on_status["cluster"]
    heat = cl.get("storage_heat") or {}
    report = {"seed": seed, "duration": duration,
              "storm": on_stats, "heat": heat,
              "off": {"digest": off_digest, "tasks_run": off_tasks,
                      "messages_sent": off_msgs,
                      "heat": off_status["cluster"].get("storage_heat")},
              "armed": {"digest": on_digest, "tasks_run": on_tasks,
                        "messages_sent": on_msgs},
              "replay": {"digest": re_digest, "messages_sent": re_msgs,
                         "heat": re_status["cluster"].get("storage_heat")}}
    try:
        # (1) off-posture pin: arming the observe-only plane must not
        # perturb the sim — same digest, same step count, same message
        # count, same storm outcome
        assert on_digest == off_digest, (off_digest, on_digest)
        assert on_tasks == off_tasks, (off_tasks, on_tasks)
        assert on_msgs == off_msgs, (off_msgs, on_msgs)
        assert on_stats["issued"] == off_stats["issued"], report
        assert on_stats["completed"] == off_stats["completed"], report
        # ...and the disarmed plane is genuinely empty
        off_heat = off_status["cluster"]["storage_heat"]
        assert off_heat["tracking_enabled"] == 0, off_heat
        assert not off_heat["ranges"], off_heat
        assert not off_heat["busiest_read_tags"], off_heat

        # (2) the armed plane NAMES the injected hot sub-range: the
        # top-ranked flagged range overlaps the storm's hot range
        assert heat["tracking_enabled"] == 1, heat
        assert heat["ranges"], "no read-hot ranges flagged"
        hb, he = storm.hot_range
        top = heat["ranges"][0]
        tb, te = bytes.fromhex(top["begin"]), bytes.fromhex(top["end"])
        assert tb < he and te > hb, (
            "top hot range misses the injected one", top,
            hb.hex(), he.hex())
        assert top["density"] >= float(
            flow.SERVER_KNOBS.read_hot_range_ratio), top

        # (3) ...and the hot tenant: every reporting server's busiest
        # read tag is the storm's hot tag
        btags = heat["busiest_read_tags"]
        assert btags, "no busiest-read-tag rows"
        assert all(r["tag"] == storm.hot_tag.hex() for r in btags), btags

        # (4) the heat signals ride the storage QosSamples and the
        # ratekeeper saw the observe-only inputs
        roles = (cl.get("qos") or {}).get("roles") or {}
        sto = next(iter(roles.get("storage", {}).values()))
        for sig in ("read_bytes_per_sec", "read_ops_per_sec",
                    "read_hot_ranges", "busiest_read_tag_busyness",
                    "write_bandwidth"):
            assert sig in sto, (sig, sto)
        assert sto["read_bytes_per_sec"] > 0, sto
        inputs = (cl.get("qos") or {}).get("inputs") or {}
        assert inputs.get("worst_read_hot", 0) > 0, inputs
        assert inputs.get("busiest_read_tag_busyness", 0) > 0, inputs
        assert (cl.get("qos") or {}).get("busiest_read_tag") == \
            storm.hot_tag.hex(), cl.get("qos")

        # (5) exporter families parse and cover the plane
        samples = parse_prometheus(render_prometheus(on_status))
        names = {n for n, _l, _v in samples}
        for need in ("fdbtpu_storage_read_bytes",
                     "fdbtpu_storage_read_ops",
                     "fdbtpu_storage_read_hot_ranges",
                     "fdbtpu_storage_tag_busyness",
                     "fdbtpu_storage_shard_bytes",
                     "fdbtpu_storage_write_bandwidth",
                     "fdbtpu_storage_heat_tracking"):
            assert need in names, f"exporter missing {need}"
        busy = [(l, v) for n, l, v in samples
                if n == "fdbtpu_storage_tag_busyness"]
        assert busy and all(l["tag"] == storm.hot_tag.hex()
                            for l, _v in busy), busy

        # (6) operator surfaces render
        heat_view = _render_heat(cl)
        for section in ("Storage heat", "Read-hot sub-ranges",
                        "Busiest read tag", storm.hot_tag.hex()):
            assert section in heat_view, (section, heat_view)
        details = _render_details(cl)
        assert "Storage heat (read-hot sub-ranges):" in details, details

        # (7) same-seed replay: the armed plane names the same range
        # and tag BIT-IDENTICALLY (digest + message count too)
        re_heat = re_status["cluster"]["storage_heat"]
        assert re_heat == heat, (heat, re_heat)
        assert re_digest == on_digest, (on_digest, re_digest)
        assert re_msgs == on_msgs, (on_msgs, re_msgs)
        assert re_stats == on_stats or re_stats["issued"] == \
            on_stats["issued"], (on_stats, re_stats)
        report["asserts"] = "all passed"
    finally:
        with open(report_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")
    out(f"HEAT SMOKE OK: {on_stats['issued']} read arrivals "
        f"({on_stats['hot_issued']} hot / "
        f"{on_stats['background_issued']} background), hot range "
        f"[{top['begin']}, {top['end']}) density {top['density']}x "
        f"named on server {top['server']}, busiest tag "
        f"{btags[0]['tag']} everywhere, off-posture pin held "
        f"(digest {on_digest[:16]}, {on_tasks} steps, {on_msgs} msgs), "
        f"replay identical; report at {report_path}")
    return 0


def run_smoke_simprof(out=print,
                      report_path: str = SIMPROF_REPORT_PATH) -> int:
    """Sim-perf attribution smoke (ISSUE 11's acceptance cell): the
    SAME seeded open-loop storm run twice — SIM_TASK_STATS off, then
    armed. The off-posture pin: identical keyspace digest, identical
    network message count, identical scheduler step count and storm
    outcome (profiling reads only the wall clock, never the sim
    timeline). The armed run must POPULATE the plane: a per-task table
    naming the storm's actors, a priority-band rollup, per-message-type
    counts, the wall-vs-sim budget in the storm report, the
    fdbtpu_task_* / fdbtpu_net_* / fdbtpu_sim_* exporter families
    parsing, and the `cli top` attribution section rendering. The
    report lands at /tmp/_simprof_smoke.txt for the CI artifact."""
    import json
    import os

    from .. import flow
    from ..server import SimCluster
    from ..server.chaos import database_digest
    from ..server.workloads import OpenLoopStorm
    from .cli import _render_top
    from .exporter import parse_prometheus, render_prometheus

    seed = int(os.environ.get("SIMPROF_SEED", 7272))
    duration = float(os.environ.get("SIMPROF_DURATION", 2.0))

    def run_once(armed: bool) -> tuple:
        cluster = SimCluster(seed=seed, durable=True)
        if armed:
            # knob AFTER SimCluster re-initializes them; arm directly
            # (the knob path arms at boot for operator-configured runs)
            flow.SERVER_KNOBS.set("sim_task_stats", 1)
            cluster.sched.start_task_stats()
            cluster.net.arm_message_stats()
        try:
            dbs = [cluster.client(f"sp{i}") for i in range(4)]

            async def main():
                storm = OpenLoopStorm(
                    dbs, flow.g_random, duration=duration, rate=80.0,
                    burst_rate=300.0, burst_start=0.5, burst_len=0.5,
                    max_inflight=256)
                stats = await storm.run()
                digest = await database_digest(dbs[0])
                status = await dbs[0].get_status()
                return stats, digest, status

            stats, digest, status = cluster.run(main(), timeout_time=600)
            return (stats, digest, status, cluster.sched.tasks_run,
                    cluster.net.messages_sent)
        finally:
            flow.reset_server_knobs(randomize=False)
            cluster.shutdown()

    off_stats, off_digest, _off_status, off_tasks, off_msgs = \
        run_once(armed=False)
    on_stats, on_digest, on_status, on_tasks, on_msgs = \
        run_once(armed=True)

    sp = on_stats.get("sim_perf") or {}
    report = {"seed": seed, "duration": duration,
              "off": {"digest": off_digest, "tasks_run": off_tasks,
                      "messages_sent": off_msgs,
                      "issued": off_stats["issued"],
                      "completed": off_stats["completed"]},
              "armed": {"digest": on_digest, "tasks_run": on_tasks,
                        "messages_sent": on_msgs,
                        "issued": on_stats["issued"],
                        "completed": on_stats["completed"]},
              "sim_perf": sp}
    try:
        # (1) off-posture pin: the armed plane must not perturb the sim
        assert on_digest == off_digest, (off_digest, on_digest)
        assert on_msgs == off_msgs, (off_msgs, on_msgs)
        assert on_tasks == off_tasks, (off_tasks, on_tasks)
        assert on_stats["issued"] == off_stats["issued"], report
        assert on_stats["completed"] == off_stats["completed"], report

        # (2) the plane populates under the storm
        assert sp.get("top_tasks"), sp
        top_names = [r["task"] for r in sp["top_tasks"]]
        assert "storm-txn-*" in top_names, top_names
        assert sp.get("top_messages"), sp
        msg_types = {r["type"] for r in sp["top_messages"]}
        assert "GetReadVersionRequest" in msg_types, msg_types
        rl = on_status["cluster"]["run_loop"]
        ts = rl.get("task_stats") or {}
        assert ts.get("tasks") and ts.get("bands"), rl
        assert rl.get("sim_per_busy"), rl
        netdoc = on_status["cluster"]["network"]
        assert netdoc["armed"] and netdoc["types"], netdoc

        # (3) exporter families parse and cover the plane
        samples = parse_prometheus(render_prometheus(on_status))
        names = {n for n, _l, _v in samples}
        for need in ("fdbtpu_task_steps", "fdbtpu_task_busy_us",
                     "fdbtpu_task_band_steps", "fdbtpu_net_messages",
                     "fdbtpu_net_delivery_timers", "fdbtpu_sim_seconds",
                     "fdbtpu_sim_per_busy_second"):
            assert need in names, f"exporter missing {need}"

        # (4) the operator view renders the attribution tables
        top = _render_top(on_status["cluster"])
        assert "Run-loop attribution" in top, top
        assert "Network messages" in top, top
        report["asserts"] = "all passed"
    finally:
        with open(report_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")
    out(f"SIMPROF SMOKE OK: seed={seed} off-posture pin held "
        f"(digest {on_digest[:16]}, {on_tasks} steps, {on_msgs} msgs "
        f"both postures); sim {sp['sim_seconds']}s in wall "
        f"{sp['wall_seconds']}s ({sp['sim_per_wall']}x), top task "
        f"{top_names[0]}, {len(msg_types)} message types; "
        f"report at {report_path}")
    return 0


def run_smoke_packed(out=print) -> int:
    """Packed interval feed-path smoke: a TPU-backend (cpu-platform
    jax) cluster runs a conflicting workload with both point and
    genuine interval conflict ranges, and the packed single-buffer
    discipline must be LIVE and counted — exactly ONE host->device
    transfer per dispatched batch (`kernel_stats()["h2d"]`), staging
    buffers reused rather than reallocated, the `h2d=` figure rendered
    in `status details`, and the fdbtpu_kernel_h2d_* exporter family
    parsing with per_batch == 1."""
    import os

    from .. import flow
    from ..client import run_transaction
    from ..server import SimCluster
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus

    cluster = SimCluster(seed=4747, durable=True, conflict_backend="tpu")
    flow.SERVER_KNOBS.set(
        "resolve_pipeline_depth",
        int(os.environ.get("RESOLVE_PIPELINE_DEPTH", 4)))
    assert int(flow.SERVER_KNOBS.interval_packed_feed) == 1, \
        "packed feed must be the default posture"
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("psmoke")

        async def workload():
            async def seed(tr):
                for i in range(8):
                    tr.set(b"k%02d" % i, b"0")
            await run_transaction(db, seed)
            # enough conflicting rounds that the staging pool must be
            # REUSED (transfers well past the pool size), with interval
            # read ranges (get_range) riding next to point ones
            for i in range(12):
                tr = db.create_transaction()
                await tr.get_range(b"k00", b"k99")
                tr.set(b"mine%d" % i, b"v")

                async def bump(t2, i=i):
                    t2.set(b"k%02d" % (i % 8), b"x%d" % i)
                await run_transaction(db, bump)
                try:
                    await tr.commit()
                    raise AssertionError("expected a conflict")
                except flow.FdbError as e:
                    assert e.name == "not_committed", e.name
            return await db.get_status()

        status = cluster.run(workload(), timeout_time=300)
        cl = status["cluster"]
        res = cl.get("resolvers", ())
        assert res, "no resolvers in status"
        for r in res:
            kern = r.get("kernel") or {}
            assert kern.get("backend") == "tpu", kern.get("backend")
            h2d = kern.get("h2d") or {}
            batches = kern.get("batches", 0)
            assert batches > 0, "no batches dispatched"
            # THE acceptance figure: one transfer per interval batch,
            # counted at the dispatch seam — not inferred
            assert h2d.get("transfers") == batches, (h2d, batches)
            assert h2d.get("per_batch") == 1.0, h2d
            assert h2d.get("bytes", 0) > 0, h2d
            # steady state is allocation-flat: the staging pool is
            # bounded by pipeline depth + 2 (plus the encode scratch),
            # far below one-allocation-per-batch churn
            allocs = h2d.get("staging_allocs", 0)
            assert 0 < allocs < batches, (allocs, batches)

        details = cli.execute("status details")
        assert "Resolver kernels:" in details, details
        assert "h2d=1/batch" in details, details

        text = render_prometheus(status)
        samples = parse_prometheus(text)   # raises on malformed lines
        names = {n for n, _, _ in samples}
        for need in ("fdbtpu_kernel_h2d_transfers",
                     "fdbtpu_kernel_h2d_bytes",
                     "fdbtpu_kernel_h2d_per_batch",
                     "fdbtpu_kernel_h2d_staging_allocs"):
            assert need in names, f"exporter missing {need}"
        per_batch = [v for n, _, v in samples
                     if n == "fdbtpu_kernel_h2d_per_batch"]
        assert per_batch and all(v == 1.0 for v in per_batch), per_batch
        h2d = res[0]["kernel"]["h2d"]
        out(f"PACKED SMOKE OK: {h2d['transfers']} transfers / "
            f"{res[0]['kernel']['batches']} batches "
            f"({h2d['bytes']}B, {h2d['staging_allocs']} staging allocs), "
            f"{len(samples)} exporter samples")
        return 0
    finally:
        cluster.shutdown()


def run_smoke_splits(out=print,
                     report_path: str = SPLITS_REPORT_PATH) -> int:
    """Dynamic resolver split smoke (ISSUE 15's acceptance cell): the
    SAME seeded skewed SplitStorm run twice on a 2-proxy × 2-resolver
    cluster — balance loop armed-but-idle (unreachable MIN_WORK) as
    the unsplit baseline, then with the one-shot FORCE dropped in
    mid-storm so exactly one load-driven split (checkpoint → clip →
    graft-install → early release) lands under live traffic.

    Asserts: the split run's read-modify-write counter sums are EXACT
    and its keyspace digest equals the unsplit same-seed run's (the
    bit-exact-handoff acceptance); ≥1 split with the donor's per-batch
    load share measurably reduced; split counters render in `status
    details`; and the fdbtpu_resolver_split_* exporter family parses.
    Report lands at /tmp/_splits_report.txt for the CI artifact."""
    import json
    import os

    from .. import flow
    from ..server import SimCluster
    from ..server.workloads import SplitStorm
    from .cli import _render_details
    from .exporter import parse_prometheus, render_prometheus

    seed = int(os.environ.get("SPLITS_SEED", 4242))
    duration = float(os.environ.get("SPLITS_DURATION", 10.0))

    def run_once(force_split: bool) -> tuple:
        cluster = SimCluster(seed=seed, n_resolvers=2, n_proxies=2)
        # the loop is SPAWNED (so arming mid-storm works) but cannot
        # trigger: MIN_WORK is unreachable until the storm drops in
        # the one-shot FORCE; merges disabled so the forced split's
        # load-share drop is stable for the assert
        flow.SERVER_KNOBS.set("resolver_balance", 1)
        flow.SERVER_KNOBS.set("resolver_balance_min_work", 10 ** 12)
        flow.SERVER_KNOBS.set("resolver_balance_merge_work", -1)
        flow.SERVER_KNOBS.set("resolver_balance_interval", 0.5)
        try:
            dbs = [cluster.client(f"sp{i}") for i in range(4)]

            async def main():
                storm = SplitStorm(
                    cluster, dbs, flow.g_random, duration=duration,
                    arm_at=duration * 0.4 if force_split else None)
                rep = await storm.run()
                status = await dbs[0].get_status()
                return rep, status

            rep, status = cluster.run(main(), timeout_time=900)
            return rep, status
        finally:
            flow.reset_server_knobs(randomize=False)
            cluster.shutdown()

    base_rep, _base_status = run_once(force_split=False)
    rep, status = run_once(force_split=True)

    report = {"seed": seed, "duration": duration,
              "unsplit": base_rep, "split": rep}
    try:
        # unsplit baseline really was unsplit; forced run really split
        assert base_rep["balance"]["splits"] == 0, base_rep["balance"]
        assert rep["balance"]["splits"] >= 1, rep["balance"]
        assert rep["balance"]["releases"] >= 1, rep["balance"]
        # bit-exact across the handoff: exact increment sums AND the
        # same final keyspace as the same-seed unsplit run
        assert rep["exact"], (rep["expected"], rep["observed"])
        assert base_rep["exact"], base_rep
        assert rep["digest"] == base_rep["digest"], \
            ("split run diverged from unsplit same-seed run",
             rep["digest"], base_rep["digest"])
        assert rep["stats"]["conflicted"] == 0, rep["stats"]
        # the split measurably reduced the donor's per-batch share
        assert rep["share_before"] is not None \
            and rep["share_after"] is not None, rep
        assert rep["share_after"] <= rep["share_before"] - 0.1, (
            rep["share_before"], rep["share_after"])

        cl = status["cluster"]
        bal = cl["resolver_balance"]
        assert bal["enabled"] == 1 and bal["splits"] >= 1, bal
        assert bal["last_split"], bal
        installs = sum(r["splits"].get("installs", 0)
                       for r in cl["resolvers"])
        assert installs >= 1, cl["resolvers"]
        details = _render_details(cl)
        assert "Resolver balance" in details, details
        assert "last split" in details, details
        samples = parse_prometheus(render_prometheus(status))
        names = {n for n, _l, _v in samples}
        for need in ("fdbtpu_resolver_split_enabled",
                     "fdbtpu_resolver_split_splits",
                     "fdbtpu_resolver_split_releases",
                     "fdbtpu_resolver_split_owned_ranges",
                     "fdbtpu_resolver_split_state_rows",
                     "fdbtpu_resolver_split_installs"):
            assert need in names, f"exporter missing {need}"
        splits_total = sum(v for n, _l, v in samples
                           if n == "fdbtpu_resolver_split_splits")
        assert splits_total >= 1, "no splits in the exporter"
    finally:
        with open(report_path, "w") as fh:
            fh.write(json.dumps(report, indent=2, sort_keys=True,
                                default=str) + "\n")
    out(f"splits smoke OK: {rep['balance']['splits']} split(s), donor "
        f"share {rep['share_before']} -> {rep['share_after']}, digest "
        f"matches unsplit run; report -> {report_path}")
    return 0


def run_smoke_soak(out=print,
                   report_path: str = SOAK_REPORT_PATH) -> int:
    """Short multi-OS-process soak (ISSUE 16's acceptance cell): a
    real 2-client-worker soak over TCP with one SIGKILL+respawn armed
    and tracing on.

    Asserts: commits landed with ZERO divergent verdicts; the kill
    recovered (recovery time recorded); the keyspace digest is stable
    across two passes; the mid-run federated scrape covered host +
    workers and parsed cleanly; and tools/tracemerge.py reassembled at
    least one FULL client->proxy->resolver->tlog span chain across the
    OS-process boundary from the run directory's trace files."""
    import json
    import os

    from .soak import render_soak_report, run_soak

    seed = int(os.environ.get("SOAK_SEED", 11))
    duration = float(os.environ.get("SOAK_DURATION", 8.0))
    doc = run_soak(processes=2, resolvers=2, duration=duration,
                   rate=400.0, kills=1, seed=seed, out=out)
    try:
        assert not doc["errors"], doc["errors"]
        assert doc["totals"]["committed"] > 0, doc["totals"]
        assert doc["totals"]["divergent_verdicts"] == 0, doc["totals"]
        assert doc["digest"]["consistent"], doc["digest"]
        assert len(doc["kills"]) == 1, doc["kills"]
        assert "recovery_s" in doc["kills"][0], doc["kills"]
        fed = doc["federation"]
        # host + >=2 worker entries, and the scrape parsed (the parse
        # runs inside run_soak; a malformed scrape lands in errors)
        assert fed.get("process_count", 0) >= 3, fed
        assert fed.get("scrape_samples", 0) > 0, fed
        tr = doc["trace"]
        assert tr["full_commit_chains"] >= 1, tr
        assert len(tr["processes"]) >= 2, tr
        assert doc["ok"], "soak self-check failed"
    finally:
        with open(report_path, "w") as fh:
            fh.write(json.dumps(doc, indent=2, sort_keys=True,
                                default=str) + "\n")
            fh.write(render_soak_report(doc))
    out(f"soak smoke OK: {doc['totals']['committed']} committed, "
        f"kill recovered in {doc['kills'][0]['recovery_s']}s, "
        f"{doc['trace']['full_commit_chains']} cross-process commit "
        f"chains; report -> {report_path} "
        f"trace-run-dir={doc['run_dir']}")
    return 0


def run_smoke_slo(out=print,
                  report_path: str = SLO_REPORT_PATH) -> int:
    """Longitudinal-observability cell (ISSUE 17's acceptance): the
    soak run with the metric-history plane armed and a mid-run commit
    latency breach injected.

    Asserts: TimeKeeper rows landed and the clock<->version round trip
    holds; the \\xff\\x02/metrics/ keyspace holds enough signal series
    to rebuild the throughput timeline after the horizon (restart-safe
    accounting — read back from the database, not host memory); the
    ONLINE burn-rate SLO engine tripped during the injected breach (at
    least one ok->breach transition in status.cluster.slo); and the
    incident bundle covering the breach window was written with the
    version-aligned series, status/chaos docs, and the tracemerge
    report. The run is judged on DETECTION, not on ending green: the
    p99 reservoir decays slowly after the injection lifts, so the
    final evaluated state may legitimately still show the ceiling
    rules red."""
    import json
    import os

    from .soak import render_soak_report, run_soak

    seed = int(os.environ.get("SOAK_SEED", 11))
    duration = float(os.environ.get("SOAK_DURATION", 10.0))
    doc = run_soak(processes=2, resolvers=2, duration=duration,
                   rate=400.0, kills=0, seed=seed, slo=True,
                   breach_at=duration * 0.45,
                   breach_len=duration * 0.3, out=out)
    try:
        assert not doc["errors"], doc["errors"]
        assert doc["totals"]["committed"] > 0, doc["totals"]
        assert doc["totals"]["divergent_verdicts"] == 0, doc["totals"]
        assert doc["digest"]["consistent"], doc["digest"]
        sl = doc["slo"]
        assert sl["signals"] > 0, sl
        assert sl["timekeeper_rows"] > 0, sl
        assert sl["timekeeper_ok"], sl
        assert sl["rebuilt_samples"] > 0, sl
        assert sl["online_breaches"] >= 1, sl
        assert sl["posthoc_breaches"] >= 1, sl
        b = sl.get("bundle") or {}
        assert b, sl
        for name in ("manifest.json", "series.json",
                     "timekeeper.json", "status.json"):
            assert os.path.exists(os.path.join(b["dir"], name)), b
        assert b["samples"] > 0, b
        assert doc["ok"], "slo soak self-check failed"
    finally:
        with open(report_path, "w") as fh:
            fh.write(json.dumps(doc, indent=2, sort_keys=True,
                                default=str) + "\n")
            fh.write(render_soak_report(doc))
    out(f"slo smoke OK: {doc['slo']['signals']} signals, "
        f"{doc['slo']['timekeeper_rows']} timekeeper rows, "
        f"{doc['slo']['online_breaches']} online breach(es), bundle -> "
        f"{doc['slo']['bundle']['dir']}; report -> {report_path}")
    return 0


def run_smoke_path(out=print,
                   report_path: str = PATH_REPORT_PATH) -> int:
    """Latency-forensics cell (ISSUE 18's acceptance): a CRITICAL_PATH-
    armed cluster with every tlog fsync stalled by an injected delay.

    Asserts: every commit batch was decomposed into consecutive
    pipeline stations and the per-txn segments telescope to the
    end-to-end latency within the pinned tolerance; the injected stall
    makes `tlog_fsync` the attributed dominant cause — per-commit
    counts, the decaying top-cause table, AND the queue-vs-service
    split all agree; the host ProcessMetrics sample rides the status
    doc; the fdbtpu_path_* / fdbtpu_process_* exporter families parse
    cleanly; and the `cli path` view renders. The report lands in
    /tmp/_path_report.txt for the CI artifact."""
    import json

    from .. import flow
    from ..client import run_transaction
    from ..server import SimCluster
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus

    cluster = SimCluster(seed=7, durable=True, critical_path=True)
    # the stall: 3ms added to every fsync — set AFTER construction
    # (SimCluster re-initializes the knob set)
    flow.SERVER_KNOBS.set("tlog_fsync_injection", 0.003)
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("path-smoke")

        async def workload():
            for i in range(40):
                async def w(tr, i=i):
                    tr.set(b"path/%04d" % i, b"v%d" % i)
                await run_transaction(db, w)
            # past CRITICAL_PATH_INTERVAL so the CC folds the proxies'
            # samples into the decaying cause table at least once
            await flow.delay(5.0)
            return await db.get_status()

        status = cluster.run(workload(), timeout_time=300)
        cl = status["cluster"]
        cp = cl["critical_path"]
        assert cp["enabled"] == 1, cp
        assert cp["samples"] >= 40, cp
        # the decomposition invariant: station segments sum to the
        # end-to-end latency within the pinned tolerance
        assert cp["max_residual_seconds"] <= cp["tolerance"], cp
        # the injected stall must be ATTRIBUTED: tlog_fsync dominant
        # per-commit, now, and in the decayed table
        dom_share = (cp["dominant"].get("tlog_fsync", 0)
                     / max(1, cp["samples"]))
        assert dom_share >= 0.9, cp["dominant"]
        assert cp["dominant_now"] == "tlog_fsync", cp
        assert cp["top"] and cp["top"][0]["station"] == "tlog_fsync", \
            cp["top"]
        split = cp["splits"]["tlog_fsync"]
        assert split["service"]["total"] > 0, split
        assert split["service"]["sum_seconds"] > 0, split
        pm = cl["process_metrics"]
        assert pm["enabled"] == 1, pm
        assert (pm.get("host") or {}).get("samples", 0) >= 1, pm

        text = render_prometheus(status)
        samples = parse_prometheus(text)   # raises on malformed lines
        names = {n for n, _, _ in samples}
        for need in ("fdbtpu_path_samples_total",
                     "fdbtpu_path_residual_seconds_max",
                     "fdbtpu_path_dominant_total",
                     "fdbtpu_path_station_seconds_total",
                     "fdbtpu_path_cause_score",
                     "fdbtpu_process_cpu_seconds"):
            assert need in names, f"exporter missing {need}"
        dom = {lb["station"]: v for n, lb, v in samples
               if n == "fdbtpu_path_dominant_total"}
        assert max(dom, key=dom.get) == "tlog_fsync", dom

        view = cli.execute("path")
        assert "tlog_fsync" in view, view
        rec = flow.g_flightrec.status()
        assert rec["armed"] == 1 and rec["buffered"] > 0, rec
        with open(report_path, "w") as fh:
            fh.write(json.dumps({"critical_path": cp,
                                 "process_metrics": pm,
                                 "flightrec": rec},
                                indent=2, sort_keys=True,
                                default=str) + "\n\n")
            fh.write(view + "\n")
        out(f"path smoke OK: {cp['samples']} commits decomposed, "
            f"dominant=tlog_fsync ({dom_share:.0%} of commits), "
            f"max residual {cp['max_residual_seconds']}s <= "
            f"tolerance {cp['tolerance']}s; report -> {report_path}")
        return 0
    finally:
        cluster.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--path" in argv:
        return run_smoke_path()
    if "--soak" in argv:
        return run_smoke_soak()
    if "--slo" in argv:
        return run_smoke_slo()
    if "--profile" in argv:
        return run_smoke_profile()
    if "--faults" in argv:
        return run_smoke_faults()
    if "--storm" in argv:
        return run_smoke_storm()
    if "--chaos" in argv:
        return run_smoke_chaos()
    if "--contention" in argv:
        return run_smoke_contention()
    if "--overload" in argv:
        return run_smoke_overload()
    if "--simprof" in argv:
        return run_smoke_simprof()
    if "--heat" in argv:
        return run_smoke_heat()
    if "--packed" in argv:
        return run_smoke_packed()
    if "--splits" in argv:
        return run_smoke_splits()
    return run_smoke()


if __name__ == "__main__":
    sys.exit(main())
