"""CI smoke: boot an in-process cluster, run a conflicting workload and
one latency-probe round, then assert the operator surfaces are
well-formed — `status details` (conflict hot spots + latency probe
sections), `top`, and the Prometheus exporter text.

`python -m foundationdb_tpu.tools.smoke` exits 0 on success; the
tier-1 workflow runs it after the test suite as an end-to-end guard
that the observability stack assembles outside pytest too.
`--profile` runs the transaction-profiling smoke instead: sampling at
100%, a conflicting workload, and the tools/profiler.py analyzer must
find both a committed and a conflicted transaction; the report lands
in /tmp/_profile_report.txt for the CI artifact."""

from __future__ import annotations

import sys
from typing import List, Optional

PROFILE_REPORT_PATH = "/tmp/_profile_report.txt"


def run_smoke(out=print) -> int:
    import os

    from .. import flow
    from ..client import run_transaction
    from ..server import SimCluster
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus

    cluster = SimCluster(seed=4242, durable=True)
    # resolve-pipeline depth under test (CI runs RESOLVE_PIPELINE_DEPTH=4
    # on the CPU backend); set AFTER SimCluster re-initializes the knobs
    flow.SERVER_KNOBS.set(
        "resolve_pipeline_depth",
        int(os.environ.get("RESOLVE_PIPELINE_DEPTH", 4)))
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("smoke")

        async def workload():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            for _ in range(6):
                tr = db.create_transaction()
                tr.set_option("report_conflicting_keys")
                await tr.get(b"hot")
                tr.set(b"mine", b"v")

                async def bump(t2):
                    t2.set(b"hot", b"x")
                await run_transaction(db, bump)
                try:
                    await tr.commit()
                    raise AssertionError("expected a conflict")
                except flow.FdbError as e:
                    assert e.name == "not_committed", e.name
                assert tr.get_conflicting_ranges() == \
                    ((b"hot", b"hot\x00"),), tr.get_conflicting_ranges()
            # one probe round: past LATENCY_PROBE_INTERVAL (5s) + the
            # metric sampler tick
            await flow.delay(7.0)
            return await db.get_status()

        status = cluster.run(workload(), timeout_time=300)
        cl = status["cluster"]
        assert cl["conflict_hot_spots"], "no hot spots attributed"
        assert cl["conflict_hot_spots"][0]["begin"] == b"hot".hex()
        assert cl["latency_probe"].get("rounds", 0) >= 1, \
            "latency probe never ran"

        # the resolve pipeline must be visible without a bench run:
        # every resolver submitted/drained batches through it
        res = cl.get("resolvers", ())
        assert res, "no resolvers in status"
        for r in res:
            pipe = r.get("pipeline") or {}
            assert pipe.get("submits", 0) > 0, f"pipeline idle: {pipe}"
            assert pipe.get("drains") == pipe.get("submits"), pipe
            assert pipe.get("depth", 0) >= 1, pipe

        details = cli.execute("status details")
        for section in ("Latency (seconds):", "Conflict hot spots",
                        "Latency probe:", "Resolve pipeline:",
                        b"hot".hex()):
            assert str(section) in details, f"missing {section!r}"
        top = cli.execute("top")
        assert b"hot".hex() in top

        text = render_prometheus(status)
        samples = parse_prometheus(text)   # raises on malformed lines
        kinds = {l.get("kind") for n, l, _ in samples
                 if n == "fdbtpu_role_counter"}
        missing = {"proxy", "resolver", "tlog", "storage"} - kinds
        assert not missing, f"exporter missing role kinds: {missing}"
        names = {n for n, _, _ in samples}
        for need in ("fdbtpu_conflict_hot_spot_score",
                     "fdbtpu_latency_probe_seconds",
                     "fdbtpu_request_latency_seconds_bucket",
                     "fdbtpu_resolve_pipeline_submits",
                     "fdbtpu_resolve_pipeline_depth"):
            assert need in names, f"exporter missing {need}"
        out(f"SMOKE OK: {len(samples)} exporter samples, "
            f"{len(cl['conflict_hot_spots'])} hot spots, "
            f"{cl['latency_probe']['rounds']} probe rounds, "
            f"pipeline depth {res[0]['pipeline']['depth']} "
            f"({res[0]['pipeline']['submits']} submits)")
        return 0
    finally:
        cluster.shutdown()


def run_smoke_profile(out=print,
                      report_path: str = PROFILE_REPORT_PATH) -> int:
    """The transaction-profiling end-to-end: sample EVERY transaction,
    drive a workload with a guaranteed conflict, and require the
    analyzer to read back ≥1 committed and ≥1 conflicted transaction
    from the \\xff\\x02/fdbClientInfo/ keyspace."""
    from .. import flow
    from ..client import run_transaction
    from ..client.profiling import profiler_counters
    from ..server import SimCluster
    from .cli import Cli
    from .exporter import parse_prometheus, render_prometheus
    from .profiler import format_report, profile_analysis

    cluster = SimCluster(seed=2424, durable=True, profile_janitor=True)
    flow.SERVER_KNOBS.set("profile_sample_rate", 1.0)
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("psmoke")

        async def workload():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            for i in range(4):
                async def w(tr, i=i):
                    await tr.get(b"hot")
                    tr.set(b"k%d" % i, b"v")
                await run_transaction(db, w)
            # one transaction that conflicts and is NOT retried, so a
            # "conflicted" verdict persists
            tr = db.create_transaction()
            tr.set_option("report_conflicting_keys")
            await tr.get(b"hot")
            tr.set(b"mine", b"v")

            async def bump(t2):
                t2.set(b"hot", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected a conflict")
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
            await flow.delay(2.0)   # let background flushes land
            return await profile_analysis(db)

        analysis, stats = cluster.run(workload(), timeout_time=300)
        assert analysis["records"] >= 2, analysis
        assert analysis["committed"] >= 1, analysis
        assert analysis["conflicted"] >= 1, analysis
        assert stats["skipped_missing_chunks"] == 0, stats
        assert any(r["key"] == b"hot".hex()
                   for r in analysis["hottest_keys"]), analysis

        # the cli renders the same analysis
        report = cli.execute("profile analyze")
        assert "Slowest transactions:" in report, report
        assert "conflicted" in report, report

        # sampler counters reach status + the exporter
        async def st():
            return await db.get_status()
        status = cluster.run(st(), timeout_time=60)
        counters = status["cluster"].get("client_profile", {})
        assert counters.get("transactions_sampled", 0) >= 2, counters
        names = {n for n, _, _ in
                 parse_prometheus(render_prometheus(status))}
        assert "fdbtpu_client_profile" in names, sorted(names)

        with open(report_path, "w") as f:
            f.write(format_report(analysis, stats) + "\n")
        out(f"PROFILE SMOKE OK: {analysis['records']} records "
            f"({analysis['committed']} committed, "
            f"{analysis['conflicted']} conflicted), "
            f"{profiler_counters()['chunks_written']} chunks; "
            f"report at {report_path}")
        return 0
    finally:
        flow.SERVER_KNOBS.set("profile_sample_rate", 0.0)
        cluster.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--profile" in argv:
        return run_smoke_profile()
    return run_smoke()


if __name__ == "__main__":
    sys.exit(main())
