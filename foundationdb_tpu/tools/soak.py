"""SOAK_r01: a long-horizon multi-OS-process soak with kills armed.

Reference: the reference project's nightly "soak" runs — a real
cluster held under load for hours with failures injected, watching
throughput, latency, recovery time, and end-state consistency. Here
the host process runs the full commit pipeline wall-clock behind a
peer-serving TcpGateway (the PR 15 plumbing) and `--processes` client
worker OS processes drive a seeded open-loop workload over real TCP.
At a scheduled point the harness SIGKILLs a worker and respawns it,
measuring recovery time (kill -> first committed transaction of the
replacement). Throughout, it samples committed-txn/s and latency
bands into time-series rows, fetches every worker's StatusRequest doc
mid-run for the federated status/metrics surface (ISSUE 16), and at
the end asserts ZERO divergent verdicts and a digest that is stable
across two full keyspace passes. With tracing armed (the default)
every worker writes role+pid-stamped trace files into the shared run
directory and tools/tracemerge.py must reassemble at least one full
client->proxy->resolver->tlog commit chain across the process
boundary.

With `--slo` (ISSUE 17) the cluster runs the longitudinal plane:
TimeKeeper + metric-history recorder + SLO engine armed
(METRIC_HISTORY=1), per-sample timeline rows streamed to
<run_dir>/timeline.jsonl and cumulative counts banked to banked.json
(an hours-long run's accounting survives a host crash, not just
client SIGKILLs), and the final timeline + verdict REBUILT from the
persistent \\xff\\x02/metrics/ + \\xff\\x02/timeKeeper/ keyspaces — the
run is judged by what the database recorded about itself, not by the
driver's memory. `--breach-at T` arms COMMIT_LATENCY_INJECTION for
`--breach-len` seconds mid-run: the burn-rate SLO must trip online
and an incident bundle (tools/incident.py) must cover the window.
`--hours H` is the long-horizon spelling of --duration.

CLI:
  python -m foundationdb_tpu.tools.soak [--processes N] [--duration S]
      [--hours H] [--rate R] [--resolvers N] [--kills N] [--seed S]
      [--sample-period S] [--run-dir D] [--no-trace] [--slo]
      [--breach-at T] [--breach-len S]
      [--resolver-processes N] [--tlog-processes N] [--kill-resolver N]
      [--out SOAK_r01.json] [--report SOAK_r01.md]

Role-per-process arming (ISSUE 19): `--resolver-processes N` hosts
every resolver in its own rolehost OS process (tools/rolehost.py) and
`--tlog-processes 1` does the same for the tlog — the host's proxies
fan out resolve/commit over real TCP with per-request retry, and the
role processes' proc stubs join the same federation fetch.
`--kill-resolver K` SIGKILLs a live resolver process K times at evenly
spaced points and respawns it on its pinned port: recovery (checkpoint
restore + deterministic journal replay, kill -> first cluster-wide
committed advance) must land inside CHAOS_RECOVERY_BOUND.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional

from .. import flow
from ..flow import rng as _rng
from ..flow.future import Promise
from .clusterbench import (RoleProcs, _drive_commits, _lat_ms,
                           worker_trace_setup, write_proc_file)

OUT_PATH = "SOAK_r01.json"
REPORT_PATH = "SOAK_r01.md"
COUNT_KEYS = ("offered", "shed", "committed", "conflicted", "too_old",
              "errors")


# ------------------------------------------------------------- worker
def run_soak_worker(cfg: dict) -> dict:
    """Client-worker entry (one OS process): fetch the CLIENT describe
    document from the gateway and drive a share of the open-loop
    workload against the HOST's proxies over real TCP — so every
    sampled commit's span tree crosses the process boundary at the
    client->proxy hop. Emits a cumulative-count JSON sample line every
    `sample_period` seconds (cumulative so the driver's accounting
    survives a SIGKILL mid-run) and a final line when the horizon
    ends."""
    prev_sched = flow.get_scheduler()
    prev_rng = _rng.rng_state()
    transport = None
    try:
        from ..rpc.gateway import DESCRIBE_TOKEN
        from ..rpc.tcp import TcpRequestStream, TcpTransport
        from ..server.process_metrics import ProcessMetrics, \
            loop_lag_probe
        flow.set_seed(int(cfg["seed"]))
        s = flow.Scheduler(virtual=False)
        flow.set_scheduler(s)
        idx = int(cfg["index"])
        gen = int(cfg.get("generation", 0))
        role = f"client-{idx}"
        pid = os.getpid()
        # worker_trace_setup also arms the flight recorder against the
        # shared run dir (auto-dump on SevError)
        worker_trace_setup(role, cfg)
        metrics = ProcessMetrics(role=role)
        transport = TcpTransport()
        status_stream = TcpRequestStream(transport)
        if cfg.get("run_dir"):
            write_proc_file(cfg["run_dir"], role, transport.port,
                            status_stream.token)
        host, port = cfg["host"], int(cfg["port"])
        live: dict = {}
        started = time.perf_counter()

        def worker_status() -> dict:
            counts = live.get("counts") or {}
            return {
                "process": f"{role}:{pid}", "role": role, "pid": pid,
                "generation": gen,
                "uptime_s": round(time.perf_counter() - started, 3),
                "counters": dict(counts),
                "grv": _lat_ms(list(live.get("grv_lat") or [])),
                "commit": _lat_ms(list(live.get("commit_lat") or [])),
                "process_metrics": metrics.sample(),
                "flightrec": flow.g_flightrec.status(),
            }

        async def status_loop():
            while True:
                _req, reply = await status_stream.pop()
                reply.send(worker_status())

        async def pipe(fut, promise: Promise) -> None:
            try:
                promise.send(await fut)
            except flow.FdbError as e:
                promise.send_error(e)

        async def sampler():
            period = float(cfg.get("sample_period", 1.0))
            gi = ci = 0
            while True:
                await flow.delay(period)
                counts = dict(live.get("counts") or {})
                grv_lat = live.get("grv_lat") or []
                commit_lat = live.get("commit_lat") or []
                row = {"type": "sample", "index": idx, "pid": pid,
                       "generation": gen,
                       "t": round(time.perf_counter() - started, 3)}
                for k in COUNT_KEYS:
                    row[k] = counts.get(k, 0)
                # latency over the window since the LAST sample — a
                # time series of bands, not one run-wide smear
                if len(grv_lat) > gi:
                    row["grv"] = _lat_ms(list(grv_lat[gi:]))
                if len(commit_lat) > ci:
                    row["commit"] = _lat_ms(list(commit_lat[ci:]))
                gi, ci = len(grv_lat), len(commit_lat)
                row["proc"] = metrics.sample()
                print(json.dumps(row), flush=True)

        async def main():
            transport.start()
            flow.spawn(status_loop())
            flow.spawn(loop_lag_probe(metrics))
            describe = transport.ref(host, port, DESCRIBE_TOKEN)
            doc = None
            for _ in range(50):
                try:
                    doc = await flow.timeout_error(
                        describe.get_reply(-1), 5.0)
                    if doc.get("proxies"):
                        break
                    doc = None
                except flow.FdbError:
                    pass
                await flow.delay(0.2)
            if doc is None:
                raise RuntimeError("client describe never became ready")
            grv_refs = [transport.ref(host, port, p["grvs"])
                        for p in doc["proxies"]]
            commit_refs = [transport.ref(host, port, p["commits"])
                           for p in doc["proxies"]]

            def grv_send(req, reply):
                flow.spawn(pipe(grv_refs[0].get_reply(req), reply))

            def commit_send(i, req, reply):
                ref = commit_refs[i % len(commit_refs)]
                # get_reply is called HERE, synchronously, while the
                # NativeAPI.commit span _drive_commits opened is still
                # the top of this debug id's stack — the transport
                # captures it as the cross-process parent
                flow.spawn(pipe(ref.get_reply(req), reply))

            # priming commit: a blind write (no read ranges — immune
            # to the MVCC too_old window) advances the cluster's
            # committed version past the idle gap this process's own
            # startup opened, so the measured workload's first GRVs
            # land inside max_write_transaction_life_versions
            from ..server.types import (CommitRequest,
                                        GetReadVersionRequest,
                                        MutationRef, SET_VALUE)
            pk = b"\x00soak-prime/%d" % idx
            reply = Promise()
            grv_send(GetReadVersionRequest(), reply)
            ver0 = (await reply.future).version
            reply = Promise()
            commit_send(0, CommitRequest(
                ver0, (), ((pk, pk + b"\x00"),),
                (MutationRef(SET_VALUE, pk, b"p"),)), reply)
            await reply.future

            flow.spawn(sampler())
            counts = await _drive_commits(
                grv_send, commit_send, seed=int(cfg["seed"]),
                duration=float(cfg["duration"]),
                rate=float(cfg["rate"]),
                key_prefix=b"soak/%d/%d/" % (idx, gen),
                clock=time.perf_counter,
                sample_every=int(cfg.get("sample_every", 0)),
                debug_prefix=f"soak{idx}g{gen}-", live=live)
            counts["type"] = "final"
            counts["index"] = idx
            counts["pid"] = pid
            counts["generation"] = gen
            return counts

        t = s.spawn(main())
        result = s.run(until=t, timeout_time=float(cfg["duration"]) + 90)
        print(json.dumps(result), flush=True)
        return result
    finally:
        if transport is not None:
            transport.close()
        try:
            flow.g_trace_batch.dump()
            flow.g_trace.flush()
        except Exception:  # noqa: BLE001 — exiting anyway
            pass
        flow.g_flightrec.disarm()
        flow.set_scheduler(prev_sched)
        _rng.restore_rng_state(prev_rng)


# ------------------------------------------------------------- driver
class _Slot:
    """One worker seat: the live Popen, its reader thread, the latest
    cumulative sample, and the counts already banked from previous
    (killed or finished) generations."""

    def __init__(self, index: int):
        self.index = index
        self.generation = -1
        self.proc: Optional[subprocess.Popen] = None
        self.pid = 0
        self.last: Optional[dict] = None       # latest sample row
        self.final: Optional[dict] = None      # final row, if any
        self.banked = {k: 0 for k in COUNT_KEYS}
        self.kill_time: Optional[float] = None  # awaiting recovery

    def live_counts(self) -> dict:
        row = self.final or self.last or {}
        return {k: self.banked[k] + row.get(k, 0) for k in COUNT_KEYS}


def run_soak(*, processes: int = 2, resolvers: int = 2,
             duration: float = 20.0, hours: float = None,
             rate: float = 600.0,
             kills: int = 1, seed: int = 0, sample_period: float = 1.0,
             sample_every: int = 32, trace: bool = True,
             run_dir: str = None, slo: bool = False,
             breach_at: float = None, breach_len: float = 4.0,
             breach_delay: float = 0.4,
             resolver_processes: int = 0, tlog_processes: int = 0,
             kill_resolver: int = 0, out=print) -> dict:
    """The soak: host cluster + gateway in this process, `processes`
    client workers as real OS processes, `kills` SIGKILL+respawn
    rounds at evenly spaced points of the horizon. Returns the
    SOAK_r01 document (see module docstring for what it asserts).

    Role-per-process arming (ISSUE 19): `resolver_processes` > 0 puts
    every resolver in its own rolehost OS process (and overrides
    `resolvers`), `tlog_processes` > 0 does the same for the tlog.
    `kill_resolver` rounds SIGKILL a LIVE resolver process at evenly
    spaced points and respawn it on its pinned port: the commit
    pipeline must resume — checkpoint restore + deterministic journal
    replay — within CHAOS_RECOVERY_BOUND wall seconds, measured as
    kill -> first cluster-wide committed-count advance."""
    if processes < 1:
        raise ValueError("soak needs at least one worker process")
    if hours is not None:
        duration = hours * 3600.0
    if breach_at is not None and not slo:
        raise ValueError("--breach-at needs --slo (nothing would "
                         "detect the breach)")
    if resolver_processes:
        resolvers = resolver_processes
    if kill_resolver and not resolver_processes:
        raise ValueError("--kill-resolver needs --resolver-processes "
                         "(in-host resolvers have no pid to SIGKILL)")
    prev_sched = flow.get_scheduler()
    prev_rng = _rng.rng_state()
    prev_trace_path = flow.g_trace.path
    cluster = gw = fed_transport = timeline_fh = None
    roles = ext = None
    if run_dir is None:
        import tempfile
        run_dir = tempfile.mkdtemp(prefix="fdbtpu-soak-")
    else:
        os.makedirs(run_dir, exist_ok=True)
    lock = threading.Lock()
    slots = [_Slot(i) for i in range(processes)]
    kill_rows: List[dict] = []
    resolver_kill_rows: List[dict] = []
    errors: List[str] = []
    t_start = [0.0]
    try:
        from ..rpc.gateway import TcpGateway
        from ..rpc.tcp import TcpTransport
        from ..server import SimCluster
        from ..server import dbinfo as dbi
        from ..server.chaos import database_digest
        from ..server.process_metrics import ProcessMetrics, \
            loop_lag_probe
        from ..server.types import STATUS_REQUEST
        from . import exporter, tracemerge
        if trace:
            flow.reset_trace(os.path.join(
                run_dir, f"trace.cluster-host.{os.getpid()}.jsonl"))
            flow.trace.set_process_identity("cluster-host")
        if resolver_processes or tlog_processes:
            # role hosts first: the master's recruitment phase needs
            # their control endpoints live before the first epoch
            roles = RoleProcs(
                n_resolvers=resolver_processes,
                n_tlogs=1 if tlog_processes else 0,
                run_dir=run_dir,
                state_root=os.path.join(run_dir, "state"),
                seed=seed, trace=trace)
            roles.spawn_all().wait_ready()
        cluster = SimCluster(seed=seed, virtual=False, n_proxies=1,
                             n_resolvers=resolvers, n_storage=1,
                             n_logs=1, metric_history=slo,
                             metrics_janitor=slo)
        if roles is not None:
            ext = roles.external_roles()
            cluster.cc.external_roles = ext
        if trace:
            # AFTER construction — SimCluster re-seeds the knob set
            flow.SERVER_KNOBS.set("trace_propagation", 1)
        if slo:
            # scale the longitudinal plane to the horizon (also AFTER
            # construction): small chunks + burn windows that fit a
            # smoke-length run, their defaults for long runs. Both
            # retentions must out-live the run — the end-of-run
            # read-back and the breach-window version alignment need
            # the WHOLE timeline still in the keyspace (the janitor's
            # trim math is unit-tested; here it must not eat evidence)
            flow.SERVER_KNOBS.set("metric_history_chunk",
                                  4 if duration < 60 else 8)
            fast = max(2.0, min(10.0, duration * 0.2))
            flow.SERVER_KNOBS.set("slo_burn_fast_window", fast)
            flow.SERVER_KNOBS.set("slo_burn_slow_window",
                                  max(2 * fast, min(60.0,
                                                    duration * 0.5)))
            flow.SERVER_KNOBS.set("slo_eval_interval", 0.5)
            flow.SERVER_KNOBS.set("metric_retention_seconds",
                                  duration * 2 + 600.0)
            flow.SERVER_KNOBS.set("timekeeper_retention",
                                  duration * 2 + 600.0)
        # host-side telemetry + flight recorder (ISSUE 18) — armed
        # AFTER construction (SimCluster disarms the process-global
        # recorder to keep pinned sims clean)
        host_metrics = ProcessMetrics(role="cluster-host")
        flow.g_flightrec.arm(dump_dir=run_dir,
                             name=f"cluster-host.{os.getpid()}")
        db = cluster.client("soak-status")
        gw = TcpGateway(cluster.client("soakgw"), cluster=cluster)

        def reader(slot: _Slot, p: subprocess.Popen) -> None:
            for line in p.stdout:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                with lock:
                    if row.get("pid") != slot.pid:
                        continue   # a straggler line from an old gen
                    if row.get("type") == "sample":
                        slot.last = row
                        if slot.kill_time is not None and \
                                row.get("committed", 0) > 0:
                            kill_rows[-1]["recovery_s"] = round(
                                time.perf_counter() - slot.kill_time,
                                3)
                            kill_rows[-1]["recovered_pid"] = slot.pid
                            slot.kill_time = None
                    elif row.get("type") == "final":
                        slot.final = row

        def spawn_worker(slot: _Slot, remaining: float) -> None:
            with lock:
                slot.generation += 1
                slot.last = slot.final = None
                cfg = {"host": "127.0.0.1", "port": gw.port,
                       "seed": seed + 1000 * (slot.index + 1)
                       + 71 * slot.generation,
                       "index": slot.index,
                       "generation": slot.generation,
                       "duration": round(remaining, 3),
                       "rate": rate / processes,
                       "run_dir": run_dir,
                       "trace": int(bool(trace)),
                       # the HOST's roll size governs worker trace
                       # files too: an hours-long worker rotates into
                       # .N segments tracemerge reads back in order
                       "trace_roll_size":
                           int(flow.SERVER_KNOBS.trace_roll_size),
                       "sample_every": sample_every if trace else 0,
                       "sample_period": sample_period}
            err_path = os.path.join(
                run_dir, f"worker-{slot.index}.{slot.generation}.stderr")
            p = subprocess.Popen(
                [sys.executable, "-m", "foundationdb_tpu.tools.soak",
                 "--worker", json.dumps(cfg)],
                stdout=subprocess.PIPE,
                stderr=open(err_path, "w"),
                text=True, bufsize=1)
            with lock:
                slot.proc = p
                slot.pid = p.pid
            threading.Thread(target=reader, args=(slot, p),
                             daemon=True).start()

        def kill_worker(slot: _Slot) -> None:
            with lock:
                p, pid, gen = slot.proc, slot.pid, slot.generation
                row = slot.last or {}
                for k in COUNT_KEYS:
                    slot.banked[k] += row.get(k, 0)
                slot.last = slot.final = None
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait(timeout=30)
            with lock:
                slot.kill_time = time.perf_counter()
                kill_rows.append({
                    "t": round(time.perf_counter() - t_start[0], 3),
                    "slot": slot.index, "killed_pid": pid,
                    "killed_generation": gen,
                    "committed_before_kill": row.get("committed", 0)})

        # long-horizon accounting (ISSUE 17 satellite): per-sample rows
        # STREAM to disk as JSON lines and only a bounded tail stays in
        # memory for the report; cumulative totals + kill rows bank to
        # banked.json every tick so a host crash loses at most one
        # sample period of accounting
        timeline: List[dict] = []
        timeline_tail = 720
        timeline_rows = [0]
        timeline_path = os.path.join(run_dir, "timeline.jsonl")
        timeline_fh = open(timeline_path, "a", buffering=1)
        federation: dict = {}
        breach = {"t0": None, "t1": None}

        def note_sample(trow: dict) -> None:
            timeline_fh.write(json.dumps(trow) + "\n")
            timeline_rows[0] += 1
            timeline.append(trow)
            if len(timeline) > timeline_tail:
                del timeline[: len(timeline) - timeline_tail]

        def bank_totals(totals: dict) -> None:
            tmp = os.path.join(run_dir, ".banked.json.tmp")
            with open(tmp, "w") as fh:
                json.dump({"totals": totals, "kills": kill_rows,
                           "samples": timeline_rows[0]}, fh)
            os.replace(tmp, os.path.join(run_dir, "banked.json"))

        async def fetch_federation() -> None:
            """Mid-run: every worker's StatusRequest doc over the
            host's own client TCP transport, folded with the CC
            status into one federated doc + one Prometheus scrape."""
            stubs = exporter.read_proc_files(run_dir)
            procs: List[dict] = []
            for stub in stubs:
                ref = fed_transport.ref(stub.get("host", "127.0.0.1"),
                                        int(stub["port"]),
                                        int(stub["status_token"]))
                try:
                    doc = await flow.timeout_error(
                        ref.get_reply(STATUS_REQUEST), 5.0)
                    doc = dict(doc)
                    doc.setdefault("process", stub.get("name", "?"))
                    doc["up"] = 1
                except flow.FdbError:
                    doc = {"process": stub.get("name", "?"),
                           "role": stub.get("role", "?"),
                           "pid": stub.get("pid"), "up": 0}
                procs.append(doc)
            host_status = await db.get_status()
            # the host's own resource sample rides the cluster doc —
            # when the sim's CRITICAL_PATH plane is off (the soak's
            # default), inject it so the federated scrape still covers
            # EVERY OS process with fdbtpu_process_* samples
            cl_doc = host_status.setdefault("cluster", {})
            if not (cl_doc.get("process_metrics") or {}).get("enabled"):
                cl_doc["process_metrics"] = {
                    "enabled": 1, "interval": sample_period,
                    "host": host_metrics.sample(),
                    "role_cpu_share": {}}
            fed_doc = exporter.federate_status(
                host_status, procs,
                host_process=f"cluster-host:{os.getpid()}")
            scrape = exporter.render_federated(
                host_status, procs,
                host_process=f"cluster-host:{os.getpid()}")
            samples = exporter.parse_prometheus(scrape)  # well-formed?
            federation["processes"] = sorted(
                fed_doc["cluster"]["processes"])
            federation["process_count"] = \
                fed_doc["cluster"]["federation"]["process_count"]
            federation["up"] = sum(
                1 for p in procs if p.get("up"))
            federation["scrape_samples"] = len(samples)
            federation["process_metric_pids"] = sorted(
                {lb.get("pid") for name, lb, _v in samples
                 if name == "fdbtpu_process_cpu_seconds"})
            federation["role_cpu_share"] = \
                fed_doc["cluster"]["federation"].get(
                    "role_cpu_share") or {}

        async def slo_read_back(run_t0_clock: float) -> dict:
            """ISSUE 17 acceptance: the timeline and the final verdict
            must be reconstructable from the PERSISTENT plane alone —
            the \\xff\\x02/metrics/ series plus the TimeKeeper map, not
            host memory — so a restarted observer reaches the same
            conclusion the live SLO engine did."""
            from ..layers import metrics as metrics_layer
            from ..server import slo as slo_mod
            from ..server import timekeeper
            from . import incident
            status = await db.get_status()
            slo_status = (status.get("cluster") or {}).get("slo") or {}
            signals = await metrics_layer.list_history_signals(db)
            series = {}
            for sig in signals:
                series[sig] = await metrics_layer.read_history(db, sig)
            # the rebuilt timeline: throughput from the keyspace series
            rebuilt = []
            prev = None
            for ts_ms, committed in series.get("cluster/txn_committed",
                                               []):
                row = {"t": round(ts_ms / 1000.0 - run_t0_clock, 3),
                       "committed": committed}
                if prev is not None and ts_ms > prev[0]:
                    row["txn_per_s"] = round(
                        (committed - prev[1]) * 1000.0
                        / (ts_ms - prev[0]), 1)
                rebuilt.append(row)
                prev = (ts_ms, committed)
            rules = slo_mod.default_rules()
            sample_ts = sorted({ts for s in series.values()
                                for ts, _ in s})
            final_verdict = (slo_mod.evaluate(rules, series,
                                              sample_ts[-1])
                             if sample_ts else {"state": "no-data",
                                                "breached": []})
            # post-hoc sweep: replay the rules over the persisted
            # series (strided so an hours-long run stays O(samples))
            posthoc_breaches = 0
            prev_state = "ok"
            for ts in sample_ts[::max(1, len(sample_ts) // 600)]:
                v = slo_mod.evaluate(rules, series, ts)
                if v["state"] == "breach" and prev_state == "ok":
                    posthoc_breaches += 1
                prev_state = v["state"]
            # TimeKeeper sanity: clock -> version -> clock round trip
            tk_map = await timekeeper.read_time_map(db)
            tk_ok = len(tk_map) > 0
            if sample_ts and tk_map:
                mid = sample_ts[len(sample_ts) // 2] / 1000.0
                v_mid = timekeeper.version_at_time_from_map(tk_map, mid)
                t_back = timekeeper.time_at_version_from_map(tk_map,
                                                             v_mid)
                tk_ok = v_mid > 0 and abs(t_back - mid) < 5.0
            sdoc = {
                "signals": len(signals),
                "series_samples": sum(len(s) for s in series.values()),
                "timekeeper_rows": len(tk_map),
                "timekeeper_ok": tk_ok,
                "rebuilt_samples": len(rebuilt),
                "rebuilt_tail": rebuilt[-5:],
                "timeline_source": "metric-history keyspace",
                "final_state": final_verdict.get("state"),
                "final_breached": final_verdict.get("breached", []),
                "posthoc_breaches": posthoc_breaches,
                "online_state": slo_status.get("state"),
                "online_breaches": slo_status.get("breaches", 0),
                "breach_window": dict(breach),
            }
            if breach["t0"] is not None or \
                    final_verdict.get("state") == "breach":
                # red run (or breach drill): snapshot the window
                if trace:
                    flow.g_trace_batch.dump()
                    flow.g_trace.flush()
                w0 = (breach["t0"] if breach["t0"] is not None
                      else (sample_ts[0] / 1000.0 if sample_ts
                            else run_t0_clock))
                w1 = (breach["t1"] if breach["t1"] is not None
                      else flow.now())
                bundle_dir = os.path.join(run_dir, "incident")
                manifest = await incident.capture_bundle(
                    db, bundle_dir, (w0, w1),
                    run_dir=run_dir if trace else None,
                    status_doc=status, verdict=final_verdict,
                    reason=("breach_drill" if breach["t0"] is not None
                            else "slo_breach"))
                sdoc["bundle"] = {
                    "dir": bundle_dir,
                    "samples": manifest.get("samples", 0),
                    "signals": len(manifest.get("signals", [])),
                    "contents": manifest.get("contents", [])}
            return sdoc

        async def main():
            gw.start()
            while cluster.cc.dbinfo.get().recovery_state != \
                    dbi.FULLY_RECOVERED:
                await flow.delay(0.05)
            fed_transport.start()
            t0 = time.perf_counter()
            t_start[0] = t0
            run_t0_clock = flow.now()
            for slot in slots:
                spawn_worker(slot, duration)
            kill_at = [t0 + duration * (k + 1) / (kills + 1)
                       for k in range(kills)]
            rkill_at = [t0 + duration * (k + 1) / (kill_resolver + 1)
                        for k in range(kill_resolver)]

            async def rewait_resolver(i: int, row: dict) -> None:
                # scheduler-friendly: the host keeps serving while the
                # replacement boots, recovers, and re-writes ready
                await roles.wait_ready_async(
                    [("resolver", i)],
                    timeout=float(
                        flow.SERVER_KNOBS.chaos_recovery_bound))
                rdoc = roles.ready[("resolver", i)]
                row["respawned_pid"] = rdoc["pid"]
                row["respawn_recovered_state"] = bool(
                    rdoc.get("recovered"))
            fed_at = t0 + duration * 0.75
            fed_done = False
            next_sample = t0 + sample_period
            prev_committed = 0
            prev_t = t0
            breach_on_at = (t0 + breach_at if breach_at is not None
                            else None)
            breach_off_at = None
            while time.perf_counter() < t0 + duration:
                await flow.delay(0.1)
                wall = time.perf_counter()
                if breach_on_at is not None and wall >= breach_on_at:
                    # the drill: every commit batch slowed past the
                    # latency-band edge until breach_len elapses — the
                    # ONLINE SLO engine must notice within its fast
                    # window (asserted below from the status doc)
                    breach_on_at = None
                    breach_off_at = wall + breach_len
                    breach["t0"] = flow.now()
                    flow.SERVER_KNOBS.set("commit_latency_injection",
                                          breach_delay)
                if breach_off_at is not None and wall >= breach_off_at:
                    breach_off_at = None
                    breach["t1"] = flow.now()
                    flow.SERVER_KNOBS.set("commit_latency_injection",
                                          0.0)
                while kill_at and wall >= kill_at[0]:
                    kill_at.pop(0)
                    victim = slots[len(kill_rows) % processes]
                    kill_worker(victim)
                    spawn_worker(victim,
                                 t0 + duration - time.perf_counter())
                while rkill_at and wall >= rkill_at[0]:
                    # resolver chaos (ISSUE 19): SIGKILL a LIVE
                    # resolver role process mid-load, respawn on its
                    # pinned port; recovery is judged by the whole
                    # pipeline committing again (every commit fans out
                    # to every resolver, so a dead one stalls all)
                    rkill_at.pop(0)
                    ri = len(resolver_kill_rows) % roles.n_resolvers
                    with lock:
                        before = 0
                        for slot in slots:
                            before += slot.live_counts()["committed"]
                    dead = roles.kill("resolver", ri)
                    rrow = {"t": round(wall - t0, 3), "resolver": ri,
                            "name": roles.name("resolver", ri),
                            "killed_pid": dead,
                            "committed_before_kill": before,
                            "wall_at_kill": wall}
                    resolver_kill_rows.append(rrow)
                    roles.respawn("resolver", ri)
                    flow.spawn(rewait_resolver(ri, rrow))
                if not fed_done and wall >= fed_at:
                    fed_done = True
                    try:
                        await fetch_federation()
                    except Exception as e:  # noqa: BLE001 — recorded
                        errors.append(f"federation: {e!r}")
                if wall >= next_sample:
                    next_sample += sample_period
                    with lock:
                        totals = {k: 0 for k in COUNT_KEYS}
                        lat = {}
                        up = 0
                        procs_row = {}
                        for slot in slots:
                            for k, v in slot.live_counts().items():
                                totals[k] += v
                            row = slot.last or {}
                            if slot.proc is not None and \
                                    slot.proc.poll() is None:
                                up += 1
                            prow = row.get("proc") or {}
                            if prow:
                                procs_row[f"client-{slot.index}"] = {
                                    k: prow.get(k) for k in
                                    ("cpu_seconds", "rss_bytes",
                                     "open_fds", "loop_lag_ms")}
                            for req in ("grv", "commit"):
                                for q, v in (row.get(req)
                                             or {}).items():
                                    key = f"{req}_{q}"
                                    lat[key] = max(lat.get(key, 0.0),
                                                   v)
                    hrow = host_metrics.sample()
                    procs_row["cluster-host"] = {
                        k: hrow.get(k) for k in
                        ("cpu_seconds", "rss_bytes", "open_fds",
                         "loop_lag_ms")}
                    trow = {"t": round(wall - t0, 3),
                            "committed": totals["committed"],
                            "txn_per_s": round(
                                (totals["committed"] - prev_committed)
                                / max(1e-9, wall - prev_t), 1),
                            "divergent": totals["conflicted"]
                            + totals["too_old"] + totals["errors"],
                            "workers_up": up}
                    trow.update({k: round(v, 3)
                                 for k, v in sorted(lat.items())})
                    trow["proc"] = procs_row
                    # resolver-kill recovery: the pipeline is
                    # recovered once the CLUSTER-WIDE committed count
                    # moves past its pre-kill snapshot (every commit
                    # fans out to every resolver, so a dead one stalls
                    # all workers, not a share)
                    for rrow in resolver_kill_rows:
                        if "recovery_s" not in rrow and \
                                totals["committed"] > \
                                rrow["committed_before_kill"]:
                            rrow["recovery_s"] = round(
                                wall - rrow.pop("wall_at_kill"), 3)
                    note_sample(trow)
                    bank_totals(totals)
                    prev_committed = totals["committed"]
                    prev_t = wall
            # horizon over: let the workers publish their final rows
            grace = time.perf_counter() + 30
            while time.perf_counter() < grace:
                with lock:
                    if all(s.final is not None or s.proc is None
                           or s.proc.poll() is not None
                           for s in slots):
                        break
                await flow.delay(0.2)
            if not fed_done:
                try:
                    await fetch_federation()
                except Exception as e:  # noqa: BLE001 — recorded
                    errors.append(f"federation: {e!r}")
            # end-state consistency: two full keyspace passes must
            # hash identically (quiesced database, stable digest)
            d1 = await database_digest(db)
            d2 = await database_digest(db)
            sdoc = None
            if slo:
                try:
                    sdoc = await slo_read_back(run_t0_clock)
                except Exception as e:  # noqa: BLE001 — recorded
                    errors.append(f"slo_read_back: {e!r}")
            return d1, d2, round(time.perf_counter() - t0, 3), sdoc

        fed_transport = TcpTransport()
        d1, d2, wall, slo_doc = cluster.run(main(),
                                            timeout_time=duration + 300)
        for slot in slots:
            if slot.proc is not None and slot.proc.poll() is None:
                slot.proc.send_signal(signal.SIGKILL)
                slot.proc.wait(timeout=30)
        with lock:
            totals = {k: 0 for k in COUNT_KEYS}
            for slot in slots:
                for k, v in slot.live_counts().items():
                    totals[k] += v
            finals = [s.final for s in slots if s.final is not None]
        totals["divergent_verdicts"] = (totals["conflicted"]
                                        + totals["too_old"]
                                        + totals["errors"])
        doc = {
            "metric": "soak_multi_process",
            "config": {"processes": processes, "resolvers": resolvers,
                       "duration_wall_s": duration, "offered_rate": rate,
                       "kills": kills, "seed": seed,
                       "sample_period_s": sample_period,
                       "sample_every": sample_every,
                       "trace": bool(trace), "slo": bool(slo),
                       "hours": hours, "breach_at": breach_at,
                       "breach_len": breach_len,
                       "resolver_processes": resolver_processes,
                       "tlog_processes": tlog_processes,
                       "kill_resolver": kill_resolver},
            "run_dir": run_dir,
            "wall_seconds": wall,
            "timeline": timeline,
            "timeline_path": timeline_path,
            "timeline_rows": timeline_rows[0],
            "kills": kill_rows,
            "totals": totals,
            "txn_per_s": round(totals["committed"] / max(1e-9, wall), 1),
            "latency_ms": {
                "grv": finals[0].get("grv", {}) if finals else {},
                "commit": finals[0].get("commit", {}) if finals else {},
            },
            "digest": {"first": d1, "second": d2,
                       "consistent": d1 == d2},
            "federation": federation,
            "errors": errors,
        }
        if roles is not None:
            doc["resolver_kills"] = resolver_kill_rows
            try:
                role_docs = exporter.fetch_process_docs(
                    run_dir, stubs=roles.status_stubs())
            except Exception as e:  # noqa: BLE001 — recorded
                role_docs = []
                errors.append(f"role_status: {e!r}")
            doc["role_processes"] = {
                "resolvers": roles.n_resolvers,
                "tlogs": roles.n_tlogs,
                "kills": roles.kills,
                "status": [
                    {k: d.get(k) for k in
                     ("process", "role", "name", "pid", "up",
                      "uptime_s", "counters", "version",
                      "process_metrics") if k in d}
                    for d in role_docs],
            }
        if trace:
            # the cross-process proof: merge the run dir and demand at
            # least one complete client->proxy->resolver->tlog chain
            flow.g_trace_batch.dump()
            flow.g_trace.flush()
            if roles is not None:
                # role processes are still serving: their resolver/tlog
                # span legs sit in per-process TraceBatch buffers until
                # asked — flush before reading the run dir or the merge
                # sees client+host legs only
                from . import rolehost
                try:
                    rolehost.flush_role_traces(
                        list(roles.ready.values()))
                except Exception as e:  # noqa: BLE001 — recorded
                    errors.append(f"trace_flush: {e!r}")
            merged = tracemerge.merge(run_dir)
            full = tracemerge.full_commit_chains(merged)
            doc["trace"] = {
                "run_dir": run_dir,
                "processes": merged["processes"],
                "chains": len(merged["chains"]),
                "cross_process_chains": len(
                    tracemerge.cross_process_chains(merged)),
                "full_commit_chains": len(full),
                "clock_offsets_s": merged["clock_offsets_s"],
            }
        if slo_doc is not None:
            doc["slo"] = slo_doc
        ok = (not errors
              and totals["divergent_verdicts"] == 0
              and totals["committed"] > 0
              and doc["digest"]["consistent"]
              and all("recovery_s" in k for k in kill_rows)
              and (not trace
                   or doc["trace"]["full_commit_chains"] >= 1))
        if kill_resolver:
            # every resolver SIGKILL must have recovered — checkpoint
            # restore + deterministic journal replay — within the same
            # bound the in-sim chaos workloads are held to
            bound = float(flow.SERVER_KNOBS.chaos_recovery_bound)
            ok = (ok and len(resolver_kill_rows) == kill_resolver
                  and all("recovery_s" in r
                          for r in resolver_kill_rows)
                  and max(r["recovery_s"]
                          for r in resolver_kill_rows) <= bound)
        if slo:
            # the self-watching contract: the persistent plane must
            # hold a readable timeline, a sane TimeKeeper map, and —
            # when the drill armed — the online engine must have
            # tripped AND the incident bundle must exist. A drill run
            # is judged on detection, not on ending green (the p99
            # reservoir decays slowly after the injection lifts).
            ok = (ok and slo_doc is not None
                  and slo_doc["signals"] > 0
                  and slo_doc["timekeeper_rows"] > 0
                  and slo_doc["timekeeper_ok"]
                  and slo_doc["rebuilt_samples"] > 0)
            if ok and breach_at is not None:
                ok = (slo_doc["online_breaches"] >= 1
                      and "bundle" in slo_doc)
            elif ok:
                ok = slo_doc["final_state"] == "ok"
        doc["ok"] = ok
        if not ok:
            # red run: the host's flight-recorder ring joins the run
            # dir (the workers' rings already auto-dump on SevError) —
            # nightly CI uploads the whole directory on failure
            dump_path = flow.g_flightrec.dump(directory=run_dir,
                                              reason="soak_red")
            if dump_path:
                doc["flightrec_dump"] = dump_path
        slo_note = ""
        if slo_doc is not None:
            slo_note = (f"slo={slo_doc['final_state']} "
                        f"online_breaches={slo_doc['online_breaches']} "
                        f"signals={slo_doc['signals']} ")
        out(f"  soak {processes}p x {duration}s: "
            f"{doc['txn_per_s']}/s committed={totals['committed']} "
            f"divergent={totals['divergent_verdicts']} "
            f"kills={len(kill_rows)} "
            f"resolver_kills={len(resolver_kill_rows)} "
            f"digest_consistent={doc['digest']['consistent']} "
            f"{slo_note}ok={ok} trace-run-dir={run_dir}")
        return doc
    finally:
        for slot in slots:
            if slot.proc is not None and slot.proc.poll() is None:
                slot.proc.send_signal(signal.SIGKILL)
        if timeline_fh is not None:
            timeline_fh.close()
        if fed_transport is not None:
            fed_transport.close()
        if gw is not None:
            gw.close()
        if ext is not None:
            ext.close()
        if cluster is not None:
            cluster.shutdown()
        if roles is not None:
            roles.terminate_all()
        if trace:
            flow.reset_trace(prev_trace_path)
            flow.trace.clear_process_identity()
            flow.SERVER_KNOBS.set("trace_propagation", 0)
        if slo:
            flow.SERVER_KNOBS.set("commit_latency_injection", 0.0)
            flow.SERVER_KNOBS.set("metric_history", 0)
        flow.g_flightrec.disarm()
        flow.set_scheduler(prev_sched)
        _rng.restore_rng_state(prev_rng)


def render_soak_report(doc: dict) -> str:
    """SOAK_rNN.md: the document as a human report."""
    cfg = doc["config"]
    role_proc = cfg.get("resolver_processes", 0)
    name = "SOAK_r02" if role_proc else "SOAK_r01"
    kind = ("role-per-process soak" if role_proc
            else "multi-process soak")
    lines = [
        f"# {name} — {kind}",
        "",
        f"- processes: {cfg['processes']} client workers + 1 cluster "
        f"host, resolvers={cfg['resolvers']}, seed={cfg['seed']}",
        f"- horizon: {cfg['duration_wall_s']}s wall at "
        f"{cfg['offered_rate']} offered txn/s, kills armed: "
        f"{cfg['kills']}",
        f"- committed: {doc['totals']['committed']} "
        f"({doc['txn_per_s']}/s), divergent verdicts: "
        f"{doc['totals']['divergent_verdicts']}",
        f"- digest: consistent={doc['digest']['consistent']} "
        f"({doc['digest']['first'][:16]}...)",
        f"- verdict: {'PASS' if doc.get('ok') else 'FAIL'}",
        "",
        "## Kills",
        "",
    ]
    for k in doc["kills"]:
        rec = k.get("recovery_s")
        lines.append(
            f"- t={k['t']}s slot {k['slot']}: SIGKILL pid "
            f"{k['killed_pid']} (gen {k['killed_generation']}, "
            f"{k['committed_before_kill']} committed) -> recovered in "
            f"{rec if rec is not None else 'NEVER'}s")
    if not doc["kills"]:
        lines.append("- none armed")
    rp = doc.get("role_processes") or {}
    if rp:
        lines += [
            "",
            "## Role processes",
            "",
            f"- externally hosted: {rp.get('resolvers', 0)} "
            f"resolver(s) + {rp.get('tlogs', 0)} tlog(s) "
            f"(rolehost OS processes), resolver SIGKILLs armed: "
            f"{cfg.get('kill_resolver', 0)}",
        ]
        for r in doc.get("resolver_kills") or []:
            rec = r.get("recovery_s")
            lines.append(
                f"- t={r['t']}s {r['name']}: SIGKILL pid "
                f"{r['killed_pid']} ({r['committed_before_kill']} "
                f"committed cluster-wide) -> pipeline recovered in "
                f"{rec if rec is not None else 'NEVER'}s "
                f"(respawned pid {r.get('respawned_pid', '?')}, "
                f"recovered_state="
                f"{r.get('respawn_recovered_state', '?')})")
        for d in rp.get("status") or []:
            c = d.get("counters") or {}
            pm = d.get("process_metrics") or {}
            lines.append(
                f"- {d.get('process', '?')}: requests="
                f"{c.get('requests', 0)} journaled="
                f"{c.get('journaled', 0)} replayed="
                f"{c.get('replayed', 0)} checkpoints="
                f"{c.get('checkpoints', 0)} cpu_s="
                f"{pm.get('cpu_seconds', '?')} rss="
                f"{pm.get('rss_bytes', '?')}")
    fed = doc.get("federation") or {}
    lines += [
        "",
        "## Federation",
        "",
        f"- processes in status.cluster.processes: "
        f"{fed.get('process_count', 0)} "
        f"({fed.get('up', 0)} up), scrape samples: "
        f"{fed.get('scrape_samples', 0)}",
        "- fdbtpu_process_* coverage (pids): "
        + (", ".join(str(p)
                     for p in fed.get("process_metric_pids", ()))
           or "-"),
    ]
    if fed.get("role_cpu_share"):
        lines.append(
            "- federated role_cpu_share: "
            + ", ".join(f"{r}={v}" for r, v in
                        fed["role_cpu_share"].items()))
    tr = doc.get("trace") or {}
    if tr:
        lines += [
            "",
            "## Cross-process traces",
            "",
            f"- merged chains: {tr['chains']} "
            f"({tr['cross_process_chains']} cross-process, "
            f"{tr['full_commit_chains']} full "
            f"client->proxy->resolver->tlog paths)",
            f"- processes: {', '.join(tr['processes'])}",
        ]
    sl = doc.get("slo") or {}
    if sl:
        lines += [
            "",
            "## SLO (read back from the persistent plane)",
            "",
            f"- signals: {sl.get('signals', 0)} "
            f"({sl.get('series_samples', 0)} samples), timekeeper rows: "
            f"{sl.get('timekeeper_rows', 0)} "
            f"(round-trip ok={sl.get('timekeeper_ok')})",
            f"- final verdict: {sl.get('final_state')} "
            f"breached={sl.get('final_breached')}",
            f"- breaches: online={sl.get('online_breaches', 0)} "
            f"post-hoc={sl.get('posthoc_breaches', 0)}, drill window: "
            f"{sl.get('breach_window')}",
        ]
        if sl.get("bundle"):
            b = sl["bundle"]
            lines.append(
                f"- incident bundle: {b['dir']} "
                f"({b['samples']} samples over {b['signals']} signals)")
    lines += ["", "## Timeline", ""]
    total_rows = doc.get("timeline_rows", len(doc["timeline"]))
    if total_rows > len(doc["timeline"]):
        lines += [f"(tail of {total_rows} rows — full series streams "
                  f"to {doc.get('timeline_path', 'timeline.jsonl')})",
                  ""]
    lines += ["| t (s) | committed | txn/s | divergent | workers up |",
              "|---|---|---|---|---|"]
    for row in doc["timeline"]:
        lines.append(
            f"| {row['t']} | {row['committed']} | {row['txn_per_s']} "
            f"| {row['divergent']} | {row['workers_up']} |")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    kw: dict = {}
    out_path = OUT_PATH
    report_path = REPORT_PATH
    while argv:
        a = argv.pop(0)
        if a == "--worker":
            run_soak_worker(json.loads(argv.pop(0)))
            return 0
        if a == "--processes":
            kw["processes"] = int(argv.pop(0))
        elif a == "--resolvers":
            kw["resolvers"] = int(argv.pop(0))
        elif a == "--duration":
            kw["duration"] = float(argv.pop(0))
        elif a == "--rate":
            kw["rate"] = float(argv.pop(0))
        elif a == "--kills":
            kw["kills"] = int(argv.pop(0))
        elif a == "--seed":
            kw["seed"] = int(argv.pop(0))
        elif a == "--sample-period":
            kw["sample_period"] = float(argv.pop(0))
        elif a == "--hours":
            kw["hours"] = float(argv.pop(0))
        elif a == "--slo":
            kw["slo"] = True
        elif a == "--breach-at":
            kw["breach_at"] = float(argv.pop(0))
        elif a == "--breach-len":
            kw["breach_len"] = float(argv.pop(0))
        elif a == "--resolver-processes":
            kw["resolver_processes"] = int(argv.pop(0))
        elif a == "--tlog-processes":
            kw["tlog_processes"] = int(argv.pop(0))
        elif a == "--kill-resolver":
            kw["kill_resolver"] = int(argv.pop(0))
        elif a == "--run-dir":
            kw["run_dir"] = argv.pop(0)
        elif a == "--no-trace":
            kw["trace"] = False
        elif a == "--out":
            out_path = argv.pop(0)
        elif a == "--report":
            report_path = argv.pop(0)
        else:
            print(f"unknown argument {a!r}")
            return 2
    doc = run_soak(out=print, **kw)
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    with open(report_path, "w") as fh:
        fh.write(render_soak_report(doc))
    print(f"report -> {out_path} + {report_path} "
          f"trace-run-dir={doc['run_dir']}")
    return 0 if doc.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
