/*
 * fdb_tpu.h — C ABI for the foundationdb_tpu client.
 *
 * Reference surface: bindings/c/foundationdb/fdb_c.h — database and
 * transaction handles, byte-string keys/values, numeric error codes
 * (flow/error_definitions.h; this framework keeps the same numbers),
 * and the standard on_error retry protocol.
 *
 * Unlike the reference's fdb_c (a thin ABI over the linked-in C++
 * NativeAPI), this library IS a native client: it speaks the
 * framework's wire protocol (rpc/tcp.py framing + rpc/wire.py tagged
 * encoding) over a TCP connection to a cluster gateway, and implements
 * the client logic itself — read-your-writes overlay, atomic-op
 * folding, shard-routed reads with replica failover, conflict-range
 * recording, OCC commit, and the retry/refresh loop
 * (fdbclient/NativeAPI.actor.cpp, fdbclient/ReadYourWrites.actor.cpp).
 *
 * Calls are blocking; one connection is shared and the library is
 * thread-safe per handle (a transaction must not be used from two
 * threads at once, matching the reference's rule).
 *
 * Not yet carried over this ABI: versionstamped operand reads
 * (set-versionstamp mutations themselves DO commit).
 */

#ifndef FDB_TPU_C_H
#define FDB_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef int fdb_tpu_error_t; /* 0 = success; codes = error_definitions.h */

typedef struct FDBTpuDatabase FDBTpuDatabase;
typedef struct FDBTpuTransaction FDBTpuTransaction;

typedef struct {
    uint8_t* key;
    int key_length;
    uint8_t* value;
    int value_length;
} FDBTpuKeyValue;

/* mutation type numbers = fdbclient/CommitTransaction.h (server/types.py) */
enum {
    FDB_TPU_OP_ADD = 2,
    FDB_TPU_OP_AND = 6,
    FDB_TPU_OP_OR = 7,
    FDB_TPU_OP_XOR = 8,
    FDB_TPU_OP_APPEND_IF_FITS = 9,
    FDB_TPU_OP_MAX = 12,
    FDB_TPU_OP_MIN = 13,
    FDB_TPU_OP_SET_VERSIONSTAMPED_KEY = 14,
    FDB_TPU_OP_SET_VERSIONSTAMPED_VALUE = 15,
    FDB_TPU_OP_BYTE_MIN = 16,
    FDB_TPU_OP_BYTE_MAX = 17,
    FDB_TPU_OP_MIN_V2 = 18,  /* MIN already applies V2 semantics */
    FDB_TPU_OP_AND_V2 = 19,
    FDB_TPU_OP_COMPARE_AND_CLEAR = 20,
};

const char* fdb_tpu_get_error(fdb_tpu_error_t code);
int fdb_tpu_error_retryable(fdb_tpu_error_t code);
/* The 8-byte wire-protocol tag this library speaks (build-time
 * FDBTPU_PROTOCOL; a MultiVersion loader selects the copy matching
 * the cluster). */
const char* fdb_tpu_get_protocol(void);

/* Connect to a cluster gateway and fetch the initial cluster picture. */
fdb_tpu_error_t fdb_tpu_create_database(const char* host, int port,
                                        FDBTpuDatabase** out_db);
void fdb_tpu_database_destroy(FDBTpuDatabase* db);

fdb_tpu_error_t fdb_tpu_database_create_transaction(
    FDBTpuDatabase* db, FDBTpuTransaction** out_tr);
void fdb_tpu_transaction_destroy(FDBTpuTransaction* tr);
void fdb_tpu_transaction_reset(FDBTpuTransaction* tr);

/* Named options: "access_system_keys" (admits stored \xff\x02 writes +
 * \xff reads), "read_system_keys" (reads only). Unknown names return
 * invalid_option_value (2006). Options reset with the transaction. */
fdb_tpu_error_t fdb_tpu_transaction_set_option(FDBTpuTransaction* tr,
                                               const char* option);

fdb_tpu_error_t fdb_tpu_transaction_get_read_version(FDBTpuTransaction* tr,
                                                     int64_t* out_version);

/* *out_present = 0 and *out_value = NULL for an absent key. The value
 * buffer is malloc'd; free with fdb_tpu_free. */
fdb_tpu_error_t fdb_tpu_transaction_get(FDBTpuTransaction* tr,
                                        const uint8_t* key, int key_length,
                                        int snapshot, int* out_present,
                                        uint8_t** out_value,
                                        int* out_value_length);

/* Resolve a key selector: the `offset`-th key past the first key
 * >= (or_equal=0) / > (or_equal=1) the anchor. Result malloc'd. */
fdb_tpu_error_t fdb_tpu_transaction_get_key(FDBTpuTransaction* tr,
                                            const uint8_t* key,
                                            int key_length, int or_equal,
                                            int offset, int snapshot,
                                            uint8_t** out_key,
                                            int* out_key_length);

/* Result array + every contained buffer are malloc'd; free with
 * fdb_tpu_free_keyvalues. */
fdb_tpu_error_t fdb_tpu_transaction_get_range(
    FDBTpuTransaction* tr, const uint8_t* begin, int begin_length,
    const uint8_t* end, int end_length, int limit, int reverse, int snapshot,
    FDBTpuKeyValue** out_kv, int* out_count);

fdb_tpu_error_t fdb_tpu_transaction_set(FDBTpuTransaction* tr,
                                        const uint8_t* key, int key_length,
                                        const uint8_t* value,
                                        int value_length);
fdb_tpu_error_t fdb_tpu_transaction_clear(FDBTpuTransaction* tr,
                                          const uint8_t* key, int key_length);
fdb_tpu_error_t fdb_tpu_transaction_clear_range(FDBTpuTransaction* tr,
                                                const uint8_t* begin,
                                                int begin_length,
                                                const uint8_t* end,
                                                int end_length);
fdb_tpu_error_t fdb_tpu_transaction_atomic_op(FDBTpuTransaction* tr,
                                              const uint8_t* key,
                                              int key_length,
                                              const uint8_t* param,
                                              int param_length,
                                              int operation_type);

/* write=0 adds a read conflict range, write=1 a write conflict range */
fdb_tpu_error_t fdb_tpu_transaction_add_conflict_range(
    FDBTpuTransaction* tr, const uint8_t* begin, int begin_length,
    const uint8_t* end, int end_length, int write);

fdb_tpu_error_t fdb_tpu_transaction_commit(FDBTpuTransaction* tr,
                                           int64_t* out_committed_version);

/* 10-byte versionstamp of the last commit (8B BE version + 2B BE batch
 * index); buffer malloc'd. Errors if the transaction has not committed. */
fdb_tpu_error_t fdb_tpu_transaction_get_versionstamp(FDBTpuTransaction* tr,
                                                     uint8_t** out_stamp,
                                                     int* out_length);

/* The standard retry protocol: returns 0 after backoff/reset when the
 * error is retryable (refreshing the cluster picture when it implies a
 * stale one), else returns the error back (ref: fdb_transaction_on_error). */
fdb_tpu_error_t fdb_tpu_transaction_on_error(FDBTpuTransaction* tr,
                                             fdb_tpu_error_t code);

/* Block until the key's value differs from its value as of now, or
 * timeout_ms elapses (returns timed_out). A thread-safe blocking watch
 * (ref: fdb_transaction_watch; the blocking shape suits this ABI). */
fdb_tpu_error_t fdb_tpu_database_watch(FDBTpuDatabase* db,
                                       const uint8_t* key, int key_length,
                                       int timeout_ms);

void fdb_tpu_free(void* p);
void fdb_tpu_free_keyvalues(FDBTpuKeyValue* kv, int count);

#ifdef __cplusplus
}
#endif

#endif /* FDB_TPU_C_H */
