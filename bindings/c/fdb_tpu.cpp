/*
 * fdb_tpu.cpp — native C client for the foundationdb_tpu wire protocol.
 *
 * What the reference's NativeAPI + fdb_c pair does in-process
 * (fdbclient/NativeAPI.actor.cpp, bindings/c/fdb_c.cpp), this file does
 * over the framework's TCP transport: framed token-addressed
 * request/reply (rpc/tcp.py: [u32 len][u8 kind][u64 req_id][u64 token],
 * protocol tag "fdbtpu01"), the tagged value encoding (rpc/wire.py),
 * the cluster picture from the gateway's describe endpoint (playing
 * MonitorLeader/openDatabase), shard-routed reads with replica
 * failover, a read-your-writes overlay with atomic-op folding
 * (fdbclient/ReadYourWrites.actor.cpp, fdbclient/Atomic.h), and the
 * on_error retry/refresh protocol.
 */

#include "fdb_tpu.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

/* ---------------- error table (flow/error.py; codes identical to
 * flow/error_definitions.h) ---------------- */

struct ErrDef {
    const char* name;
    int code;
};

const ErrDef kErrors[] = {
    {"success", 0},
    {"end_of_stream", 1},
    {"operation_failed", 1000},
    {"wrong_shard_server", 1001},
    {"timed_out", 1004},
    {"all_alternatives_failed", 1006},
    {"transaction_too_old", 1007},
    {"future_version", 1009},
    {"tlog_stopped", 1011},
    {"server_request_queue_full", 1012},
    {"not_committed", 1020},
    {"commit_unknown_result", 1021},
    {"transaction_cancelled", 1025},
    {"connection_failed", 1026},
    {"coordinators_changed", 1027},
    {"transaction_timed_out", 1031},
    {"process_behind", 1037},
    {"database_locked", 1038},
    {"broken_promise", 1100},
    {"operation_cancelled", 1101},
    {"client_invalid_operation", 2000},
    {"key_outside_legal_range", 2004},
    {"invalid_option_value", 2006},
    {"inverted_range", 2005},
    {"transaction_too_large", 2101},
    {"key_too_large", 2102},
    {"value_too_large", 2103},
    {"unknown_error", 4000},
    {"internal_error", 4100},
};

int err_code(const std::string& name) {
    for (const auto& e : kErrors)
        if (name == e.name) return e.code;
    return 4000;
}

const char* err_name(int code) {
    for (const auto& e : kErrors)
        if (code == e.code) return e.name;
    return "unknown_error";
}

/* retry classification mirrors client/transaction.py RETRYABLE /
 * REFRESH_ERRORS */
bool is_retryable(int code) {
    switch (code) {
        case 1020: case 1007: case 1009: case 1100: case 1021:
        case 1004: case 1011: case 1027: case 1001:
            return true;
        default:
            return false;
    }
}

bool needs_refresh(int code) {
    switch (code) {
        case 1100: case 1021: case 1011: case 1027: case 1001:
            return true;
        default:
            return false;
    }
}

/* client-side size limits (flow/knobs.py defaults) */
constexpr size_t kKeySizeLimit = 10000;
constexpr size_t kValueSizeLimit = 100000;
constexpr size_t kTxnSizeLimit = 10000000;
constexpr int kRequestTimeoutMs = 5000;

/* ---------------- wire value model (rpc/wire.py tags) ---------------- */

enum WTag : uint8_t {
    W_NONE = 0, W_FALSE = 1, W_TRUE = 2, W_INT = 3, W_BIGINT = 4,
    W_FLOAT = 5, W_BYTES = 6, W_STR = 7, W_TUPLE = 8, W_LIST = 9,
    W_NT = 10, W_REF = 11, W_DICT = 12,
};

struct WVal {
    enum T { NONE, BOOL, INT, FLOAT, BYTES, STR, TUPLE, LIST, DICT, NT } t =
        NONE;
    bool b = false;
    int64_t i = 0;
    double f = 0;
    std::string s;            /* BYTES/STR payload; NT: type name */
    std::vector<WVal> items;  /* TUPLE/LIST/NT fields; DICT: k,v,k,v... */

    static WVal none() { return WVal{}; }
    static WVal boolean(bool v) {
        WVal w; w.t = BOOL; w.b = v; return w;
    }
    static WVal integer(int64_t v) {
        WVal w; w.t = INT; w.i = v; return w;
    }
    static WVal bytes(const std::string& v) {
        WVal w; w.t = BYTES; w.s = v; return w;
    }
    static WVal tuple(std::vector<WVal> v) {
        WVal w; w.t = TUPLE; w.items = std::move(v); return w;
    }
    static WVal nt(const char* name, std::vector<WVal> fields) {
        WVal w; w.t = NT; w.s = name; w.items = std::move(fields); return w;
    }
};

void put_u32(std::string& out, uint32_t v) {
    char b[4];
    b[0] = char(v); b[1] = char(v >> 8); b[2] = char(v >> 16);
    b[3] = char(v >> 24);
    out.append(b, 4);
}

void put_i64(std::string& out, int64_t sv) {
    uint64_t v = uint64_t(sv);
    char b[8];
    for (int k = 0; k < 8; k++) b[k] = char(v >> (8 * k));
    out.append(b, 8);
}

void wire_encode(const WVal& v, std::string& out) {
    switch (v.t) {
        case WVal::NONE:
            out.push_back(char(W_NONE));
            break;
        case WVal::BOOL:
            out.push_back(char(v.b ? W_TRUE : W_FALSE));
            break;
        case WVal::INT:
            out.push_back(char(W_INT));
            put_i64(out, v.i);
            break;
        case WVal::FLOAT: {
            out.push_back(char(W_FLOAT));
            char b[8];
            std::memcpy(b, &v.f, 8); /* IEEE754 little-endian host */
            out.append(b, 8);
            break;
        }
        case WVal::BYTES:
            out.push_back(char(W_BYTES));
            put_u32(out, uint32_t(v.s.size()));
            out.append(v.s);
            break;
        case WVal::STR:
            out.push_back(char(W_STR));
            put_u32(out, uint32_t(v.s.size()));
            out.append(v.s);
            break;
        case WVal::TUPLE:
        case WVal::LIST:
            out.push_back(char(v.t == WVal::TUPLE ? W_TUPLE : W_LIST));
            put_u32(out, uint32_t(v.items.size()));
            for (const auto& it : v.items) wire_encode(it, out);
            break;
        case WVal::DICT:
            out.push_back(char(W_DICT));
            put_u32(out, uint32_t(v.items.size() / 2));
            for (const auto& it : v.items) wire_encode(it, out);
            break;
        case WVal::NT:
            out.push_back(char(W_NT));
            put_u32(out, uint32_t(v.s.size()));
            out.append(v.s);
            put_u32(out, uint32_t(v.items.size()));
            for (const auto& it : v.items) wire_encode(it, out);
            break;
    }
}

bool get_u32(const std::string& buf, size_t& off, uint32_t* out) {
    if (off + 4 > buf.size()) return false;
    const unsigned char* p = (const unsigned char*)buf.data() + off;
    *out = uint32_t(p[0]) | uint32_t(p[1]) << 8 | uint32_t(p[2]) << 16 |
           uint32_t(p[3]) << 24;
    off += 4;
    return true;
}

bool get_i64(const std::string& buf, size_t& off, int64_t* out) {
    if (off + 8 > buf.size()) return false;
    const unsigned char* p = (const unsigned char*)buf.data() + off;
    uint64_t v = 0;
    for (int k = 0; k < 8; k++) v |= uint64_t(p[k]) << (8 * k);
    *out = int64_t(v);
    off += 8;
    return true;
}

bool wire_decode(const std::string& buf, size_t& off, WVal* out,
                 int depth = 0) {
    /* nesting bound: a frame of repeated 1-element list headers must
     * not be able to overflow the stack */
    if (depth > 64) return false;
    if (off >= buf.size()) return false;
    uint8_t tag = uint8_t(buf[off++]);
    switch (tag) {
        case W_NONE:
            out->t = WVal::NONE;
            return true;
        case W_FALSE:
        case W_TRUE:
            out->t = WVal::BOOL;
            out->b = (tag == W_TRUE);
            return true;
        case W_INT:
            out->t = WVal::INT;
            return get_i64(buf, off, &out->i);
        case W_FLOAT: {
            if (off + 8 > buf.size()) return false;
            out->t = WVal::FLOAT;
            std::memcpy(&out->f, buf.data() + off, 8);
            off += 8;
            return true;
        }
        case W_BYTES:
        case W_STR: {
            uint32_t ln;
            if (!get_u32(buf, off, &ln) || off + ln > buf.size())
                return false;
            out->t = (tag == W_BYTES ? WVal::BYTES : WVal::STR);
            out->s.assign(buf, off, ln);
            off += ln;
            return true;
        }
        case W_TUPLE:
        case W_LIST: {
            uint32_t n;
            if (!get_u32(buf, off, &n)) return false;
            /* each element needs >=1 byte: an untrusted count beyond the
             * remaining buffer is malformed, not a multi-GB resize */
            if (n > buf.size() - off) return false;
            out->t = (tag == W_TUPLE ? WVal::TUPLE : WVal::LIST);
            out->items.resize(n);
            for (uint32_t k = 0; k < n; k++)
                if (!wire_decode(buf, off, &out->items[k], depth + 1))
                    return false;
            return true;
        }
        case W_DICT: {
            uint32_t n;
            if (!get_u32(buf, off, &n)) return false;
            if (n > (buf.size() - off) / 2) return false;
            out->t = WVal::DICT;
            out->items.resize(size_t(n) * 2);
            for (uint32_t k = 0; k < 2 * n; k++)
                if (!wire_decode(buf, off, &out->items[k], depth + 1))
                    return false;
            return true;
        }
        case W_NT: {
            uint32_t ln, n;
            if (!get_u32(buf, off, &ln) || off + ln > buf.size())
                return false;
            out->t = WVal::NT;
            out->s.assign(buf, off, ln);
            off += ln;
            if (!get_u32(buf, off, &n)) return false;
            if (n > buf.size() - off) return false;
            out->items.resize(n);
            for (uint32_t k = 0; k < n; k++)
                if (!wire_decode(buf, off, &out->items[k], depth + 1))
                    return false;
            return true;
        }
        default:
            /* W_BIGINT/W_REF never appear on the gateway's client
             * surface; treat as malformed */
            return false;
    }
}

/* dict lookup by string key */
const WVal* dict_get(const WVal& d, const char* key) {
    if (d.t != WVal::DICT) return nullptr;
    for (size_t k = 0; k + 1 < d.items.size(); k += 2)
        if (d.items[k].t == WVal::STR && d.items[k].s == key)
            return &d.items[k + 1];
    return nullptr;
}

/* ---------------- connection (rpc/tcp.py peer) ---------------- */

constexpr uint8_t K_REQUEST = 0, K_REPLY = 1, K_ERROR = 2;
/* 8 bytes, PROTOCOL_VERSION. Overridable at build time so versioned
 * copies of this library can be built for a MultiVersion client to
 * select among (ref: MultiVersionApi dlopening versioned libfdb_c) */
#ifndef FDBTPU_PROTOCOL
#define FDBTPU_PROTOCOL "fdbtpu01"
#endif
constexpr char kProtocol[] = FDBTPU_PROTOCOL;
static_assert(sizeof(kProtocol) == 9, "protocol tag must be 8 bytes");
constexpr size_t kHdrSize = 21;          /* <IBQQ: 4+1+8+8 */

struct Pending {
    bool done = false;
    uint8_t kind = K_ERROR;
    std::string payload;
};

struct ConnState {
    int fd = -1;
    bool dead = false;
    std::mutex mut; /* guards fd-writes, pending, dead */
    std::condition_variable cv;
    std::map<uint64_t, std::shared_ptr<Pending>> pending;

    void die_locked() {
        if (dead) return;
        dead = true;
        /* shutdown() only — the reader thread owns the close: closing
         * here would race its recv() against kernel fd-number reuse by
         * a reconnect */
        if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
        for (auto& kv : pending) {
            kv.second->done = true;
            kv.second->kind = K_ERROR;
            kv.second->payload.clear(); /* empty payload = broken_promise */
        }
        pending.clear();
    }
    void die() {
        std::lock_guard<std::mutex> g(mut);
        die_locked();
        cv.notify_all();
    }
};

bool read_exact(int fd, void* buf, size_t n) {
    char* p = (char*)buf;
    while (n > 0) {
        ssize_t r = ::recv(fd, p, n, 0);
        if (r <= 0) return false;
        p += r;
        n -= size_t(r);
    }
    return true;
}

bool write_all(int fd, const void* buf, size_t n) {
    const char* p = (const char*)buf;
    while (n > 0) {
        ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
        if (r <= 0) return false;
        p += r;
        n -= size_t(r);
    }
    return true;
}

void reader_thread(std::shared_ptr<ConnState> st) {
    for (;;) {
        int fd;
        {
            std::lock_guard<std::mutex> g(st->mut);
            if (st->dead) return;
            fd = st->fd;
        }
        uint8_t hdr[kHdrSize];
        if (!read_exact(fd, hdr, kHdrSize)) break;
        uint32_t ln = uint32_t(hdr[0]) | uint32_t(hdr[1]) << 8 |
                      uint32_t(hdr[2]) << 16 | uint32_t(hdr[3]) << 24;
        uint8_t kind = hdr[4];
        uint64_t req_id = 0;
        for (int k = 0; k < 8; k++) req_id |= uint64_t(hdr[5 + k]) << (8 * k);
        /* a corrupt length must not become a multi-GB allocation; no
         * legitimate reply approaches this (txn limit is 10MB, range
         * replies are row-limited) */
        if (ln > (1u << 30)) break;
        std::string payload(ln, '\0');
        if (ln && !read_exact(fd, payload.data(), ln)) break;
        std::lock_guard<std::mutex> g(st->mut);
        auto it = st->pending.find(req_id);
        if (it != st->pending.end()) {
            it->second->done = true;
            it->second->kind = kind;
            it->second->payload = std::move(payload);
            st->pending.erase(it);
            st->cv.notify_all();
        }
    }
    st->die();
    {
        /* sole closer of the fd (see die_locked); writers hold the
         * mutex for their whole write, so this cannot race a send */
        std::lock_guard<std::mutex> g(st->mut);
        if (st->fd >= 0) {
            ::close(st->fd);
            st->fd = -1;
        }
    }
}

struct Conn {
    std::string host;
    int port = 0;
    std::shared_ptr<ConnState> st;
    uint64_t next_req = 1;
    std::mutex mut; /* guards st swap + next_req */

    fdb_tpu_error_t ensure_connected(std::shared_ptr<ConnState>* out) {
        std::lock_guard<std::mutex> g(mut);
        if (st) {
            std::lock_guard<std::mutex> g2(st->mut);
            if (!st->dead) {
                *out = st;
                return 0;
            }
        }
        int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) return 1026;
        struct addrinfo hints;
        std::memset(&hints, 0, sizeof(hints));
        hints.ai_family = AF_INET;
        hints.ai_socktype = SOCK_STREAM;
        struct addrinfo* res = nullptr;
        std::string portstr = std::to_string(port);
        if (getaddrinfo(host.c_str(), portstr.c_str(), &hints, &res) != 0 ||
            res == nullptr) {
            ::close(fd);
            return 1026;
        }
        int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
        freeaddrinfo(res);
        if (rc != 0 || !write_all(fd, kProtocol, 8)) {
            ::close(fd);
            return 1026;
        }
        auto fresh = std::make_shared<ConnState>();
        fresh->fd = fd;
        st = fresh;
        std::thread(reader_thread, fresh).detach();
        *out = fresh;
        return 0;
    }

    /* blocking request; on success *out holds the decoded reply value */
    fdb_tpu_error_t request(uint64_t token, const WVal& req, WVal* out,
                            int timeout_ms = kRequestTimeoutMs) {
        std::string payload;
        wire_encode(req, payload);
        std::shared_ptr<ConnState> c;
        fdb_tpu_error_t err = ensure_connected(&c);
        if (err) return err;
        auto p = std::make_shared<Pending>();
        uint64_t req_id;
        {
            std::lock_guard<std::mutex> g(mut);
            req_id = next_req++;
        }
        std::string frame;
        frame.reserve(kHdrSize + payload.size());
        put_u32(frame, uint32_t(payload.size()));
        frame.push_back(char(K_REQUEST));
        put_i64(frame, int64_t(req_id));
        put_i64(frame, int64_t(token));
        frame += payload;
        {
            std::unique_lock<std::mutex> g(c->mut);
            if (c->dead) return 1100;
            c->pending[req_id] = p;
            /* write under the conn lock: frames stay whole (the Python
             * side queues via a writer thread; one lock suffices here) */
            if (!write_all(c->fd, frame.data(), frame.size())) {
                c->die_locked();
                c->cv.notify_all();
                return 1100;
            }
            bool ok = c->cv.wait_for(
                g, std::chrono::milliseconds(timeout_ms),
                [&] { return p->done; });
            if (!ok) {
                c->pending.erase(req_id);
                return 1004; /* timed_out */
            }
        }
        if (p->kind == K_REPLY) {
            size_t off = 0;
            if (!wire_decode(p->payload, off, out)) return 4000;
            return 0;
        }
        if (p->payload.empty()) return 1100; /* connection death */
        size_t off = 0;
        WVal nm;
        if (!wire_decode(p->payload, off, &nm) || nm.t != WVal::STR)
            return 4000;
        return err_code(nm.s);
    }
};

/* ---------------- cluster picture (gateway describe) ---------------- */

struct Replica {
    uint64_t gets = 0, ranges = 0, get_keys = 0, watches = 0;
    std::string name;
};

struct Shard {
    std::string begin;
    std::string end;
    bool has_end = false;
    std::vector<Replica> replicas;
};

struct ProxyEndpoints {
    uint64_t grvs = 0, commits = 0;
};

struct ClusterInfo {
    int64_t seq = -1;
    std::vector<ProxyEndpoints> proxies;
    std::vector<Shard> shards;
};

bool parse_info(const WVal& d, ClusterInfo* out) {
    const WVal* seq = dict_get(d, "seq");
    const WVal* proxies = dict_get(d, "proxies");
    const WVal* shards = dict_get(d, "shards");
    if (!seq || seq->t != WVal::INT || !proxies || !shards) return false;
    out->seq = seq->i;
    for (const auto& p : proxies->items) {
        const WVal* g = dict_get(p, "grvs");
        const WVal* c = dict_get(p, "commits");
        if (!g || !c || g->t != WVal::INT || c->t != WVal::INT) return false;
        out->proxies.push_back({uint64_t(g->i), uint64_t(c->i)});
    }
    for (const auto& s : shards->items) {
        const WVal* b = dict_get(s, "begin");
        const WVal* e = dict_get(s, "end");
        const WVal* he = dict_get(s, "has_end");
        const WVal* reps = dict_get(s, "replicas");
        if (!b || !e || !he || !reps) return false;
        Shard sh;
        sh.begin = b->s;
        sh.end = e->s;
        sh.has_end = he->b;
        for (const auto& r : reps->items) {
            const WVal* g = dict_get(r, "gets");
            const WVal* rg = dict_get(r, "ranges");
            const WVal* gk = dict_get(r, "get_keys");
            const WVal* wa = dict_get(r, "watches");
            const WVal* nm = dict_get(r, "name");
            if (!g || !rg || !gk) return false;
            sh.replicas.push_back(
                {uint64_t(g->i), uint64_t(rg->i), uint64_t(gk->i),
                 wa ? uint64_t(wa->i) : 0,
                 nm ? nm->s : std::string()});
        }
        out->shards.push_back(std::move(sh));
    }
    return !out->proxies.empty() && !out->shards.empty();
}

/* ---------------- atomic ops (server/atomic.py parity) ---------------- */

using OptBytes = std::optional<std::string>;

std::string le_add_like(const std::string& a, const std::string& b,
                        bool is_add, bool take_max) {
    /* unsigned little-endian arithmetic over arbitrary widths; result
     * truncated/zero-padded to the PARAM's length (doLittleEndianAdd) */
    size_t n = b.size();
    std::string out(n, '\0');
    if (is_add) {
        unsigned carry = 0;
        for (size_t k = 0; k < n; k++) {
            unsigned av = k < a.size() ? (unsigned char)a[k] : 0;
            unsigned sum = av + (unsigned char)b[k] + carry;
            out[k] = char(sum & 0xFF);
            carry = sum >> 8;
        }
        return out;
    }
    /* max/min: compare as little-endian unsigned integers of arbitrary
     * width, then truncate the winner to param width */
    auto cmp_le = [](const std::string& x, const std::string& y) {
        size_t nx = x.size(), ny = y.size();
        size_t top = std::max(nx, ny);
        for (size_t k = top; k-- > 0;) {
            unsigned xv = k < nx ? (unsigned char)x[k] : 0;
            unsigned yv = k < ny ? (unsigned char)y[k] : 0;
            if (xv != yv) return xv < yv ? -1 : 1;
        }
        return 0;
    };
    int c = cmp_le(a, b);
    const std::string& win = (take_max ? (c >= 0 ? a : b) : (c <= 0 ? a : b));
    std::string out2(n, '\0');
    for (size_t k = 0; k < n && k < win.size(); k++) out2[k] = win[k];
    return out2;
}

OptBytes apply_atomic(int op, const OptBytes& existing,
                      const std::string& param) {
    switch (op) {
        case FDB_TPU_OP_ADD:
            if (param.empty()) return std::string();
            if (!existing || existing->empty()) return param;
            return le_add_like(*existing, param, true, false);
        case FDB_TPU_OP_AND:
        case FDB_TPU_OP_AND_V2: {
            if (!existing) return param; /* V2 semantics */
            std::string out(param);
            for (size_t k = 0; k < out.size(); k++) {
                char e = k < existing->size() ? (*existing)[k] : 0;
                out[k] = char(out[k] & e);
            }
            return out;
        }
        case FDB_TPU_OP_OR:
        case FDB_TPU_OP_XOR: {
            std::string ex = existing ? *existing : std::string();
            std::string out(param);
            for (size_t k = 0; k < out.size(); k++) {
                char e = k < ex.size() ? ex[k] : 0;
                out[k] = char(op == FDB_TPU_OP_OR ? (out[k] | e)
                                                  : (out[k] ^ e));
            }
            return out;
        }
        case FDB_TPU_OP_APPEND_IF_FITS: {
            std::string ex = existing ? *existing : std::string();
            if (ex.size() + param.size() <= kValueSizeLimit)
                return ex + param;
            return ex;
        }
        case FDB_TPU_OP_MAX:
            if (!existing || existing->empty() || param.empty()) return param;
            return le_add_like(*existing, param, false, true);
        case FDB_TPU_OP_MIN:
        case FDB_TPU_OP_MIN_V2:
            if (!existing) return param; /* V2 semantics */
            if (param.empty()) return param;
            return le_add_like(*existing, param, false, false);
        case FDB_TPU_OP_BYTE_MIN:
            if (!existing) return param;
            return std::min(*existing, param);
        case FDB_TPU_OP_BYTE_MAX:
            if (!existing) return param;
            return std::max(*existing, param);
        case FDB_TPU_OP_COMPARE_AND_CLEAR:
            if (existing && *existing == param) return std::nullopt;
            return existing;
        default:
            return existing;
    }
}

bool is_atomic_op(int op) {
    switch (op) {
        case FDB_TPU_OP_ADD: case FDB_TPU_OP_AND: case FDB_TPU_OP_OR:
        case FDB_TPU_OP_XOR: case FDB_TPU_OP_APPEND_IF_FITS:
        case FDB_TPU_OP_MAX: case FDB_TPU_OP_MIN: case FDB_TPU_OP_BYTE_MIN:
        case FDB_TPU_OP_BYTE_MAX: case FDB_TPU_OP_MIN_V2:
        case FDB_TPU_OP_AND_V2: case FDB_TPU_OP_COMPARE_AND_CLEAR:
            return true;
        default:
            return false;
    }
}

std::string next_key(const std::string& k) { return k + '\0'; }

size_t shard_index_for(const std::shared_ptr<const ClusterInfo>& p,
                       const std::string& key) {
    for (size_t k = p->shards.size(); k-- > 0;)
        if (key >= p->shards[k].begin) return k;
    return 0;
}

} /* namespace */

/* ---------------- public handles ---------------- */

struct FDBTpuDatabase {
    Conn conn;
    std::mutex mut; /* guards info + rng */
    std::shared_ptr<const ClusterInfo> info;
    std::mt19937 rng{0x5eed};

    std::shared_ptr<const ClusterInfo> picture() {
        std::lock_guard<std::mutex> g(mut);
        return info;
    }

    uint32_t rand_below(uint32_t n) {
        std::lock_guard<std::mutex> g(mut);
        return n ? rng() % n : 0;
    }

    fdb_tpu_error_t describe(int64_t min_seq) {
        WVal reply;
        fdb_tpu_error_t err =
            conn.request(1 /* DESCRIBE_TOKEN */, WVal::integer(min_seq),
                         &reply);
        if (err) return err;
        auto fresh = std::make_shared<ClusterInfo>();
        if (!parse_info(reply, fresh.get())) return 4000;
        std::lock_guard<std::mutex> g(mut);
        if (!info || fresh->seq >= info->seq) info = std::move(fresh);
        return 0;
    }
};

struct Mutation {
    int type;
    std::string p1, p2;
};

/* \xff system-keyspace boundaries (client/transaction.py SYSTEM_PREFIX/
 * STORED_SYSTEM_PREFIX/ENGINE_PREFIX; ref: fdbclient/SystemData.cpp) */
static bool in_system(const std::string& k) {
    return !k.empty() && (unsigned char)k[0] == 0xFFu;
}
static const std::string kSystemBegin("\xff", 1);
static const std::string kStoredBegin("\xff\x02", 2);
static const std::string kEngineBegin("\xff\xff", 2);
static const std::string kKeyServersPrefix("\xff/keyServers/");
static const std::string kKeyServersEnd("\xff/keyServers0");
/* the STORED region [\xff\x02, \xff\xff) minus the materialized
 * \xff/keyServers/ view — matches server/systemkeys.py
 * is_stored_system (conf/excluded/backup rows are real shard data) */
static bool stored_system(const std::string& k) {
    return k >= kStoredBegin && k < kEngineBegin &&
           !(k >= kKeyServersPrefix && k < kKeyServersEnd);
}
/* one synthesized \xff/keyServers/ row value: the shard's replica
 * team, comma-joined (client/transaction.py _system_rows) */
static std::string team_value(const Shard& s) {
    std::string v;
    for (size_t i = 0; i < s.replicas.size(); ++i) {
        if (i) v += ",";
        v += s.replicas[i].name;
    }
    return v;
}

struct FDBTpuTransaction {
    FDBTpuDatabase* db;
    bool read_system = false;    /* READ_SYSTEM_KEYS */
    bool access_system = false;  /* ACCESS_SYSTEM_KEYS (implies read) */
    int64_t read_version = -1;
    int64_t used_seq = -1;
    /* RYW overlay: key -> (present, value); clears in op order */
    std::map<std::string, std::pair<bool, std::string>> writes;
    std::vector<std::pair<std::string, std::string>> clears;
    std::map<std::string, std::vector<std::pair<int, std::string>>> ops;
    std::vector<Mutation> mutations;
    std::vector<std::pair<std::string, std::string>> rc, wc;
    size_t txn_bytes = 0;
    int64_t committed_version = -1;
    int64_t committed_batch_index = -1;

    void reset() {
        read_system = false;
        access_system = false;
        read_version = -1;
        used_seq = -1;
        writes.clear();
        clears.clear();
        ops.clear();
        mutations.clear();
        rc.clear();
        wc.clear();
        txn_bytes = 0;
        committed_version = -1;
        committed_batch_index = -1;
    }

    std::shared_ptr<const ClusterInfo> picture() {
        auto p = db->picture();
        if (p && p->seq > used_seq) used_seq = p->seq;
        return p;
    }

    /* (found, value) against uncommitted writes, newest-first
     * (client/transaction.py _overlay_get) */
    bool overlay_get(const std::string& key, OptBytes* out) {
        auto it = writes.find(key);
        if (it != writes.end()) {
            *out = it->second.first ? OptBytes(it->second.second)
                                    : std::nullopt;
            return true;
        }
        for (auto rit = clears.rbegin(); rit != clears.rend(); ++rit)
            if (rit->first <= key && key < rit->second) {
                *out = std::nullopt;
                return true;
            }
        return false;
    }

    fdb_tpu_error_t grv(int64_t* out) {
        if (read_version < 0) {
            auto p = picture();
            if (!p) return 1100;
            const ProxyEndpoints& proxy =
                p->proxies[db->rand_below(uint32_t(p->proxies.size()))];
            WVal reply;
            fdb_tpu_error_t err = db->conn.request(
                proxy.grvs,
                WVal::nt("GetReadVersionRequest", {WVal::integer(1)}),
                &reply);
            if (err) return err;
            if (reply.t != WVal::NT || reply.items.empty() ||
                reply.items[0].t != WVal::INT)
                return 4000;
            read_version = reply.items[0].i;
        }
        *out = read_version;
        return 0;
    }

    size_t shard_index(const std::shared_ptr<const ClusterInfo>& p,
                       const std::string& key) {
        return shard_index_for(p, key);
    }

    /* rotated replica failover (client/transaction.py _storage_rpc) */
    fdb_tpu_error_t storage_rpc(const Shard& shard,
                                uint64_t Replica::*endpoint, const WVal& req,
                                WVal* out) {
        size_t n = shard.replicas.size();
        size_t start = db->rand_below(uint32_t(n));
        fdb_tpu_error_t last = 1100;
        for (size_t j = 0; j < n; j++) {
            const Replica& rep = shard.replicas[(start + j) % n];
            fdb_tpu_error_t err =
                db->conn.request(rep.*endpoint, req, out);
            if (err == 0) return 0;
            if (err != 1100 && err != 1004) return err;
            last = err;
        }
        return last;
    }

    fdb_tpu_error_t base_get(const std::string& key, OptBytes* out) {
        if (overlay_get(key, out)) return 0;
        int64_t version;
        fdb_tpu_error_t err = grv(&version);
        if (err) return err;
        auto p = picture();
        if (!p) return 1100;
        const Shard& shard = p->shards[shard_index(p, key)];
        WVal reply;
        err = storage_rpc(
            shard, &Replica::gets,
            WVal::nt("StorageGetRequest",
                     {WVal::bytes(key), WVal::integer(version)}),
            &reply);
        if (err) return err;
        if (reply.t == WVal::NONE)
            *out = std::nullopt;
        else if (reply.t == WVal::BYTES)
            *out = reply.s;
        else
            return 4000;
        return 0;
    }

    fdb_tpu_error_t get(const std::string& key, bool snapshot, OptBytes* out) {
        if (!snapshot) rc.emplace_back(key, next_key(key));
        fdb_tpu_error_t err = base_get(key, out);
        if (err) return err;
        auto it = ops.find(key);
        if (it != ops.end())
            for (const auto& op : it->second)
                *out = apply_atomic(op.first, *out, op.second);
        return 0;
    }

    fdb_tpu_error_t check_sizes(const std::string& key,
                                const std::string& value, size_t slack = 0) {
        if (key.size() > kKeySizeLimit + slack) return 2102;
        if (value.size() > kValueSizeLimit) return 2103;
        txn_bytes += key.size() + value.size();
        if (txn_bytes > kTxnSizeLimit) return 2101;
        return 0;
    }

    /* client/transaction.py _check_writable: ACCESS_SYSTEM_KEYS admits
     * the stored region [\xff\x02, \xff\xff) — conf/excluded/backup
     * rows are real transactional data — but never the materialized
     * \xff/keyServers/ view and never \xff\xff engine metadata */
    fdb_tpu_error_t check_writable(const std::string& b,
                                   const std::string* e = nullptr) {
        if (e == nullptr) {
            if (in_system(b) && !(access_system && stored_system(b)))
                return 2004;
        } else {
            if (in_system(b) || *e > kSystemBegin) {
                if (!(access_system && b >= kStoredBegin &&
                      *e <= kEngineBegin &&
                      !(b < kKeyServersEnd && *e > kKeyServersPrefix)))
                    return 2004;
            }
        }
        return 0;
    }

    void record_write(const std::string& key, const OptBytes& value) {
        writes[key] = value ? std::make_pair(true, *value)
                            : std::make_pair(false, std::string());
    }
};

/* ---------------- C ABI ---------------- */

extern "C" {

const char* fdb_tpu_get_error(fdb_tpu_error_t code) {
    return err_name(code);
}

const char* fdb_tpu_get_protocol(void) {
    /* the 8-byte wire tag this library speaks (ref: the protocol
     * version a MultiVersion loader matches against the cluster's) */
    return kProtocol;
}

int fdb_tpu_error_retryable(fdb_tpu_error_t code) {
    return is_retryable(code) ? 1 : 0;
}

fdb_tpu_error_t fdb_tpu_create_database(const char* host, int port,
                                        FDBTpuDatabase** out_db) {
    auto* db = new FDBTpuDatabase();
    db->conn.host = host;
    db->conn.port = port;
    fdb_tpu_error_t err = db->describe(-1);
    if (err) {
        fdb_tpu_database_destroy(db); /* reaps the reader thread + fd */
        return err;
    }
    *out_db = db;
    return 0;
}

void fdb_tpu_database_destroy(FDBTpuDatabase* db) {
    if (!db) return;
    if (db->conn.st) db->conn.st->die();
    delete db;
}

fdb_tpu_error_t fdb_tpu_database_create_transaction(
    FDBTpuDatabase* db, FDBTpuTransaction** out_tr) {
    auto* tr = new FDBTpuTransaction();
    tr->db = db;
    *out_tr = tr;
    return 0;
}

void fdb_tpu_transaction_destroy(FDBTpuTransaction* tr) { delete tr; }

fdb_tpu_error_t fdb_tpu_transaction_set_option(FDBTpuTransaction* tr,
                                               const char* option) {
    std::string o(option ? option : "");
    if (o == "access_system_keys") {
        tr->access_system = true;
        tr->read_system = true;
        return 0;
    }
    if (o == "read_system_keys") {
        tr->read_system = true;
        return 0;
    }
    return 2006; /* invalid_option_value */
}

void fdb_tpu_transaction_reset(FDBTpuTransaction* tr) { tr->reset(); }

fdb_tpu_error_t fdb_tpu_transaction_get_read_version(FDBTpuTransaction* tr,
                                                     int64_t* out_version) {
    return tr->grv(out_version);
}

static uint8_t* dup_bytes(const std::string& s) {
    auto* p = (uint8_t*)std::malloc(s.size() ? s.size() : 1);
    if (s.size()) std::memcpy(p, s.data(), s.size());
    return p;
}

fdb_tpu_error_t fdb_tpu_transaction_get(FDBTpuTransaction* tr,
                                        const uint8_t* key, int key_length,
                                        int snapshot, int* out_present,
                                        uint8_t** out_value,
                                        int* out_value_length) {
    std::string k((const char*)key, key_length);
    if (in_system(k) && !tr->read_system)
        return 2004; /* ref: key_outside_legal_range without the option */
    if (in_system(k) && !stored_system(k)) {
        /* the MATERIALIZED view (client/transaction.py _system_get):
         * \xff/keyServers/<key> answers with the owning replica team;
         * other non-stored system keys have no rows. No read conflict
         * — the synthesized view is not transactional data. */
        *out_present = 0;
        *out_value = nullptr;
        *out_value_length = 0;
        if (k.compare(0, kKeyServersPrefix.size(),
                      kKeyServersPrefix) == 0) {
            auto p = tr->picture();
            if (!p) return 1100;
            const Shard& s = p->shards[shard_index_for(
                p, k.substr(kKeyServersPrefix.size()))];
            std::string v = team_value(s);
            *out_present = 1;
            *out_value = dup_bytes(v);
            *out_value_length = int(v.size());
        }
        return 0;
    }
    OptBytes v;
    fdb_tpu_error_t err = tr->get(k, snapshot != 0, &v);
    if (err) return err;
    if (!v) {
        *out_present = 0;
        *out_value = nullptr;
        *out_value_length = 0;
    } else {
        *out_present = 1;
        *out_value = dup_bytes(*v);
        *out_value_length = int(v->size());
    }
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_get_key(FDBTpuTransaction* tr,
                                            const uint8_t* key,
                                            int key_length, int or_equal,
                                            int offset, int snapshot,
                                            uint8_t** out_key,
                                            int* out_key_length) {
    /* selector resolution against the READ-YOUR-WRITES view — merged
     * committed data + this transaction's uncommitted writes/clears
     * (client/transaction.py get_key; ref: ReadYourWrites getKey via
     * RYWIterator). ALL anchors resolve via bounded merged scans so
     * get_key always agrees with what get_range enumerates;
     * READ_SYSTEM_KEYS widens the walk to the system region. */
    std::string anchor((const char*)key, key_length);
    /* anchor == "\xff" (allKeys.end) stays legal: the canonical
     * last-key idiom, same exclusive-end convention as get_range */
    if (in_system(anchor) && anchor != kSystemBegin && !tr->read_system)
        return 2004;
    fdb_tpu_error_t err;
    std::string resolved;
    const std::string& hi_bound =
        tr->read_system ? kEngineBegin : kSystemBegin;
    std::string a = anchor;
    if (or_equal) a.push_back('\0');
    FDBTpuKeyValue* kv = nullptr;
    int n = 0;
    if (offset >= 1) {
        /* the offset-th present merged key >= anchor */
        std::string b = std::min(a, hi_bound);
        if (b < hi_bound) {
            err = fdb_tpu_transaction_get_range(
                tr, (const uint8_t*)b.data(), int(b.size()),
                (const uint8_t*)hi_bound.data(), int(hi_bound.size()),
                offset, 0, 1, &kv, &n);
            if (err) return err;
        }
        if (n >= offset)
            resolved.assign((const char*)kv[offset - 1].key,
                            kv[offset - 1].key_length);
        else
            resolved = hi_bound;
    } else {
        /* the (1-offset)-th present merged key < anchor */
        int needed = 1 - offset;
        std::string e = std::min(a, hi_bound);
        if (!e.empty()) {
            err = fdb_tpu_transaction_get_range(
                tr, (const uint8_t*)"", 0, (const uint8_t*)e.data(),
                int(e.size()), needed, 1, 1, &kv, &n);
            if (err) return err;
        }
        if (n >= needed)
            resolved.assign((const char*)kv[needed - 1].key,
                            kv[needed - 1].key_length);
        else
            resolved.clear();
    }
    if (kv) fdb_tpu_free_keyvalues(kv, n);
    /* a selector walking off user space clamps to maxKey instead of
     * leaking stored \xff rows (client/transaction.py get_key) */
    if (resolved > kSystemBegin && !tr->read_system) resolved = kSystemBegin;
    if (!snapshot) {
        const std::string& lo = std::min(resolved, anchor);
        const std::string& hi = std::max(resolved, anchor);
        tr->rc.emplace_back(lo, next_key(hi));
    }
    *out_key = dup_bytes(resolved);
    *out_key_length = int(resolved.size());
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_get_range(
    FDBTpuTransaction* tr, const uint8_t* begin_p, int begin_length,
    const uint8_t* end_p, int end_length, int limit, int reverse,
    int snapshot, FDBTpuKeyValue** out_kv, int* out_count) {
    std::string begin((const char*)begin_p, begin_length);
    std::string end((const char*)end_p, end_length);
    *out_kv = nullptr;
    *out_count = 0;
    if (begin >= end) return 0;
    if (!tr->read_system) {
        if (in_system(begin) || end > kSystemBegin) return 2004;
    } else if (end > kEngineBegin) {
        return 2004;
    }
    if (limit <= 0) limit = 1 << 20;
    /* system-region parity with client/transaction.py get_range: a
     * scan crossing into \xff splits at the boundary, and a scan
     * touching the materialized \xff/keyServers/ view merges the
     * synthesized rows with the stored subranges around the hole */
    if (tr->read_system &&
        ((!in_system(begin) && end > kSystemBegin) ||
         (in_system(begin) &&
          (!stored_system(begin) ||
           (begin < kKeyServersEnd && end > kKeyServersPrefix))))) {
        std::vector<std::pair<std::string, std::string>> rows;
        std::vector<std::pair<std::string, std::string>> subs;
        if (!in_system(begin)) {
            subs.emplace_back(begin, kSystemBegin);
            subs.emplace_back(kSystemBegin, end);
        } else {
            auto p = tr->picture();
            if (!p) return 1100;
            for (const auto& s : p->shards) {
                std::string rk = kKeyServersPrefix + s.begin;
                if (begin <= rk && rk < end)
                    rows.emplace_back(rk, team_value(s));
            }
            std::string lo = std::max(begin, kStoredBegin);
            std::string hi = std::min(end, kEngineBegin);
            std::string m1 = std::min(hi, kKeyServersPrefix);
            std::string m2 = std::max(lo, kKeyServersEnd);
            if (lo < m1) subs.emplace_back(lo, m1);
            if (m2 < hi) subs.emplace_back(m2, hi);
        }
        for (const auto& sub : subs) {
            FDBTpuKeyValue* kv = nullptr;
            int cnt = 0;
            fdb_tpu_error_t serr = fdb_tpu_transaction_get_range(
                tr, (const uint8_t*)sub.first.data(),
                int(sub.first.size()),
                (const uint8_t*)sub.second.data(),
                int(sub.second.size()),
                in_system(begin) ? 0 : limit, reverse, snapshot,
                &kv, &cnt);
            if (serr) return serr;
            for (int i = 0; i < cnt; ++i)
                rows.emplace_back(
                    std::string((const char*)kv[i].key,
                                size_t(kv[i].key_length)),
                    std::string((const char*)kv[i].value,
                                size_t(kv[i].value_length)));
            fdb_tpu_free_keyvalues(kv, cnt);
        }
        std::sort(rows.begin(), rows.end());
        if (reverse) std::reverse(rows.begin(), rows.end());
        if (int64_t(rows.size()) > limit) rows.resize(limit);
        auto* arr = (FDBTpuKeyValue*)std::calloc(
            rows.size() ? rows.size() : 1, sizeof(FDBTpuKeyValue));
        for (size_t k = 0; k < rows.size(); k++) {
            arr[k].key = dup_bytes(rows[k].first);
            arr[k].key_length = int(rows[k].first.size());
            arr[k].value = dup_bytes(rows[k].second);
            arr[k].value_length = int(rows[k].second.size());
        }
        *out_kv = arr;
        *out_count = int(rows.size());
        return 0;
    }
    int64_t version;
    fdb_tpu_error_t err = tr->grv(&version);
    if (err) return err;
    auto p = tr->picture();
    if (!p) return 1100;

    /* Overlay writes/atomics remove at most one base row each, so the
     * base fetch stays bounded at limit + overlay count in the
     * requested direction; only a clear intersecting the range can
     * delete unboundedly many base rows and forces the full fetch
     * (client/transaction.py get_range; ref: RYWIterator) */
    bool clear_in_range = false;
    for (const auto& cl : tr->clears)
        if (cl.first < end && cl.second > begin) clear_in_range = true;
    int64_t n_writes = 0;
    for (auto it = tr->writes.lower_bound(begin);
         it != tr->writes.end() && it->first < end; ++it)
        n_writes++;
    int64_t n_ops = 0;
    for (const auto& kv : tr->ops)
        if (begin <= kv.first && kv.first < end) n_ops++;
    int fetch_limit = clear_in_range
                          ? (1 << 20)
                          : int(std::min<int64_t>(limit + n_writes + n_ops,
                                                  1 << 20));
    bool fetch_rev = clear_in_range ? false : (reverse != 0);

    std::vector<std::pair<std::string, std::string>> base;
    std::vector<const Shard*> overlapping;
    for (const auto& s : p->shards) {
        bool before_end = !s.has_end || begin < s.end;
        if (before_end && s.begin < end) overlapping.push_back(&s);
    }
    if (fetch_rev) std::reverse(overlapping.begin(), overlapping.end());
    for (const Shard* s : overlapping) {
        std::string b = std::max(begin, s->begin);
        std::string e = s->has_end ? std::min(end, s->end) : end;
        WVal reply;
        err = tr->storage_rpc(
            *s, &Replica::ranges,
            WVal::nt("StorageGetRangeRequest",
                     {WVal::bytes(b), WVal::bytes(e), WVal::integer(version),
                      WVal::integer(fetch_limit - int64_t(base.size())),
                      WVal::boolean(fetch_rev)}),
            &reply);
        if (err) return err;
        if (reply.t != WVal::LIST) return 4000;
        for (const auto& kv : reply.items) {
            if (kv.t != WVal::TUPLE || kv.items.size() != 2) return 4000;
            base.emplace_back(kv.items[0].s, kv.items[1].s);
        }
        if (int64_t(base.size()) >= fetch_limit) break;
    }

    std::map<std::string, std::string> merged(base.begin(), base.end());
    for (const auto& cl : tr->clears) {
        auto it = merged.lower_bound(cl.first);
        while (it != merged.end() && it->first < cl.second)
            it = merged.erase(it);
    }
    for (auto it = tr->writes.lower_bound(begin);
         it != tr->writes.end() && it->first < end; ++it) {
        if (it->second.first)
            merged[it->first] = it->second.second;
        else
            merged.erase(it->first);
    }
    for (const auto& kv : tr->ops) {
        const std::string& k = kv.first;
        if (!(begin <= k && k < end)) continue;
        OptBytes val;
        auto mit = merged.find(k);
        if (mit != merged.end()) val = mit->second;
        if (!val) {
            bool written = tr->writes.count(k) != 0;
            bool cleared = false;
            for (const auto& cl : tr->clears)
                if (cl.first <= k && k < cl.second) cleared = true;
            if (!written && !cleared) {
                /* base value for a key the fetch may have missed */
                const Shard& shard = p->shards[tr->shard_index(p, k)];
                WVal reply;
                err = tr->storage_rpc(
                    shard, &Replica::gets,
                    WVal::nt("StorageGetRequest",
                             {WVal::bytes(k), WVal::integer(version)}),
                    &reply);
                if (err) return err;
                if (reply.t == WVal::BYTES) val = reply.s;
            }
        }
        for (const auto& op : kv.second)
            val = apply_atomic(op.first, val, op.second);
        if (val)
            merged[k] = *val;
        else
            merged.erase(k);
    }

    std::vector<std::pair<std::string, std::string>> rows(merged.begin(),
                                                          merged.end());
    if (reverse) std::reverse(rows.begin(), rows.end());
    if (int64_t(rows.size()) > limit) rows.resize(limit);

    if (!snapshot) {
        /* record only the observed portion when the limit truncates */
        if (int64_t(rows.size()) == limit && !rows.empty()) {
            if (reverse)
                tr->rc.emplace_back(rows.back().first, end);
            else
                tr->rc.emplace_back(begin, next_key(rows.back().first));
        } else {
            tr->rc.emplace_back(begin, end);
        }
    }

    auto* arr = (FDBTpuKeyValue*)std::calloc(
        rows.size() ? rows.size() : 1, sizeof(FDBTpuKeyValue));
    for (size_t k = 0; k < rows.size(); k++) {
        arr[k].key = dup_bytes(rows[k].first);
        arr[k].key_length = int(rows[k].first.size());
        arr[k].value = dup_bytes(rows[k].second);
        arr[k].value_length = int(rows[k].second.size());
    }
    *out_kv = arr;
    *out_count = int(rows.size());
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_set(FDBTpuTransaction* tr,
                                        const uint8_t* key, int key_length,
                                        const uint8_t* value,
                                        int value_length) {
    std::string k((const char*)key, key_length);
    std::string v((const char*)value, value_length);
    fdb_tpu_error_t err = tr->check_writable(k);
    if (err) return err;
    err = tr->check_sizes(k, v);
    if (err) return err;
    tr->record_write(k, v);
    tr->ops.erase(k); /* a set supersedes pending atomics */
    tr->mutations.push_back({0 /* SET_VALUE */, k, v});
    tr->wc.emplace_back(k, next_key(k));
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_clear(FDBTpuTransaction* tr,
                                          const uint8_t* key,
                                          int key_length) {
    std::string k((const char*)key, key_length);
    std::string e = next_key(k);
    return fdb_tpu_transaction_clear_range(tr, key, key_length,
                                           (const uint8_t*)e.data(),
                                           int(e.size()));
}

fdb_tpu_error_t fdb_tpu_transaction_clear_range(FDBTpuTransaction* tr,
                                                const uint8_t* begin_p,
                                                int begin_length,
                                                const uint8_t* end_p,
                                                int end_length) {
    std::string b((const char*)begin_p, begin_length);
    std::string e((const char*)end_p, end_length);
    if (b >= e) return 0;
    fdb_tpu_error_t err = tr->check_writable(b, &e);
    if (err) return err;
    err = tr->check_sizes(b, "");
    if (err) return err;
    err = tr->check_sizes(e, "", 1); /* keyAfter(max-size key) is legal */
    if (err) return err;
    tr->clears.emplace_back(b, e);
    for (auto it = tr->writes.lower_bound(b);
         it != tr->writes.end() && it->first < e; ++it)
        it->second = {false, std::string()};
    for (auto it = tr->ops.lower_bound(b);
         it != tr->ops.end() && it->first < e;)
        it = tr->ops.erase(it);
    tr->mutations.push_back({1 /* CLEAR_RANGE */, b, e});
    tr->wc.emplace_back(b, e);
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_atomic_op(FDBTpuTransaction* tr,
                                              const uint8_t* key,
                                              int key_length,
                                              const uint8_t* param,
                                              int param_length,
                                              int operation_type) {
    std::string k((const char*)key, key_length);
    std::string pm((const char*)param, param_length);
    fdb_tpu_error_t err = tr->check_writable(k);
    if (err) return err;
    err = tr->check_sizes(k, pm);
    if (err) return err;
    if (operation_type == FDB_TPU_OP_SET_VERSIONSTAMPED_KEY ||
        operation_type == FDB_TPU_OP_SET_VERSIONSTAMPED_VALUE) {
        /* transformed at the proxy; operand's trailing 4 bytes are the
         * placeholder offset (client/transaction.py atomic_op) */
        tr->mutations.push_back({operation_type, k, pm});
        std::string wkey =
            operation_type == FDB_TPU_OP_SET_VERSIONSTAMPED_KEY && k.size() >= 4
                ? k.substr(0, k.size() - 4)
                : k;
        tr->wc.emplace_back(wkey, next_key(wkey));
        return 0;
    }
    if (!is_atomic_op(operation_type)) return 2000;
    OptBytes cur;
    bool found = tr->overlay_get(k, &cur);
    if (found && tr->ops.find(k) == tr->ops.end()) {
        OptBytes result = apply_atomic(operation_type, cur, pm);
        if (!result) {
            tr->record_write(k, std::nullopt);
            tr->mutations.push_back({1 /* CLEAR_RANGE */, k, next_key(k)});
        } else {
            tr->record_write(k, result);
            tr->mutations.push_back({0 /* SET_VALUE */, k, *result});
        }
    } else {
        tr->ops[k].emplace_back(operation_type, pm);
        tr->mutations.push_back({operation_type, k, pm});
    }
    tr->wc.emplace_back(k, next_key(k));
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_add_conflict_range(
    FDBTpuTransaction* tr, const uint8_t* begin_p, int begin_length,
    const uint8_t* end_p, int end_length, int write) {
    std::string b((const char*)begin_p, begin_length);
    std::string e((const char*)end_p, end_length);
    if (b >= e) return 2005;
    (write ? tr->wc : tr->rc).emplace_back(b, e);
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_commit(FDBTpuTransaction* tr,
                                           int64_t* out_committed_version) {
    if (tr->mutations.empty()) {
        /* read-only: succeeds at the read version without a round trip */
        tr->committed_version = tr->read_version < 0 ? 0 : tr->read_version;
        *out_committed_version = tr->committed_version;
        return 0;
    }
    int64_t snapshot;
    fdb_tpu_error_t err = tr->grv(&snapshot);
    if (err) return err;
    auto p = tr->picture();
    if (!p) return 1100;

    auto ranges = [](const std::vector<std::pair<std::string, std::string>>&
                         rs) {
        std::vector<WVal> out;
        out.reserve(rs.size());
        for (const auto& r : rs)
            out.push_back(WVal::tuple(
                {WVal::bytes(r.first), WVal::bytes(r.second)}));
        return WVal::tuple(std::move(out));
    };
    std::vector<WVal> muts;
    muts.reserve(tr->mutations.size());
    for (const auto& m : tr->mutations)
        muts.push_back(WVal::nt(
            "MutationRef", {WVal::integer(m.type), WVal::bytes(m.p1),
                            WVal::bytes(m.p2)}));
    WVal req = WVal::nt("CommitRequest",
                        {WVal::integer(snapshot), ranges(tr->rc),
                         ranges(tr->wc), WVal::tuple(std::move(muts))});
    const ProxyEndpoints& proxy =
        p->proxies[tr->db->rand_below(uint32_t(p->proxies.size()))];
    WVal reply;
    err = tr->db->conn.request(proxy.commits, req, &reply);
    if (err) return err;
    if (reply.t != WVal::NT || reply.items.size() < 2 ||
        reply.items[0].t != WVal::INT)
        return 4000;
    tr->committed_version = reply.items[0].i;
    tr->committed_batch_index = reply.items[1].i;
    *out_committed_version = tr->committed_version;
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_get_versionstamp(FDBTpuTransaction* tr,
                                                     uint8_t** out_stamp,
                                                     int* out_length) {
    if (tr->committed_version < 0) return 2000;
    /* server/proxy.py make_versionstamp: 8B BE version + 2B BE batch */
    std::string stamp(10, '\0');
    uint64_t v = uint64_t(tr->committed_version);
    for (int k = 0; k < 8; k++) stamp[k] = char(v >> (8 * (7 - k)));
    uint64_t bi = uint64_t(
        tr->committed_batch_index < 0 ? 0 : tr->committed_batch_index);
    stamp[8] = char(bi >> 8);
    stamp[9] = char(bi & 0xFF);
    *out_stamp = dup_bytes(stamp);
    *out_length = 10;
    return 0;
}

fdb_tpu_error_t fdb_tpu_transaction_on_error(FDBTpuTransaction* tr,
                                             fdb_tpu_error_t code) {
    if (!is_retryable(code)) return code;
    if (needs_refresh(code)) {
        /* long-poll past the picture this attempt used (Database.
         * refresh_past); a refresh failure still allows the retry */
        tr->db->describe(tr->used_seq < 0 ? 0 : tr->used_seq);
    }
    std::this_thread::sleep_for(
        std::chrono::milliseconds(1 + int(tr->db->rand_below(10))));
    tr->reset();
    return 0;
}

fdb_tpu_error_t fdb_tpu_database_watch(FDBTpuDatabase* db,
                                       const uint8_t* key, int key_length,
                                       int timeout_ms) {
    /* a watch rides the same resilience rules as every read: rotate
     * replicas on connection-class failures and refresh a stale
     * picture once before giving up (a recovery swaps the tokens) */
    std::string k((const char*)key, key_length);
    /* system keys are unwatchable through this option-less ABI
     * (client/transaction.py watch gate) */
    if (in_system(k)) return 2004;
    fdb_tpu_error_t last = 1100;
    for (int attempt = 0; attempt < 2; attempt++) {
        auto p = db->picture();
        if (!p) return 1100;
        const ProxyEndpoints& proxy =
            p->proxies[db->rand_below(uint32_t(p->proxies.size()))];
        WVal grv;
        fdb_tpu_error_t err = db->conn.request(
            proxy.grvs,
            WVal::nt("GetReadVersionRequest", {WVal::integer(1)}), &grv);
        if (err == 0) {
            if (grv.t != WVal::NT || grv.items.empty()) return 4000;
            int64_t version = grv.items[0].i;
            const Shard& shard = p->shards[shard_index_for(p, k)];
            size_t n = shard.replicas.size();
            size_t start = db->rand_below(uint32_t(n));
            for (size_t j = 0; j < n; j++) {
                const Replica& rep = shard.replicas[(start + j) % n];
                if (rep.watches == 0) return 2000; /* seam lacks watches */
                WVal reply;
                err = db->conn.request(
                    rep.watches,
                    WVal::nt("StorageWatchRequest",
                             {WVal::bytes(k), WVal::integer(version)}),
                    &reply, timeout_ms);
                if (err == 0) return 0;
                if (err != 1100) return err; /* incl. the caller's 1004 */
                last = err;
            }
        } else if (err != 1100 && err != 1004) {
            return err;
        } else {
            last = err;
        }
        db->describe(p->seq);   /* stale picture: refresh and retry */
    }
    return last;
}

void fdb_tpu_free(void* ptr) { std::free(ptr); }

void fdb_tpu_free_keyvalues(FDBTpuKeyValue* kv, int count) {
    if (!kv) return;
    for (int k = 0; k < count; k++) {
        std::free(kv[k].key);
        std::free(kv[k].value);
    }
    std::free(kv);
}

} /* extern "C" */
